"""pio-lint engine: AST walking, suppressions, baseline, reporting.

The reference system leaned on Scala's type system and Spark's typed RDD
contracts to reject mis-wired DASE components at compile time. The
Python/JAX rebuild has no compiler to do that job, and its failure
modes are worse: tracer misuse, sharding hazards and host syncs surface
only when a kernel is COMPILED for real hardware — often long after the
code merged (ROUND5.md documents the interpret-passes/Mosaic-fails
class). This package is the repo-specific replacement guardrail: pure
AST analysis (nothing is imported or executed), a small rule registry
(:mod:`.rules`), inline ``# pio-lint: disable=RULE`` suppressions and a
checked-in baseline for deliberate exceptions.

Run it as ``python -m incubator_predictionio_tpu.analysis`` (see
``docs/lint.md``); CI runs it against the baseline via
``tests/test_lint.py`` on the tier-1 path.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: severity levels, in increasing order of concern
SEVERITIES = ("warning", "error")

_SUPPRESS_RE = re.compile(r"#\s*pio-lint:\s*disable=([\w,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*pio-lint:\s*disable-file=([\w,\- ]+)")
#: concurrency-contract annotations (docs/lint.md "Concurrency
#: contract"): ``# pio-lint: guarded-by(<lock>)`` declares the lock
#: attribute that must be held for every write of the annotated
#: attribute; ``# pio-lint: publish-only`` declares a single-writer
#: immutable-publish attribute (the recorder ring idiom). Both are
#: VERIFIED by analysis/concur.py, not trusted.
_ANNOTATION_RE = re.compile(
    r"#\s*pio-lint:\s*(publish-only|guarded-by\(\s*[\w.]+\s*\))")

#: modules allowed to read os.environ at import time by name
CONFIG_MODULE_RE = re.compile(r"(config|settings|conftest)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str      # member of SEVERITIES
    path: str          # repo-relative posix path
    line: int          # 1-based
    message: str
    snippet: str       # stripped source line — the baseline fingerprint

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule}] {self.message}")

    def baseline_entry(self) -> dict:
        return {"rule": self.rule, "path": self.path,
                "snippet": self.snippet,
                "justification": "TODO: justify or fix"}


class Module:
    """One parsed source file plus the derived facts rules share."""

    def __init__(self, path: Path, relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.aliases = _import_aliases(self.tree)
        self.traced_roots = _traced_roots(self.tree, self.aliases)
        (self.line_disables, self.file_disables,
         self.line_annotations) = _suppressions(source)

    # -- shared helpers -----------------------------------------------------

    def resolved(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with the first segment
        resolved through this module's import aliases (``jnp.where`` →
        ``jax.numpy.where``)."""
        return _resolve_dotted(node, self.aliases)

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: "object", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return self.finding_at(rule, line, message)

    def finding_at(self, rule: "object", line: int, message: str) -> Finding:
        """Finding anchored at a line number — package rules report from
        index records, not live AST nodes."""
        return Finding(rule=rule.name, severity=rule.severity,
                       path=self.relpath, line=line, message=message,
                       snippet=self.snippet_at(line))

    def annotations_at(self, line: int) -> Set[str]:
        """Concurrency-contract annotations attached to ``line``: a
        trailing ``# pio-lint: ...`` comment on the line itself, or one
        on its own comment line directly above (same attachment rule as
        suppressions)."""
        out = set(self.line_annotations.get(line, ()))
        if _is_comment_line(self.lines, line - 1):
            out |= self.line_annotations.get(line - 1, set())
        return out

    def is_suppressed(self, f: Finding) -> bool:
        for rules in (self.file_disables,
                      self.line_disables.get(f.line, set()),
                      # a directive on its own comment line suppresses
                      # the statement directly below it
                      self.line_disables.get(f.line - 1, set())
                      if _is_comment_line(self.lines, f.line - 1) else set()):
            if "all" in rules or f.rule in rules:
                return True
        return False


def _is_comment_line(lines: List[str], line: int) -> bool:
    return 1 <= line <= len(lines) and lines[line - 1].lstrip().startswith("#")


def _suppressions(
    source: str,
) -> Tuple[Dict[int, Set[str]], Set[str], Dict[int, Set[str]]]:
    """Directive parsing over COMMENT tokens only — a docstring that
    *documents* the ``# pio-lint: disable=...`` syntax must not disable
    anything (the module already parsed, so tokenize cannot fail on
    syntax; be permissive about anything else). Returns
    ``(line disables, file disables, line annotations)`` — annotations
    are the concurrency-contract directives (publish-only /
    guarded-by(<lock>)), normalized with whitespace stripped."""
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    annotations: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return per_line, whole_file, annotations
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_FILE_RE.search(tok.string)
        if m:
            whole_file |= _split_rules(m.group(1))
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m:
            per_line.setdefault(tok.start[0], set()).update(
                _split_rules(m.group(1)))
            continue
        for m in _ANNOTATION_RE.finditer(tok.string):
            annotations.setdefault(tok.start[0], set()).add(
                re.sub(r"\s+", "", m.group(1)))
    return per_line, whole_file, annotations


def _split_rules(raw: str) -> Set[str]:
    return {r.strip() for r in raw.split(",") if r.strip()}


def _resolve_dotted(node: ast.AST,
                    aliases: Dict[str, str]) -> Optional[str]:
    """THE single copy of alias-aware dotted-name resolution — rules
    (Module.resolved) and trace detection must see identical names."""
    dname = _dotted(node)
    if dname is None:
        return None
    head, _, rest = dname.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name → fully dotted origin, for imports anywhere in the file
    (the repo imports lazily inside functions too)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.partition(".")[0]] = (
                    a.name if a.asname else a.name.partition(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


_TRACE_TAILS = ("jit", "pjit", "shard_map")


def _is_trace_wrapper(resolved_name: Optional[str]) -> bool:
    return bool(resolved_name) and (
        resolved_name.rsplit(".", 1)[-1] in _TRACE_TAILS)


def _traced_roots(
    tree: ast.Module, aliases: Dict[str, str]
) -> List[Tuple[ast.AST, Set[str]]]:
    """Functions whose body runs under a JAX trace: jit/pjit/shard_map
    decorated (directly or via functools.partial), wrapped at a call site
    (``fn = jax.jit(f)`` / ``shard_map(f, ...)``), or passed to
    ``pl.pallas_call`` as the kernel body. Paired with the function's
    static argnames (trace-time Python values, exempt from tracer rules).
    """

    def resolved(node: ast.AST) -> Optional[str]:
        return _resolve_dotted(node, aliases)

    def static_names(call: ast.Call) -> Set[str]:
        names: Set[str] = set()
        for kw in call.keywords:
            # donate_argnames are donated ARRAYS — still tracers
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str):
                        names.add(sub.value)
        return names

    # simple single-target assignments, so a kernel body bound through
    # an intermediate (`body = functools.partial(_kernel, ...)` then
    # `pl.pallas_call(body, ...)`) still resolves to `_kernel`
    assigned: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            assigned[node.targets[0].id] = node.value

    def unwrap(t: ast.AST, bound: Set[str]) -> Optional[str]:
        """Follow partial() wrappers and name assignments to the
        underlying function name, collecting partial-bound keyword
        names (plain Python values — trace-time constants)."""
        visited: Set[str] = set()
        for _hop in range(8):
            if isinstance(t, ast.Call):  # functools.partial(body, ...)
                bound |= {kw.arg for kw in t.keywords if kw.arg}
                if not t.args:
                    return None
                t = t.args[0]
            elif (isinstance(t, ast.Name) and t.id in assigned
                    and t.id not in visited):  # guard x = x cycles
                visited.add(t.id)
                t = assigned[t.id]
            else:
                break
        name = _dotted(t)
        return name.rsplit(".", 1)[-1] if name else None

    # names traced by call-site wrapping, e.g. jax.jit(step) or
    # pl.pallas_call(functools.partial(_kernel, ...), ...) — mapped to
    # the statically-bound parameter names
    wrapped: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        rname = resolved(node.func) or ""
        targets: List[ast.AST] = []
        if _is_trace_wrapper(rname) and node.args:
            targets = [node.args[0]]
        elif rname.rsplit(".", 1)[-1] == "pallas_call" and node.args:
            targets = [node.args[0]]
        for t in targets:
            bound: Set[str] = static_names(node)
            short = unwrap(t, bound)
            if short:
                wrapped.setdefault(short, set()).update(bound)

    roots: List[Tuple[ast.AST, Set[str]]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        statics: Set[str] = set(wrapped.get(node.name, ()))
        traced = node.name in wrapped
        for dec in node.decorator_list:
            if _is_trace_wrapper(resolved(dec)):
                traced = True
            elif isinstance(dec, ast.Call):
                rname = resolved(dec.func) or ""
                if _is_trace_wrapper(rname):
                    traced = True
                    statics |= static_names(dec)
                elif rname.rsplit(".", 1)[-1] == "partial" and dec.args:
                    if _is_trace_wrapper(resolved(dec.args[0])):
                        traced = True
                        statics |= static_names(dec)
        if traced:
            roots.append((node, statics))
    return roots


# ---------------------------------------------------------------------------
# running rules over files
# ---------------------------------------------------------------------------

EXCLUDED_DIR_NAMES = {"__pycache__", "_build", ".git"}


def package_root() -> Path:
    """The installed ``incubator_predictionio_tpu`` package directory —
    the default scan target."""
    return Path(__file__).resolve().parents[1]


def repo_root() -> Path:
    """Directory findings/baseline paths are relative to (the checkout
    root when running from a working tree)."""
    return package_root().parent


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def iter_py_files(paths: Sequence[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not EXCLUDED_DIR_NAMES & set(f.parts):
                    yield f


def _relpath(path: Path) -> str:
    try:
        return path.resolve().relative_to(repo_root()).as_posix()
    except ValueError:
        return path.as_posix()


class Package:
    """Whole-program view handed to rule API v2 (``check_package``):
    every parsed :class:`Module` of the run, plus a shared scratch
    cache so several package rules can split one expensive index
    (analysis/concur.py builds its class/thread index once here)."""

    def __init__(self, modules: Sequence[Module]) -> None:
        self.modules = list(modules)
        self.by_relpath: Dict[str, Module] = {
            m.relpath: m for m in self.modules}
        #: shared per-run scratch space for package-rule indexes
        self.cache: Dict[str, object] = {}


def lint_paths(
    paths: Sequence[Path],
    rules: Sequence[object],
    on_parse_error: Optional[List[str]] = None,
    timings: Optional[Dict[str, float]] = None,
    suppressed_out: Optional[List[Finding]] = None,
) -> List[Finding]:
    """Run every rule over every file; inline suppressions applied,
    baseline NOT applied (see :func:`apply_baseline`).

    Two-phase protocol: per-file rules (``check(mod)``) run module by
    module exactly as before; whole-program rules (``whole_program =
    True`` + ``check_package(package)``) run once afterwards over the
    full :class:`Package`. ``timings`` (if given) is filled with
    per-rule wall-clock seconds — the ``--timings`` report and the
    tier-1 lint-budget test read it. ``suppressed_out`` (if given)
    collects findings silenced by inline directives instead of
    dropping them (the ``--format json`` report marks them)."""
    import time as _time

    findings: List[Finding] = []
    modules: List[Module] = []
    per_file = [r for r in rules
                if not getattr(r, "whole_program", False)]
    package_rules = [r for r in rules
                     if getattr(r, "whole_program", False)]

    def _book(rule: object, t0: float) -> None:
        if timings is not None:
            timings[rule.name] = (timings.get(rule.name, 0.0)
                                  + _time.perf_counter() - t0)

    def _emit(mod: Module, finding: Finding) -> None:
        if mod.is_suppressed(finding):
            if suppressed_out is not None:
                suppressed_out.append(finding)
        else:
            findings.append(finding)

    for f in iter_py_files(paths):
        try:
            mod = Module(f, _relpath(f), f.read_text(encoding="utf-8"))
        except (SyntaxError, UnicodeDecodeError) as exc:
            if on_parse_error is not None:
                on_parse_error.append(f"{f}: {exc}")
            continue
        modules.append(mod)
        for rule in per_file:
            t0 = _time.perf_counter()
            for finding in rule.check(mod):
                _emit(mod, finding)
            _book(rule, t0)
    if package_rules and modules:
        package = Package(modules)
        for rule in package_rules:
            t0 = _time.perf_counter()
            for finding in rule.check_package(package):
                mod = package.by_relpath.get(finding.path)
                if mod is None:
                    findings.append(finding)
                else:
                    _emit(mod, finding)
            _book(rule, t0)
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    if suppressed_out is not None:
        suppressed_out.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> List[dict]:
    data = json.loads(path.read_text(encoding="utf-8"))
    entries = data.get("entries", [])
    for e in entries:
        for key in ("rule", "path", "snippet"):
            if key not in e:
                raise ValueError(
                    f"baseline entry missing {key!r}: {e}")
    return entries


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """→ (findings not covered by the baseline, stale unused entries).

    Matching is by (rule, path, stripped source line) — stable across
    pure line-number drift. Each entry absorbs ONE finding; duplicated
    violations need duplicated entries.
    """
    pool: Dict[Tuple[str, str, str], List[dict]] = {}
    for e in entries:
        pool.setdefault((e["rule"], e["path"], e["snippet"]), []).append(e)
    unmatched: List[Finding] = []
    for f in findings:
        bucket = pool.get((f.rule, f.path, f.snippet))
        if bucket:
            bucket.pop()
        else:
            unmatched.append(f)
    stale = [e for bucket in pool.values() for e in bucket]
    return unmatched, stale


def write_baseline(path: Path, findings: Sequence[Finding],
                   keep_entries: Sequence[dict] = ()) -> None:
    """Regenerate the baseline from ``findings``, preserving the
    hand-written justification of every entry that still matches —
    only genuinely new entries get the TODO placeholder.
    ``keep_entries`` (entries a filtered run could not even see, e.g.
    under --select or an explicit path) are carried over verbatim so a
    partial regeneration never wipes curated out-of-scope entries."""
    kept: Dict[Tuple[str, str, str], List[str]] = {}
    if path.exists():
        try:
            for e in load_baseline(path):
                kept.setdefault(
                    (e["rule"], e["path"], e["snippet"]), []
                ).append(e.get("justification", ""))
        except (ValueError, json.JSONDecodeError):
            pass  # malformed old baseline: regenerate from scratch
    entries = list(keep_entries)
    for f in findings:
        entry = f.baseline_entry()
        old = kept.get((f.rule, f.path, f.snippet))
        if old:
            entry["justification"] = old.pop(0)
        entries.append(entry)
    entries.sort(key=lambda e: (e["path"], e["rule"], e["snippet"]))
    save_baseline_entries(path, entries)


def save_baseline_entries(path: Path, entries: Sequence[dict]) -> None:
    """Write ``entries`` as the baseline file verbatim (sorted) — the
    --prune-baseline path, which must drop stale entries WITHOUT
    touching the surviving hand-written justifications."""
    entries = sorted(entries,
                     key=lambda e: (e["path"], e["rule"], e["snippet"]))
    payload = {
        "comment": ("pio-lint baseline: deliberate exceptions, one "
                    "justification each. Regenerate with --write-baseline "
                    "(see docs/lint.md) and re-justify every entry."),
        "entries": list(entries),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")
