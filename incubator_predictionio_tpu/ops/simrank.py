"""SimRank on device — the friend-recommendation graph similarity.

The reference's parallel friend-recommendation template computes SimRank
by delta propagation over RDD pairs (examples/experimental/
scala-parallel-friend-recommendation/DeltaSimRankRDD.scala: per-pair
cartesian joins of in-neighbor lists, reduceByKey — shuffle-bound, which
is why it needs the "delta" sparsification). On a TPU the SimRank
recurrence IS two dense matmuls:

    S ← C · Wᵀ S W,   diag(S) ← 1

with ``W`` the column-normalized in-neighbor adjacency — so the whole
iteration runs as one fused ``lax.fori_loop`` of MXU work, exact, with
no shuffle machinery. Template-scale graphs (≤ a few thousand nodes)
hold S in HBM outright.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

#: dense [N, N] similarity ceiling (same rationale as ops/dimsum.py)
MAX_NODES = 16384


@functools.partial(jax.jit, static_argnames=("iterations",))
def _simrank_iterate(w_norm: jax.Array, decay: float,
                     iterations: int) -> jax.Array:
    n = w_norm.shape[0]
    eye = jnp.eye(n, dtype=jnp.float32)

    def body(_, s):
        s = decay * (w_norm.T @ s @ w_norm)
        # fix-point constraint s(a, a) = 1
        return s * (1.0 - eye) + eye

    return jax.lax.fori_loop(0, iterations, body, eye)


def simrank(
    src: np.ndarray,
    dst: np.ndarray,
    n_nodes: int,
    decay: float = 0.8,
    iterations: int = 7,
) -> np.ndarray:
    """SimRank similarity matrix [N, N] for a directed edge list.

    ``decay`` is the reference's 0.8 (DeltaSimRankRDD.scala:31);
    ``iterations`` the usual convergence budget (SimRank converges
    geometrically in ``decay^k``)."""
    if n_nodes > MAX_NODES:
        raise ValueError(
            f"dense SimRank targets graphs ≤ {MAX_NODES} nodes "
            f"(got {n_nodes})")
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    adj = np.zeros((n_nodes, n_nodes), np.float32)
    adj[src, dst] = 1.0
    in_deg = adj.sum(axis=0)
    w_norm = adj / np.maximum(in_deg, 1.0)[None, :]
    return np.asarray(_simrank_iterate(
        jnp.asarray(w_norm), float(decay), int(iterations)))
