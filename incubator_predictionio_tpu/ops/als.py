"""Alternating Least Squares on TPU — the MLlib-ALS replacement.

The reference's recommendation templates call Spark MLlib's shuffle-based ALS
(examples/scala-parallel-recommendation/custom-query/src/main/scala/
ALSAlgorithm.scala:25-31). This is the TPU-first redesign (ALX-style,
PAPERS.md): factors live in dense device arrays; each half-sweep is

  1. gather the *other* side's factors for every observed interaction
     (degree-bucketed padded rows, see ops.sparse),
  2. one big batched einsum builds all K×K normal-equation Grams at once
     (bf16 inputs, f32 accumulation — MXU-shaped work),
  3. a batched Cholesky-backed solve produces the new factors,
  4. a masked scatter writes them back.

Sharding: the padded-row batches shard across the whole mesh on the batch
axis; factor tables are replicated (they are MBs even at ML-20M scale:
270k×128 ≈ 138 MB total) so gathers are local and XLA inserts exactly one
all-gather per half-sweep when the scatter output needs replication again.
Model-parallel sharded factor tables (the full ALX layout for >100M-row
embedding tables) ride the same bucket structure and are the designated
extension on the ``mp`` mesh axis.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.ops.sparse import (
    PaddedRows,
    build_both_sides,
    build_padded_rows,
    split_heavy,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ALSState:
    """Factor matrices (a pytree — checkpoints via workflow.checkpoint).

    ``placement`` (STATIC pytree metadata, never a leaf) carries the
    mesh-sharded layout when the tables are distributed — a
    :class:`~incubator_predictionio_tpu.parallel.placement.FactorPlacement`
    recording the mesh, per-table shardings and the padded sizes. None
    (the default) is the single-chip layout; every existing constructor
    site is unchanged. Being static, a placement change is a different
    jit cache key: resharded programs recompile, same-placement
    steady-state retrains never do."""

    user_factors: Any  # [n_users, rank] f32 (padded when placed)
    item_factors: Any  # [n_items, rank] f32 (padded when placed)
    placement: Optional[Any] = dataclasses.field(
        default=None, metadata=dict(static=True))


def als_init(
    key: jax.Array, n_users: int, n_items: int, rank: int, scale: float = 0.1
) -> ALSState:
    ku, ki = jax.random.split(key)
    return ALSState(
        user_factors=scale * jax.random.normal(ku, (n_users, rank), jnp.float32),
        item_factors=scale * jax.random.normal(ki, (n_items, rank), jnp.float32),
    )


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _grow_factors(prev: jax.Array, key: jax.Array, n_rows: int,
                  scale: float) -> jax.Array:
    """Prefix-copy a factor table into a larger index space → [n_rows, K].

    The traincache tail fold interns ids in stable first-seen order, so a
    previous model's rows map onto the new index space as an EXACT prefix
    — no gather, no remap (and therefore none of the negative-padding
    wraparound `_gather_x0` clamps against): the old table is copied
    row-for-row device-side and only the NEW ids get ``als_init``-scale
    random rows appended. Not donated: checkpointed prev factors arrive
    as host numpy (never donatable — the annotation would only warn)."""
    pu, rank = prev.shape
    if n_rows == pu:
        return prev.astype(jnp.float32)
    fresh = scale * jax.random.normal(key, (n_rows - pu, rank), jnp.float32)
    return jnp.concatenate([prev.astype(jnp.float32), fresh])


def continue_state(
    prev_user: Any,            # [U0, K] prior user factors (host or device)
    prev_item: Any,            # [I0, K] prior item factors
    n_users: int,
    n_items: int,
    seed: int = 0,
    scale: float = 0.1,
) -> Optional[ALSState]:
    """Seed a retrain from a previous model's factors (the cross-retrain
    continuation of the O(delta) steady-state path).

    Returns None when the prior tables cannot be a prefix of the new
    index space (more rows than the new table — ids were deleted or the
    index space was rebuilt, so row i no longer names the same entity);
    the caller then falls back to ``als_init``. The caller is
    responsible for verifying the id-space prefix property itself (the
    engines check the BiMap prefix; see models/*/engine.py)."""
    prev_user = jnp.asarray(prev_user)
    prev_item = jnp.asarray(prev_item)
    if (prev_user.ndim != 2 or prev_item.ndim != 2
            or prev_user.shape[1] != prev_item.shape[1]
            or prev_user.shape[0] > n_users
            or prev_item.shape[0] > n_items):
        return None
    ku, ki = jax.random.split(jax.random.key(seed))
    return ALSState(
        user_factors=_grow_factors(prev_user, ku, n_users, scale),
        item_factors=_grow_factors(prev_item, ki, n_items, scale),
    )


def _gram_rhs_nnz(
    other_factors: jax.Array,  # [M, K]
    cols: jax.Array,           # [..., D] int32
    vals: jax.Array,           # [..., D] f32
    mask: jax.Array,           # [..., D] f32 in {0, 1}
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    alpha: float,
    gram_dtype: Any = jnp.float32,
):
    """Normal-equation pieces for a batch of padded rows → (gram, rhs, nnz).

    THE single copy of the numerically delicate assembly — explicit mode
    relies on mask² == mask to apply the mask once per side; implicit mode
    builds Yᵤᵗ(Cᵤ−I)Yᵤ with c = 1 + α·r (Hu-Koren-Volinsky). Everything
    accumulates in f32 at the given matmul precision (see the note on
    :func:`_solve_bucket`). Used by the bucket solvers AND the split-row
    partial-Gram path so their numerics cannot drift apart.

    ``gram_dtype=bfloat16`` casts the Gram batch in the einsum epilogue
    (accumulation stays f32): the [rows, K, K] Gram is the largest tensor
    of a sweep (~9 GB f32 on the ML-20M user side), so emitting it bf16
    halves both the write and every CG re-read without a separate
    materialized cast. Only the bf16 bucket path opts in — the split-row
    path must segment-sum partial Grams in f32 first."""
    # The gather is the dominant HBM stream at scale ([..., D, K] ≈
    # nnz·K elements per half-sweep): casting the SOURCE table to
    # compute_dtype first halves that traffic in bf16 mode AND hands the
    # MXU single-pass bf16 operands (vs the 6-pass f32 HIGHEST schedule).
    # Implicit mode NEVER casts — its bucket solver is hardcoded f32, and
    # the heavy (split-row) path must match it exactly (the "numerics
    # cannot drift apart" contract above).
    src = (other_factors
           if implicit or other_factors.dtype == compute_dtype
           else other_factors.astype(compute_dtype))
    gathered = src[cols]                                # [..., D, K]
    masked = gathered * mask[..., None].astype(gathered.dtype)
    if implicit:
        conf_minus1 = alpha * vals * mask               # (c-1), 0 on padding
        gram = jnp.einsum(
            "...d,...dk,...dl->...kl", conf_minus1, masked, gathered,
            preferred_element_type=jnp.float32, precision=precision,
        )
        rhs = jnp.einsum(
            "...d,...dk->...k", (1.0 + conf_minus1) * mask, masked,
            preferred_element_type=jnp.float32, precision=precision,
        )
    else:
        gram = jnp.einsum(
            "...dk,...dl->...kl", masked, gathered,
            preferred_element_type=jnp.float32, precision=precision,
        )
        rhs = jnp.einsum(
            "...d,...dk->...k", (vals * mask).astype(gathered.dtype), masked,
            preferred_element_type=jnp.float32, precision=precision,
        )
    return gram.astype(gram_dtype), rhs, mask.sum(axis=-1)


#: batched SPD solver: "cg" (Jacobi-preconditioned conjugate gradient) or
#: "cholesky" (XLA's batched factorization). CG is the TPU default: XLA's
#: batched Cholesky serializes K dependent steps of thin vector work
#: (measured ~25 µs per 128×128 system on v5e — it would dominate the whole
#: training run at ML-20M scale), while CG is nothing but batched matvecs,
#: ~16× faster in-trace at ≤1e-5 relative error on λ·nnz-regularized grams
#: (the diagonal regularizer is exactly what makes Jacobi preconditioning
#: effective here).
#: 16 iterations reach ≤3e-6 relative solve error on λ·nnz-regularized
#: grams (measured; 32 and 16 produce bit-identical training RMSE at
#: ML-20M-shape workloads, and the solve cost is linear in the budget)
_SOLVER = os.environ.get("PIO_ALS_SOLVER", "cg")
_CG_ITERS = int(os.environ.get("PIO_ALS_CG_ITERS", "16"))
#: fused Pallas bucket solve (ops/pallas_kernels.als_solve_cg_pallas):
#: "auto" probes Mosaic once per process and uses the kernel for explicit
#: CG buckets; "on" forces it (tests use interpret mode); "off" pins the
#: XLA path. The kernel removes the (1+iters)·rows·K² Gram HBM stream —
#: the dominant bf16-sweep traffic at ML-20M shape — by keeping each
#: row's Gram and the whole CG solve in VMEM.
_ALS_KERNEL = os.environ.get("PIO_ALS_KERNEL", "auto")
#: minimum bucket width D for kernel routing when the kernel is enabled.
#: Small-D buckets are where the fused solve loses: the kernel pads every
#: row's gather to a full 128 lane tile ((dp−d)·K wasted read per row)
#: and solves each row's CG serially, while its Gram-stream saving —
#: (1+iters)·K² per row on the XLA path — is the same for every bucket,
#: so it is RELATIVELY thinnest exactly where the padding tax is highest
#: (measured on-chip at 2M nnz, D̄≈14: kernel 1.50 s vs XLA 1.15 s).
#: Bucket widths are static at trace time, so routing is free.
_KERNEL_MIN_D = int(os.environ.get("PIO_ALS_KERNEL_MIN_D", "64"))
#: warm-start every bucket CG from the previous sweep's factors. At a
#: fixed iteration budget this only improves the residual (the start
#: point is closer); its real payoff is a LOWER budget for the same
#: RMSE — each saved CG iteration saves a full [rows, K, K] Gram-batch
#: re-read, the dominant bf16-sweep HBM stream. Measured convergence
#: curves: see docs/performance.md (warm@N vs cold@N on the planted
#: bench workload — convergence is platform-independent).
_CG_WARMSTART = os.environ.get("PIO_ALS_CG_WARMSTART", "1") not in (
    "0", "off", "false")


def _kernel_rows_default() -> int:
    """Current rows-per-program default (PIO_ALS_KERNEL_ROWS, owned by
    pallas_kernels). Read at CALL time so sweeps/monkeypatches see it;
    the resolved value is threaded as a static jit arg — never read
    mid-trace."""
    from incubator_predictionio_tpu.ops import pallas_kernels

    return pallas_kernels._ALS_ROWS


def _fused_gram_mode() -> str:
    """`PIO_ALS_FUSED_GRAM` — the fused gather+Gram+CG kernel selector
    ("auto" probes per variant, "on" forces — tests use interpret mode —
    "off" pins the two-stage kernel / XLA assembly). Read per call,
    never frozen at import (the env-import lint contract)."""
    return os.environ.get("PIO_ALS_FUSED_GRAM", "auto")


def _cg_tol_env() -> float:
    """`PIO_ALS_CG_TOL` — device-side CG residual early-exit tolerance
    (relative preconditioned residual; 0 = fixed budget, the default:
    the budget is already tuned, and a data-dependent iteration count
    would blur the analytic FLOP attribution). Read per call."""
    try:
        return float(os.environ.get("PIO_ALS_CG_TOL", "0") or 0.0)
    except ValueError:
        return 0.0


def _kernel_enabled(implicit: bool, warm: bool = False) -> bool:
    """Resolve the bucket-kernel selector OUTSIDE any jit trace (the
    Mosaic probe compiles+runs a real kernel). Explicit CG routes
    through either kernel generation; the implicit path needs the
    batch-shared YᵗY term, which only the fused-gather kernel carries —
    implicit is therefore kernel-eligible exactly when the fused
    generation is. ``warm`` is the caller's resolved warm-start setting
    so the probe compiles the exact kernel variant (x0 operand or not)
    this run will dispatch."""
    if _SOLVER != "cg" or _ALS_KERNEL == "off":
        return False
    if implicit:
        return _fused_enabled(True, warm)
    if _ALS_KERNEL == "on":
        return True
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_kernel_available,
    )

    return als_kernel_available(warm=warm)


def _fused_enabled(implicit: bool, warm: bool) -> bool:
    """Resolve the fused-gather generation selector OUTSIDE any trace.
    Forced on ONLY by its own `PIO_ALS_FUSED_GRAM=on` (the
    interpret-mode test hook); otherwise the auto probe compiles the
    exact (warm, implicit) fused variant this run would dispatch.
    `PIO_ALS_KERNEL=on` deliberately does NOT waive the probe here: a
    deployment that forced the validated two-stage kernel must not be
    silently upgraded to the brand-new in-kernel-gather lowering
    without the per-variant probe contract (the PR 1 rule)."""
    mode = _fused_gram_mode()
    if mode in ("0", "off", "false") or _SOLVER != "cg" \
            or _ALS_KERNEL == "off":
        return False
    if mode == "on":
        return True
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_kernel_available,
    )

    return als_kernel_available(warm=warm, fused=True, implicit=implicit)


def _fused_sides(n_users: int, n_items: int, implicit: bool, warm: bool,
                 compute_dtype: Any, rank: int) -> Tuple[bool, bool]:
    """Per-half-sweep fused-gather routing → (user_sweep, item_sweep).

    The fused kernel pins the OTHER side's factor table in VMEM, so the
    decision is per gather source: the user half-sweep gathers from the
    item table (small — fits at ML-20M shape), the item half-sweep from
    the user table (usually does not). Resolved HERE, outside the trace,
    from static shapes + the VMEM budget (`PIO_ALS_FUSED_VMEM_MB`), and
    threaded as a static jit arg — a mid-trace read would bake a stale
    budget into the cache."""
    dt = jnp.float32 if implicit else compute_dtype
    return (_fused_one(True, implicit, warm, n_items, rank, dt),
            _fused_one(True, implicit, warm, n_users, rank, dt))


def _fused_one(use_kernel: bool, implicit: bool, warm: bool,
               table_rows: int, rank: int, dtype: Any) -> bool:
    """THE single-side fused-routing conjunction: kernel selected AND
    the fused generation enabled for this exact (implicit, warm)
    variant AND the gather table inside the VMEM budget. Every call
    site — the per-sweep tuple above, the one-shot `_update_side`
    entries, retrain's per-leg closure via `_fused_sides` — resolves
    through here so the rule cannot drift between files."""
    if not use_kernel or not _fused_enabled(implicit, warm):
        return False
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_fused_fits,
    )

    return als_fused_fits(table_rows, rank, dtype)
#: CG budget for the bf16 early sweeps of the mixed schedule. Each CG
#: iteration re-reads the whole [rows, K, K] Gram batch (~9 GB at
#: ML-20M scale on the user side) — the dominant HBM stream once gathers
#: run bf16 — and early sweeps are re-solved next sweep anyway, so a
#: loose solve costs nothing in final quality (the f32 polish runs the
#: full budget; guarded by the planted-recovery test). With warm start
#: the default drops 6 → 3: measured on the planted workload (10
#: sweeps, λ=0.03), warm@3 reaches the same fit RMSE as cold@6 (0.162
#: vs 0.162; docs/performance.md has the full curve), and
#: warm-start's +1 initial-residual matvec still nets 5 Gram
#: reads/row vs cold@6's 7 — a ~29% cut of the dominant stream.
_CG_ITERS_BF16 = int(os.environ.get("PIO_ALS_CG_ITERS_BF16") or
                     ("3" if _CG_WARMSTART else "6"))


def _cg_solve_spd(a: jax.Array, b: jax.Array, iters: int,
                  matvec_dtype: Any = jnp.float32,
                  lam: Optional[jax.Array] = None,
                  shared: Optional[jax.Array] = None,
                  x0: Optional[jax.Array] = None,
                  tol: float = 0.0,
                  return_iters: bool = False):
    """Batched Jacobi-PCG for SPD systems → x ≈ (a [+ diag(lam)])⁻¹ b, [B, K].

    Division guards make converged (and all-zero) systems fixed points
    instead of NaN factories: a zero-nnz explicit row has a = λI, b = 0,
    so r = 0 → every α/β guard holds it at x = 0.

    ``matvec_dtype=bfloat16`` halves the dominant HBM stream (every
    iteration re-reads the whole [B, K, K] Gram batch — ~9 GB at ML-20M
    scale) by running the matvec on a bf16 Gram with f32 accumulation;
    x/r/p and all reductions stay f32. Used by the mixed schedule's bf16
    sweeps only — the f32 polish runs full-precision CG.

    ``lam`` ([B] f32) applies the λ(+λ·nnz) ridge INSIDE the matvec in
    f32, so the caller can hand over a bare bf16 Gram (half the write and
    every re-read) while the regularizer — the part conditioning depends
    on — never rounds through bf16.

    ``shared`` ([K, K] f32) adds a batch-shared SPD term (implicit ALS's
    YᵗY) inside the matvec as one thin einsum — the [B, K, K] broadcast
    ``yty[None] + gram`` never materializes, which at training scale is a
    whole extra Gram-batch write + read per half-sweep.

    ``x0`` ([B, K] f32) warm-starts the iteration (one extra matvec for
    the initial residual). ALS re-solves every factor row from scratch
    each sweep while the true solution moves less and less — warm
    starting from the previous sweep's factors buys the same residual in
    roughly half the iterations once the alternation settles, and each
    saved iteration saves a full re-read of the Gram batch.

    ``tol`` > 0 adds a DEVICE-SIDE residual early exit
    (``lax.while_loop`` with ``iters`` as the ceiling): the loop stops
    once every row's preconditioned residual rᵗz has fallen to
    tol²·(r₀ᵗz₀) — well-conditioned batches (warm starts on settled
    alternations, small fold-in systems) stop paying the full budget,
    and each saved iteration saves a full Gram-batch re-read. No host
    sync: the criterion is evaluated in-trace (the host-sync lint
    contract). ``tol == 0`` keeps the fixed-budget ``fori_loop`` —
    bit-identical to the historical path. ``return_iters`` additionally
    returns the iteration count actually run (a device scalar; tests
    pin the early exit with it)."""
    diag = jnp.diagonal(a, axis1=-2, axis2=-1).astype(jnp.float32)
    if shared is not None:
        diag = diag + jnp.diagonal(shared)[None, :]
    if lam is not None:
        diag = diag + lam[:, None]
    minv = jnp.where(diag > 0, 1.0 / diag, 0.0)
    hp = jax.lax.Precision.HIGHEST
    a_mv = a if a.dtype == matvec_dtype else a.astype(matvec_dtype)

    def matvec(p):
        ap = jnp.einsum(
            "bkl,bl->bk", a_mv, p.astype(a_mv.dtype),
            preferred_element_type=jnp.float32,
            precision=hp if a_mv.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
        if shared is not None:
            ap = ap + jnp.einsum(
                "kl,bl->bk", shared, p,
                preferred_element_type=jnp.float32, precision=hp)
        if lam is not None:
            ap = ap + lam[:, None] * p
        return ap

    def body(_, carry):
        x, r, p, rz = carry
        ap = matvec(p)
        pap = jnp.sum(p * ap, -1)
        alpha = jnp.where(pap > 0, rz / pap, 0.0)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        z = minv * r
        rz2 = jnp.sum(r * z, -1)
        beta = jnp.where(rz > 0, rz2 / rz, 0.0)
        p = z + beta[:, None] * p
        return x, r, p, rz2

    if x0 is None:
        x, r = jnp.zeros_like(b), b
    else:
        x = x0.astype(jnp.float32)
        r = b - matvec(x)
    z = minv * r
    rz0 = jnp.sum(r * z, -1)
    if tol and tol > 0.0:
        tol2 = jnp.float32(tol) ** 2

        def cond(carry):
            i, _x, _r, _p, rz = carry
            return jnp.logical_and(i < iters, jnp.any(rz > tol2 * rz0))

        def wbody(carry):
            i, x, r, p, rz = carry
            x, r, p, rz = body(0, (x, r, p, rz))
            return i + 1, x, r, p, rz

        i, x, _r, _p, _rz = jax.lax.while_loop(
            cond, wbody, (jnp.int32(0), x, r, z, rz0))
    else:
        x, _r, _p, _rz = jax.lax.fori_loop(
            0, iters, body, (x, r, z, rz0))
        i = jnp.int32(iters)
    return (x, i) if return_iters else x


def _reg_solve(
    gram: jax.Array,           # [B, K, K]
    rhs: jax.Array,            # [B, K]
    nnz: jax.Array,            # [B]
    l2: float,
    reg_nnz: bool,
    implicit: bool,
    yty: Optional[jax.Array],
    cg_iters: int = _CG_ITERS,
    cg_matvec_dtype: Any = jnp.float32,
    x0: Optional[jax.Array] = None,
    cg_tol: float = 0.0,
) -> jax.Array:
    """Regularize + batched SPD solve; zero factors for empty rows."""
    rank = gram.shape[-1]
    eye = jnp.eye(rank, dtype=jnp.float32)
    if implicit:
        # CG keeps the batch-shared YᵗY OUT of the matrix (one thin einsum
        # in the matvec) — the [B, K, K] broadcast sum never materializes
        lam = jnp.full(nnz.shape, l2, jnp.float32)
        shared = yty
        a = gram
    else:
        # MLlib-style ALS-WR: lambda scaled by row nnz (reg_nnz=True).
        # For CG the ridge stays OUT of the matrix — applied in f32 inside
        # the matvec — so a bf16 Gram batch can be solved directly.
        lam = l2 * jnp.where(reg_nnz, jnp.maximum(nnz, 1.0), 1.0)
        shared = None
        a = gram
    if _SOLVER == "cg":
        # implicit grams are dominated by the shared YᵗY with only λ (not
        # λ·nnz) on the diagonal — worse conditioned, so double the budget
        sol = _cg_solve_spd(a, rhs, cg_iters * (2 if implicit else 1),
                            matvec_dtype=cg_matvec_dtype, lam=lam,
                            shared=shared, x0=x0, tol=cg_tol)
    else:
        a = a.astype(jnp.float32) + lam[:, None, None] * eye
        if shared is not None:
            a = a + shared[None]
        chol = jax.scipy.linalg.cho_factor(a)
        sol = jax.scipy.linalg.cho_solve(chol, rhs[..., None])[..., 0]
    return jnp.where(nnz[:, None] > 0, sol, 0.0)


@functools.partial(
    jax.jit,
    static_argnames=("reg_nnz", "compute_dtype", "precision", "cg_iters",
                     "cg_tol"),
)
def _solve_bucket(
    other_factors: jax.Array,  # [M, K] f32
    cols: jax.Array,           # [B, D] int32
    vals: jax.Array,           # [B, D] f32
    mask: jax.Array,           # [B, D] f32 in {0, 1}
    l2: float,
    reg_nnz: bool = True,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    cg_iters: int = _CG_ITERS,
    x0: Optional[jax.Array] = None,
    cg_tol: float = 0.0,
) -> jax.Array:
    """Batched normal-equation solve for one degree bucket → [B, K].

    Precision note: DEFAULT matmul precision truncates f32 einsum inputs to
    bf16 passes, which stalls ALS convergence (the Gram matrices pick up
    ~1e-2 error and the alternation stops improving around RMSE 0.6 on data
    it should fit to <0.1). The Gram/rhs assembly therefore defaults to
    HIGHEST (multi-pass f32 on the MXU); ``compute_dtype=bfloat16`` with
    DEFAULT precision remains available as the fast low-precision mode for
    early sweeps.
    """
    # the bf16 bucket path emits the Gram batch directly in bf16 (CG takes
    # it as-is, with the ridge applied in f32 — see _cg_solve_spd); the
    # cholesky solver needs the f32 matrix to factor
    gram_dtype = compute_dtype if _SOLVER == "cg" else jnp.float32
    gram, rhs, nnz = _gram_rhs_nnz(
        other_factors, cols, vals, mask, compute_dtype, precision,
        implicit=False, alpha=0.0, gram_dtype=gram_dtype)
    return _reg_solve(gram, rhs, nnz, l2, reg_nnz, implicit=False, yty=None,
                      cg_iters=cg_iters, cg_matvec_dtype=compute_dtype,
                      x0=x0, cg_tol=cg_tol)


def _solve_bucket_kernel(
    gsrc: jax.Array,           # [M, K] gather source, ALREADY compute-dtype
    cols: jax.Array,
    vals: jax.Array,
    mask: jax.Array,
    l2: float,
    reg_nnz: bool,
    cg_iters: int,
    kernel_rows: int = 1,
    x0: Optional[jax.Array] = None,
) -> jax.Array:
    """Explicit-CG bucket solve via the fused Pallas kernel.

    Same contract as :func:`_solve_bucket` (CG leg): λ(+λ·nnz) ridge,
    empty rows → 0. The [B, K, K] Gram batch lives only in VMEM — see
    ops/pallas_kernels.als_solve_cg_pallas. (Interpret-mode selection
    happens inside the kernel wrapper: no Mosaic backend → interpret,
    which is how PIO_ALS_KERNEL=on works on the CPU test mesh.)
    ``kernel_rows`` selects the one-row or row-grouped kernel layout
    (resolved by the caller via :func:`_kernel_rows_default`)."""
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_solve_cg_pallas,
    )

    return als_solve_cg_pallas(
        gsrc, cols, vals, mask, l2, reg_nnz=reg_nnz, iters=cg_iters,
        rows_per_program=max(kernel_rows, 1), x0=x0)


def _solve_bucket_fused(
    gsrc: jax.Array,           # [M, K] gather source, ALREADY compute-dtype
    yty: Optional[jax.Array],  # [K, K] shared implicit term, or None
    cols: jax.Array,
    vals: jax.Array,
    mask: jax.Array,
    l2: float,
    reg_nnz: bool,
    cg_iters: int,
    implicit: bool = False,
    alpha: float = 0.0,
    x0: Optional[jax.Array] = None,
) -> jax.Array:
    """Bucket solve via the fused gather+Gram+CG Pallas kernel — the
    table-resident generation of :func:`_solve_bucket_kernel`: the
    [B, D, K] gather never materializes in HBM either. Covers BOTH
    feedback modes (implicit rides the precomputed YᵗY as one shared
    operand); callers gate on ``als_fused_fits`` for the table shape
    and pass the implicit path's doubled CG budget themselves."""
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_fused_solve_cg_pallas,
    )

    return als_fused_solve_cg_pallas(
        gsrc, cols, vals, mask, l2, reg_nnz=reg_nnz, iters=cg_iters,
        implicit=implicit, alpha=alpha, yty=yty, x0=x0)


#: f32-element budget for one bucket chunk's gather intermediate
#: ([chunk, D, K]); 2^24 elements = 64 MB. Buckets whose full gather would
#: exceed this are solved in row chunks under lax.map, keeping peak HBM for
#: the normal-equation assembly flat regardless of dataset size (the
#: ML-20M-scale requirement: 20M nnz × rank 128 would otherwise gather
#: multi-GB [B, D, K] tensors per bucket). Tunable: bigger chunks = fewer
#: sequential lax.map steps at more peak HBM.
_CHUNK_ELEMS = int(os.environ.get("PIO_ALS_CHUNK_ELEMS", str(1 << 24)))


def _solve_bucket_chunked(solver_fn, cols, vals, mask, rank: int,
                          row_elems: Optional[int] = None,
                          x0: Optional[jax.Array] = None):
    """Apply ``solver_fn((cols, vals, mask[, x0])) -> sol`` in bounded row
    chunks.

    Zero-mask padding rows solve to 0 and are sliced off, so chunk padding
    never leaks into the scatter. ``row_elems`` overrides the per-row
    gather footprint used for chunk sizing (the Pallas path pads D and K
    to lane multiples, so its materialized gather is larger than D·rank
    for narrow buckets). ``x0`` rides along row-aligned when present
    (CG warm start)."""
    B, D = cols.shape
    rank_x = x0.shape[1] if x0 is not None else rank
    chunk = max(8, _CHUNK_ELEMS // max(row_elems or (D * rank), 1))
    if B <= chunk:
        t = (cols, vals, mask) + ((x0,) if x0 is not None else ())
        return solver_fn(t)
    n = -(-B // chunk)
    pad = n * chunk - B
    if pad:
        cols = jnp.pad(cols, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, pad), (0, 0)))
        if x0 is not None:
            x0 = jnp.pad(x0, ((0, pad), (0, 0)))
    parts = (cols.reshape(n, chunk, D), vals.reshape(n, chunk, D),
             mask.reshape(n, chunk, D))
    if x0 is not None:
        parts = parts + (x0.reshape(n, chunk, rank_x),)
    sols = jax.lax.map(solver_fn, parts)
    return sols.reshape(n * chunk, rank)[:B]


def _gram_rhs_nnz_chunked(other_factors, cols, vals, mask, compute_dtype,
                          precision, implicit, alpha):
    """Apply :func:`_gram_rhs_nnz` in bounded row chunks (lax.map).

    The heavy-segment path's equivalent of :func:`_solve_bucket_chunked`:
    split segments are max_width wide, so even a few hundred of them would
    gather a multi-GB [S, D, K] tensor at once. Chunk padding rows carry
    zero masks → zero partials, sliced off before the segment sum."""
    S, D = cols.shape
    rank = other_factors.shape[1]
    chunk = max(1, _CHUNK_ELEMS // max(D * rank, 1))
    if S <= chunk:
        return _gram_rhs_nnz(other_factors, cols, vals, mask, compute_dtype,
                             precision, implicit, alpha)
    n = -(-S // chunk)
    pad = n * chunk - S
    if pad:
        cols = jnp.pad(cols, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, pad), (0, 0)))
    pg, prhs, pnnz = jax.lax.map(
        lambda t: _gram_rhs_nnz(other_factors, t[0], t[1], t[2],
                                compute_dtype, precision, implicit, alpha),
        (cols.reshape(n, chunk, D), vals.reshape(n, chunk, D),
         mask.reshape(n, chunk, D)),
    )
    return (pg.reshape(n * chunk, rank, rank)[:S],
            prhs.reshape(n * chunk, rank)[:S],
            pnnz.reshape(n * chunk)[:S])


def _gather_x0(prev_factors: jax.Array, row_ids: jax.Array) -> jax.Array:
    """Warm-start factors for a padded row batch → [rows, K] f32.

    Padding rows carry row_id -1, and a bare ``prev_factors[row_ids]``
    wraps numpy-style to the LAST row — padding rows would warm-start
    from a real row's factors. Their solutions are dropped at scatter
    (``_scatter_rows_impl``), but the wraparound still feeds garbage
    into the padded CG lanes, so clamp the gather and zero the padding
    rows (a zero start is the exact cold-start fixed point)."""
    safe = prev_factors[jnp.maximum(row_ids, 0)].astype(jnp.float32)
    return jnp.where(row_ids[:, None] >= 0, safe, 0.0)


def _scatter_rows_impl(out: jax.Array, row_ids: jax.Array,
                       sol: jax.Array) -> jax.Array:
    # Padding rows carry row_id -1. JAX scatter wraps negative indices
    # numpy-style (-1 = last row!), so remap them to n (out of bounds) where
    # mode="drop" genuinely drops them.
    safe_ids = jnp.where(row_ids < 0, out.shape[0], row_ids)
    return out.at[safe_ids].set(sol, mode="drop")


@functools.partial(jax.jit, donate_argnames=("out",),
                   static_argnames=())
def _scatter_rows(out: jax.Array, row_ids: jax.Array, sol: jax.Array) -> jax.Array:
    return _scatter_rows_impl(out, row_ids, sol)


def _sweep_side(
    n_rows: int,
    other_factors: jax.Array,
    tree,                      # ((row_ids, cols, vals, mask), ...)
    heavy,                     # (seg_ids, row_ids, cols, vals, mask) | None
    l2: float,
    alpha: float,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    cg_iters: int = _CG_ITERS,
    use_kernel: bool = False,
    kernel_min_d: int = 0,
    kernel_rows: int = 1,
    prev_factors: Optional[jax.Array] = None,
    use_fused: bool = False,
    cg_tol: float = 0.0,
) -> jax.Array:
    """One half-sweep (traced): solve every bucket + split rows, scatter.

    THE single sweep implementation — the fused trainer, als_sweep and
    als_sweep_implicit all trace through here, so the paths cannot
    diverge. ``use_kernel``, ``kernel_min_d`` and ``use_fused``
    (resolved by the caller, outside the trace, and part of every jit
    cache key — a mid-trace global read would silently survive a
    runtime override) route CG buckets of width ≥ min-D through the
    Pallas solves: ``use_fused`` selects the gather+Gram+CG generation
    (the caller has already checked the gather table fits the VMEM
    budget for THIS side — see ``_fused_sides``), otherwise the
    two-stage Gram+CG kernel serves explicit buckets. Narrower buckets
    and the heavy split-row path always use the XLA assembly; implicit
    buckets are kernel-eligible only in the fused generation (the
    shared-YᵗY operand)."""
    rank = other_factors.shape[1]
    out = jnp.zeros((n_rows, rank), jnp.float32)
    yty = _gram_all(other_factors, precision) if implicit else None
    # Hoist the compute-dtype cast of the gather source to once per
    # half-sweep — inside the chunked lax.map it would re-cast the whole
    # table per chunk (~150 chunks/half-sweep at ML-20M), swamping the
    # bf16 traffic saving it exists to provide. Implicit mode stays f32.
    gsrc = other_factors
    if not implicit and other_factors.dtype != compute_dtype:
        gsrc = other_factors.astype(compute_dtype)
    if use_fused and use_kernel:
        # the fused kernel's table block needs a sublane-aligned row
        # count; pad ONCE per half-sweep (padding rows are never
        # gathered — every col id < M — so the XLA buckets and the
        # heavy path can share the padded source unchanged)
        mp = -(-gsrc.shape[0] // 8) * 8
        if mp != gsrc.shape[0]:
            gsrc = jnp.pad(gsrc, ((0, mp - gsrc.shape[0]), (0, 0)))
    for row_ids, cols, vals, mask in tree:
        row_elems = None
        x0 = (_gather_x0(prev_factors, row_ids)
              if prev_factors is not None else None)
        if use_kernel and use_fused and cols.shape[1] >= kernel_min_d:
            from incubator_predictionio_tpu.ops.pallas_kernels import (
                als_fused_row_elems,
            )

            row_elems = als_fused_row_elems(cols.shape[1], rank)

            def solver(t, _yty=yty):
                return _solve_bucket_fused(
                    gsrc, _yty, t[0], t[1], t[2], l2, reg_nnz=reg_nnz,
                    cg_iters=cg_iters * (2 if implicit else 1),
                    implicit=implicit, alpha=alpha,
                    x0=t[3] if len(t) > 3 else None)
        elif implicit:
            def solver(t, _yty=yty):
                return _solve_bucket_implicit(
                    other_factors, _yty, t[0], t[1], t[2], l2, alpha,
                    precision=precision, cg_iters=cg_iters,
                    x0=t[3] if len(t) > 3 else None, cg_tol=cg_tol)
        elif use_kernel and cols.shape[1] >= kernel_min_d:
            # chunk by the PADDED gather footprint the kernel actually
            # materializes (single source of truth in pallas_kernels)
            from incubator_predictionio_tpu.ops.pallas_kernels import (
                als_padded_row_elems,
            )

            row_elems = als_padded_row_elems(cols.shape[1], rank)

            def solver(t):
                return _solve_bucket_kernel(
                    gsrc, t[0], t[1], t[2], l2, reg_nnz=reg_nnz,
                    cg_iters=cg_iters, kernel_rows=kernel_rows,
                    x0=t[3] if len(t) > 3 else None)
        else:
            def solver(t):
                return _solve_bucket(
                    gsrc, t[0], t[1], t[2], l2, reg_nnz=reg_nnz,
                    compute_dtype=compute_dtype, precision=precision,
                    cg_iters=cg_iters, x0=t[3] if len(t) > 3 else None,
                    cg_tol=cg_tol)
        # large buckets solve in bounded row chunks (lax.map) so the
        # [B, D, K] gather / [B, K, K] gram temps never exceed the chunk
        # budget — the ML-20M-scale HBM requirement
        sol = _solve_bucket_chunked(solver, cols, vals, mask, rank,
                                    row_elems=row_elems, x0=x0)
        out = _scatter_rows_impl(out, row_ids, sol)
    if heavy is not None:
        h_ids, h_sol = _solve_heavy(
            gsrc, heavy, l2, alpha, reg_nnz, compute_dtype,
            precision, implicit, yty, cg_iters=cg_iters,
            prev_factors=prev_factors, cg_tol=cg_tol)
        out = _scatter_rows_impl(out, h_ids, h_sol)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("n_rows", "reg_nnz", "compute_dtype", "precision",
                     "implicit", "cg_iters", "use_kernel", "kernel_min_d",
                     "kernel_rows", "use_fused", "cg_tol"),
)
def _sweep_side_jit(n_rows, other_factors, tree, heavy, l2, alpha, reg_nnz,
                    compute_dtype, precision, implicit,
                    cg_iters=_CG_ITERS, use_kernel=False, kernel_min_d=0,
                    kernel_rows=1, prev_factors=None, use_fused=False,
                    cg_tol=0.0):
    return _sweep_side(n_rows, other_factors, tree, heavy, l2, alpha,
                       reg_nnz, compute_dtype, precision, implicit,
                       cg_iters=cg_iters, use_kernel=use_kernel,
                       kernel_min_d=kernel_min_d, kernel_rows=kernel_rows,
                       prev_factors=prev_factors, use_fused=use_fused,
                       cg_tol=cg_tol)


def _update_side(
    n_rows: int,
    other_factors: jax.Array,
    buckets: Sequence[PaddedRows],
    l2: float,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
) -> jax.Array:
    use_kernel = _kernel_enabled(False, warm=False)
    return _sweep_side_jit(
        n_rows, other_factors, _buckets_tree(buckets), None, l2, 0.0,
        reg_nnz, compute_dtype, precision, implicit=False,
        # this path never passes prev_factors, so probe the cold variant
        use_kernel=use_kernel,
        kernel_min_d=_KERNEL_MIN_D,
        kernel_rows=_kernel_rows_default(),
        use_fused=_fused_one(use_kernel, False, False,
                             other_factors.shape[0],
                             other_factors.shape[1], compute_dtype),
        cg_tol=_cg_tol_env())


def assert_no_split(buckets: Sequence[PaddedRows], side: str = "row") -> None:
    """Raise if any row was split across padded rows (degree > max_width).

    The scatter-set in the sweep keeps one arbitrary segment's solution for
    a duplicated row id, which would be silently wrong. The ``als_sweep``
    API therefore rejects split rows; ``als_train``/``als_train_implicit``
    route them through the partial-Gram combining solve instead
    (``split_heavy`` + ``_solve_heavy``)."""
    ids = np.concatenate(
        [np.asarray(b.row_ids)[np.asarray(b.row_ids) >= 0] for b in buckets]
    ) if buckets else np.empty(0, np.int32)
    if len(ids) != len(np.unique(ids)):
        raise NotImplementedError(
            f"a {side} exceeds the bucket max_width (its interactions were "
            "split across solve rows); raise max_width or wait for the "
            "sharded-split solver"
        )


def als_sweep(
    state: ALSState,
    user_buckets: Sequence[PaddedRows],
    item_buckets: Sequence[PaddedRows],
    l2: float = 0.1,
    reg_nnz: bool = True,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    validate: bool = True,
) -> ALSState:
    """One full ALS iteration: solve users against items, then items against
    the *new* user factors (the classic alternation order).

    ``validate`` checks the buckets contain no split rows (see
    :func:`assert_no_split`); pass False when the caller has already
    validated (als_train does, once, outside the sweep loop)."""
    if validate:
        assert_no_split(user_buckets, "user")
        assert_no_split(item_buckets, "item")
    new_users = _update_side(
        state.user_factors.shape[0], state.item_factors, user_buckets,
        l2, reg_nnz, compute_dtype, precision,
    )
    new_items = _update_side(
        state.item_factors.shape[0], new_users, item_buckets,
        l2, reg_nnz, compute_dtype, precision,
    )
    return ALSState(user_factors=new_users, item_factors=new_items)


# ---------------------------------------------------------------------------
# Implicit-feedback ALS (Hu-Koren-Volinsky), the MLlib ALS.trainImplicit
# replacement used by the similarproduct/ecommerce templates
# (examples/scala-parallel-similarproduct/multi/src/main/scala/
# ALSAlgorithm.scala:147).
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit, static_argnames=("precision", "cg_iters", "cg_tol")
)
def _solve_bucket_implicit(
    other_factors: jax.Array,  # [M, K]
    yty: jax.Array,            # [K, K] — Gram of ALL other-side factors
    cols: jax.Array,           # [B, D]
    vals: jax.Array,           # [B, D] raw confidence weights r
    mask: jax.Array,           # [B, D]
    l2: float,
    alpha: float,
    precision: Any = jax.lax.Precision.HIGHEST,
    cg_iters: int = _CG_ITERS,
    x0: Optional[jax.Array] = None,
    cg_tol: float = 0.0,
) -> jax.Array:
    """Per-row system: (YᵗY + Yᵤᵗ(Cᵤ−I)Yᵤ + λI) x = Yᵤᵗ cᵤ with
    c = 1 + α·r and binary preference — YᵗY is shared across the whole
    batch (the classic implicit-ALS trick), so per-row work stays
    proportional to the row's observations. The implicit CG runs a
    DOUBLED budget (worse conditioning, see _reg_solve), so a closer
    starting point helps it most; the budget itself is unchanged until
    an implicit-specific convergence study justifies cutting it."""
    gram, rhs, nnz = _gram_rhs_nnz(
        other_factors, cols, vals, mask, jnp.float32, precision,
        implicit=True, alpha=alpha)
    return _reg_solve(gram, rhs, nnz, l2, True, implicit=True, yty=yty,
                      cg_iters=cg_iters, x0=x0, cg_tol=cg_tol)


@functools.partial(jax.jit, static_argnames=("precision",))
def _gram_all(factors: jax.Array, precision: Any) -> jax.Array:
    return jnp.einsum(
        "ik,il->kl", factors, factors,
        preferred_element_type=jnp.float32, precision=precision,
    )


def _update_side_implicit(
    n_rows: int,
    other_factors: jax.Array,
    buckets: Sequence[PaddedRows],
    l2: float,
    alpha: float,
    precision: Any,
) -> jax.Array:
    use_kernel = _kernel_enabled(True, warm=False)
    return _sweep_side_jit(
        n_rows, other_factors, _buckets_tree(buckets), None, l2, alpha,
        True, jnp.float32, precision, implicit=True,
        use_kernel=use_kernel, kernel_min_d=_KERNEL_MIN_D,
        use_fused=_fused_one(use_kernel, True, False,
                             other_factors.shape[0],
                             other_factors.shape[1], jnp.float32),
        cg_tol=_cg_tol_env())


def als_sweep_implicit(
    state: ALSState,
    user_buckets: Sequence[PaddedRows],
    item_buckets: Sequence[PaddedRows],
    l2: float = 0.1,
    alpha: float = 1.0,
    precision: Any = jax.lax.Precision.HIGHEST,
    validate: bool = True,
) -> ALSState:
    if validate:
        assert_no_split(user_buckets, "user")
        assert_no_split(item_buckets, "item")
    new_users = _update_side_implicit(
        state.user_factors.shape[0], state.item_factors, user_buckets,
        l2, alpha, precision,
    )
    new_items = _update_side_implicit(
        state.item_factors.shape[0], new_users, item_buckets,
        l2, alpha, precision,
    )
    return ALSState(user_factors=new_users, item_factors=new_items)


def als_train_implicit(
    users: np.ndarray,
    items: np.ndarray,
    weights: np.ndarray,
    n_users: int,
    n_items: int,
    rank: int = 64,
    iterations: int = 10,
    l2: float = 0.1,
    alpha: float = 1.0,
    seed: int = 0,
    precision: Any = jax.lax.Precision.HIGHEST,
    max_width: int = 1 << 16,
) -> ALSState:
    """Implicit-feedback training over (user, item, weight) observations."""
    (user_light, user_heavy), (item_light, item_heavy) = build_both_sides(
        users, items, weights, n_users, n_items, max_width=max_width)
    state = als_init(jax.random.key(seed), n_users, n_items, rank)
    # resolve the kernel/fused selectors HERE, outside the trace (the
    # Mosaic probe compiles real kernels) — implicit is kernel-eligible
    # only in the fused-gather generation (shared YᵗY operand)
    warm = _CG_WARMSTART
    use_kernel = _kernel_enabled(True, warm=warm)
    out = _als_run_fused(
        state, _buckets_tree(user_light), _buckets_tree(item_light),
        l2, alpha, iterations, True, jnp.float32, precision, implicit=True,
        user_heavy=_heavy_tree(user_heavy), item_heavy=_heavy_tree(item_heavy),
        warmstart=warm, use_kernel=use_kernel, kernel_min_d=_KERNEL_MIN_D,
        use_fused=(_fused_sides(n_users, n_items, True, warm,
                                jnp.float32, rank)
                   if use_kernel else (False, False)),
        cg_tol=_cg_tol_env(),
    )
    from incubator_predictionio_tpu.ops.retrain import _book_sweeps

    _book_sweeps("fresh", iterations)
    return out


# ---------------------------------------------------------------------------
# Mesh-sharded (placed) training — the full ALX layout (PAPERS.md: ALX §4).
#
# A FactorPlacement (parallel/placement.py) shards BOTH factor tables on
# rows over the flattened mesh; interaction buckets are shard-blocked so
# each device solves exactly the rows it owns; the other side's factors
# move by explicit collectives inside shard_map (parallel/collectives.py):
# an all-gather for tables narrow enough to replicate transiently, a
# ppermute ring over table SLICES for wide ones — each device only ever
# holds one slice of the wide table, which is what re-enables the fused
# Gram+solve kernel's VMEM residency at big-table shapes. Updates are
# shard-local by construction (each device scatters only its own rows:
# the cross-replica weight-update-sharding pattern, arxiv 2004.13336).
# The whole multi-sweep run is ONE dispatch; nothing crosses to the host.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _ShardCfg:
    """Hashable static config of one placed run (jit cache key)."""

    u_mode: str                 # gather strategy of the USER half-sweep
    i_mode: str                 # ... and the item half-sweep
    implicit: bool
    reg_nnz: bool
    l2: float
    alpha: float
    compute_dtype: Any
    precision: Any
    cg_iters: int
    cg_tol: float
    use_kernel: bool
    kernel_min_d: int
    kernel_rows: int
    warmstart: bool
    fused_u: bool
    fused_i: bool


def _shard_gather_modes(placement, rank: int, dtype: Any,
                        implicit: bool) -> Tuple[str, str]:
    """Per-half-sweep gather strategy → (user_sweep, item_sweep).

    `PIO_SHARD_GATHER` = allgather | ring | auto (default). Auto keeps
    the transient full-table all-gather while the gathered table stays
    under `PIO_SHARD_ALLGATHER_MB` (default 64) AND inside the fused
    kernel's VMEM table budget; it switches to the slice-resident ring
    when the full table would blow either bound but its per-shard slice
    still fits the VMEM budget — ring residency is what re-enables the
    fused Gram+solve kernel on big-table sides (at ML-20M the 35 MB
    user table routes ring and each ~4.4 MB bf16 slice pins in VMEM;
    docs/performance.md "Sharded ALS"). The decision is per gather
    SOURCE (user sweep gathers the item table and vice versa), resolved
    here outside any trace."""
    mode = os.environ.get("PIO_SHARD_GATHER", "auto")
    if mode in ("allgather", "ring"):
        return mode, mode
    try:
        cap_mb = float(os.environ.get("PIO_SHARD_ALLGATHER_MB", "64"))
    except ValueError:
        cap_mb = 64.0
    item = jnp.dtype(jnp.float32 if implicit else dtype).itemsize
    n = placement.n_shards

    def one(table_rows: int) -> str:
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            als_fused_fits,
        )

        dt = jnp.float32 if implicit else dtype
        if table_rows * rank * item > cap_mb * (1 << 20):
            return "ring"
        if (n > 1 and not als_fused_fits(table_rows, rank, dt)
                and als_fused_fits(-(-table_rows // n), rank, dt)):
            return "ring"
        return "allgather"

    return one(placement.n_items_padded), one(placement.n_users_padded)


def gather_source_rows(placement, side_gathered: str, mode: str) -> int:
    """Rows of the array a half-sweep's gather hands the solve — the
    FULL padded table under allgather, ONE slice under ring. This is
    the shape the fused kernel pins in VMEM, and the ONE rule shared by
    :func:`_fused_sides_placed` and bench_shard's ``shard_fused_fits_*``
    acceptance keys (a second copy of this math could silently drift
    from what the trainer actually routes)."""
    full = (placement.n_users_padded if side_gathered == "user"
            else placement.n_items_padded)
    return (placement.shard_rows(side_gathered) if mode == "ring"
            else full)


def _fused_sides_placed(placement, modes: Tuple[str, str], implicit: bool,
                        warm: bool, dtype: Any,
                        rank: int) -> Tuple[bool, bool]:
    """Sharded twin of :func:`_fused_sides`: the fused kernel pins the
    gather source in VMEM, and under a placement that source is either
    the transiently gathered FULL table (allgather mode) or one SLICE of
    it (ring mode) — so `als_fused_fits` is checked against the
    shard-local shape the kernel will actually pin (see
    :func:`gather_source_rows`). Sharding is the MFU unlock: a table
    over budget on one chip routes fused again once its slice fits."""
    use_kernel = _kernel_enabled(implicit, warm=warm)
    if not use_kernel:
        return False, False
    dt = jnp.float32 if implicit else dtype
    return (
        _fused_one(True, implicit, warm,
                   gather_source_rows(placement, "item", modes[0]),
                   rank, dt),
        _fused_one(True, implicit, warm,
                   gather_source_rows(placement, "user", modes[1]),
                   rank, dt),
    )


def build_placed_sides(
    users: np.ndarray,
    items: np.ndarray,
    vals: np.ndarray,
    placement,
    modes: Tuple[str, str],
    max_width: int = 1 << 16,
    ring_layouts: Tuple[Any, Any] = (None, None),
    ring_host_out: Optional[dict] = None,
):
    """Host-side prep of both orientations in their placed layouts →
    (u_data, i_data), every leaf device-put sharded on axis 0.

    allgather sides are shard-blocked single-chip buckets (cols global,
    row ids localized per device; heavy split rows partitioned to their
    owner so the partial-Gram reduction stays shard-local); ring sides
    are the per-step pure/mixed layout of
    :func:`~...parallel.sharding.build_ring_side`.

    ``ring_layouts`` lets the ring-plan cache (ops/retrain.py
    ``_ring_sides_with_reuse``) hand in an already-merged HOST
    (pure, mixed) layout per side — the side then skips the full-COO
    build and only pays the device put. ``ring_host_out`` (a dict)
    receives each ring side's host layout under its side name, so the
    cache can adopt what was built without a second construction."""
    from incubator_predictionio_tpu.parallel.sharding import (
        build_ring_side,
        localize_tree,
        shard_block_buckets,
        shard_block_heavy,
    )

    n = placement.n_shards
    sharding = placement.table_sharding()

    def put(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), sharding), tree)

    def one_side(side, rows, cols, other_side, mode, prebuilt):
        sr_self = placement.shard_rows(side)
        sr_other = placement.shard_rows(other_side)
        if mode == "ring":
            if prebuilt is not None:
                pure, mixed = prebuilt
            else:
                pure, mixed = build_ring_side(
                    rows, cols, vals, n, sr_self, sr_other,
                    max_width=max_width)
            if ring_host_out is not None:
                ring_host_out[side] = (pure, mixed)
            return put((pure, mixed))
        light, heavy = split_heavy(build_padded_rows(
            rows, cols, vals, sr_self * n, max_width=max_width))
        tree = localize_tree(
            shard_block_buckets(light, n, sr_self), n, sr_self)
        return put((tree, shard_block_heavy(heavy, n, sr_self)))

    return (one_side("user", users, items, "item", modes[0],
                     ring_layouts[0]),
            one_side("item", items, users, "user", modes[1],
                     ring_layouts[1]))


def _ring_sweep_side(
    shard_rows_self: int,
    other_local: jax.Array,     # [rows_other/n, K] — this device's slice
    data,                       # (pure, mixed) local views
    cfg: _ShardCfg,
    placement,
    prev_local: Optional[jax.Array],
    fused: bool,
) -> jax.Array:
    """One placed half-sweep in ring mode (traced, inside shard_map).

    The other table's slices rotate around the mesh ring (``ppermute``,
    n−1 hops); at each step this device solves the PURE rows whose cols
    all live in the currently held slice — complete systems, so the
    fused gather+Gram+CG kernel applies with only the slice resident —
    and accumulates partial Gram/RHS for MIXED rows (cols spanning
    slices), which solve once after the ring via the same
    partial-Gram-combining path as split rows (`_reg_solve` over the
    segment sums). Peak residency is exactly two slices (current +
    in-flight), never the full table."""
    from incubator_predictionio_tpu.parallel.collectives import (
        all_reduce_sum,
        ppermute_next,
    )

    axes = placement.axes
    n = placement.n_shards
    pure, mixed = data
    rank = other_local.shape[1]
    out = jnp.zeros((shard_rows_self, rank), jnp.float32)
    implicit = cfg.implicit
    yty = (all_reduce_sum(_gram_all(other_local, cfg.precision), axes)
           if implicit else None)
    gsrc = other_local
    if not implicit and other_local.dtype != cfg.compute_dtype:
        gsrc = other_local.astype(cfg.compute_dtype)
    if fused and cfg.use_kernel:
        mp8 = -(-gsrc.shape[0] // 8) * 8
        if mp8 != gsrc.shape[0]:
            gsrc = jnp.pad(gsrc, ((0, mp8 - gsrc.shape[0]), (0, 0)))
    h = mixed[0].shape[0] if mixed is not None else 0
    mg = jnp.zeros((h + 1, rank, rank), jnp.float32)
    mr = jnp.zeros((h + 1, rank), jnp.float32)
    mn = jnp.zeros(h + 1, jnp.float32)
    cur = gsrc
    for s in range(n):
        for rid_a, col_a, val_a, msk_a in pure:
            rid, c, v, m = rid_a[s], col_a[s], val_a[s], msk_a[s]
            x0 = (_gather_x0(prev_local, rid)
                  if prev_local is not None else None)
            # same solver dispatch as _sweep_side, and the same
            # _solve_bucket_chunked streaming: ring mode exists for the
            # catalog scale where a one-shot [B, D, K] gather temp would
            # OOM, so pure buckets must keep the bounded-chunk guarantee
            row_elems = None
            if cfg.use_kernel and fused and c.shape[1] >= cfg.kernel_min_d:
                from incubator_predictionio_tpu.ops.pallas_kernels import (
                    als_fused_row_elems,
                )

                row_elems = als_fused_row_elems(c.shape[1], rank)

                def solver(t, _cur=cur, _yty=yty):
                    return _solve_bucket_fused(
                        _cur, _yty, t[0], t[1], t[2], cfg.l2,
                        reg_nnz=cfg.reg_nnz,
                        cg_iters=cfg.cg_iters * (2 if implicit else 1),
                        implicit=implicit, alpha=cfg.alpha,
                        x0=t[3] if len(t) > 3 else None)
            elif implicit:
                def solver(t, _cur=cur, _yty=yty):
                    return _solve_bucket_implicit(
                        _cur, _yty, t[0], t[1], t[2], cfg.l2, cfg.alpha,
                        precision=cfg.precision, cg_iters=cfg.cg_iters,
                        x0=t[3] if len(t) > 3 else None,
                        cg_tol=cfg.cg_tol)
            elif cfg.use_kernel and c.shape[1] >= cfg.kernel_min_d:
                from incubator_predictionio_tpu.ops.pallas_kernels import (
                    als_padded_row_elems,
                )

                row_elems = als_padded_row_elems(c.shape[1], rank)

                def solver(t, _cur=cur):
                    return _solve_bucket_kernel(
                        _cur, t[0], t[1], t[2], cfg.l2,
                        reg_nnz=cfg.reg_nnz, cg_iters=cfg.cg_iters,
                        kernel_rows=cfg.kernel_rows,
                        x0=t[3] if len(t) > 3 else None)
            else:
                def solver(t, _cur=cur):
                    return _solve_bucket(
                        _cur, t[0], t[1], t[2], cfg.l2,
                        reg_nnz=cfg.reg_nnz,
                        compute_dtype=cfg.compute_dtype,
                        precision=cfg.precision, cg_iters=cfg.cg_iters,
                        x0=t[3] if len(t) > 3 else None,
                        cg_tol=cfg.cg_tol)
            sol = _solve_bucket_chunked(solver, c, v, m, rank,
                                        row_elems=row_elems, x0=x0)
            out = _scatter_rows_impl(out, rid, sol)
        if mixed is not None:
            _rid_m, sid_a, mc_a, mv_a, mm_a = mixed
            pg, pr, pn = _gram_rhs_nnz(
                cur, mc_a[s], mv_a[s], mm_a[s], cfg.compute_dtype,
                cfg.precision, implicit, cfg.alpha)
            sid = sid_a[s]
            mg = mg + jax.ops.segment_sum(pg, sid, num_segments=h + 1)
            mr = mr + jax.ops.segment_sum(pr, sid, num_segments=h + 1)
            mn = mn + jax.ops.segment_sum(pn, sid, num_segments=h + 1)
        if s < n - 1:
            cur = ppermute_next(cur, axes)
    if mixed is not None:
        rid_m = mixed[0]
        x0 = (_gather_x0(prev_local, rid_m)
              if prev_local is not None else None)
        sol = _reg_solve(
            mg[:h], mr[:h], mn[:h], cfg.l2, cfg.reg_nnz, implicit, yty,
            cg_iters=cfg.cg_iters,
            cg_matvec_dtype=(jnp.float32 if implicit
                             else cfg.compute_dtype),
            x0=x0, cg_tol=cfg.cg_tol)
        out = _scatter_rows_impl(out, rid_m, sol)
    return out


def _placed_half_sweep(side: str, other_local: jax.Array, data,
                       cfg: _ShardCfg, placement,
                       prev_local: Optional[jax.Array]) -> jax.Array:
    """One half-sweep of the placed program (traced, inside shard_map):
    solve the rows THIS device owns on ``side`` against the other
    side's factors, moved by the side's gather strategy."""
    from incubator_predictionio_tpu.parallel.collectives import all_gather

    mode = cfg.u_mode if side == "user" else cfg.i_mode
    fused = cfg.fused_u if side == "user" else cfg.fused_i
    rows_local = placement.shard_rows(side)
    if mode == "ring":
        return _ring_sweep_side(rows_local, other_local, data, cfg,
                                placement, prev_local, fused)
    others = all_gather(other_local, placement.axes, axis=0, tiled=True)
    tree, heavy = data
    return _sweep_side(
        rows_local, others, tree, heavy, cfg.l2, cfg.alpha, cfg.reg_nnz,
        cfg.compute_dtype, cfg.precision, cfg.implicit,
        cg_iters=cfg.cg_iters, use_kernel=cfg.use_kernel,
        kernel_min_d=cfg.kernel_min_d, kernel_rows=cfg.kernel_rows,
        prev_factors=prev_local, use_fused=fused, cg_tol=cfg.cg_tol)


def _squeeze_ring(data, mode: str):
    """Drop the sharded leading axis of a ring side's local views (the
    allgather layout is flat — each device already sees its block)."""
    if mode != "ring" or data is None:
        return data
    return jax.tree_util.tree_map(lambda a: a[0], data)


def _placed_specs(placement, u_data, i_data):
    from jax.sharding import PartitionSpec as P

    spec = P(placement.axes)
    mk = functools.partial(jax.tree_util.tree_map, lambda _: spec)
    return mk(u_data), mk(i_data)


def _placed_sweep_pair(u_loc, i_loc, u_d, i_d, cfg, placement):
    nu = _placed_half_sweep(
        "user", i_loc, u_d, cfg, placement,
        u_loc if cfg.warmstart else None)
    nv = _placed_half_sweep(
        "item", nu, i_d, cfg, placement,
        i_loc if cfg.warmstart else None)
    return nu, nv


@functools.partial(
    jax.jit, static_argnames=("placement", "cfg", "iterations"))
def _als_run_placed(uf, vf, u_data, i_data, *, placement, cfg,
                    iterations: int):
    """Fixed-budget placed training: every sweep of every shard in ONE
    dispatch (shard_map inside jit; collectives only, no host)."""
    from jax.sharding import PartitionSpec as P

    from incubator_predictionio_tpu.parallel.collectives import shard_map

    spec = P(placement.axes)

    def run(u_loc, i_loc, u_d, i_d):
        u_d = _squeeze_ring(u_d, cfg.u_mode)
        i_d = _squeeze_ring(i_d, cfg.i_mode)

        def body(_, st):
            return _placed_sweep_pair(st[0], st[1], u_d, i_d, cfg,
                                      placement)

        return jax.lax.fori_loop(0, iterations, body, (u_loc, i_loc))

    specs_u, specs_i = _placed_specs(placement, u_data, i_data)
    return shard_map(
        run, mesh=placement.mesh,
        in_specs=(spec, spec, specs_u, specs_i),
        out_specs=(spec, spec), check_rep=False,
    )(uf, vf, u_data, i_data)


def _converge_placed_impl(uf, vf, u_data, i_data, tol, placement, cfg,
                          max_sweeps: int, min_sweeps: int):
    """Traceable early-stopping placed run → (uf, vf, sweeps, delta).

    The plateau criterion is evaluated DEVICE-SIDE per sweep with the
    partial factor-delta sums reduced across shards by one psum — the
    sharded twin of :func:`_converge_impl`, still zero host syncs. Split
    out un-jitted so ops/retrain.py can fuse the O(delta) splice
    scatters into the SAME dispatch (`_converge_spliced_placed`)."""
    from jax.sharding import PartitionSpec as P

    from incubator_predictionio_tpu.parallel.collectives import (
        all_reduce_sum,
        shard_map,
    )

    spec = P(placement.axes)

    def run(u_loc, i_loc, u_d, i_d):
        u_d = _squeeze_ring(u_d, cfg.u_mode)
        i_d = _squeeze_ring(i_d, cfg.i_mode)

        def cond(carry):
            i, _u, _v, d = carry
            return jnp.logical_and(
                i < max_sweeps,
                jnp.logical_or(i < max(min_sweeps, 1), d >= tol))

        def body(carry):
            i, u, v, _d = carry
            nu, nv = _placed_sweep_pair(u, v, u_d, i_d, cfg, placement)
            num = (jnp.sum((nu - u) ** 2) + jnp.sum((nv - v) ** 2))
            den = jnp.sum(u ** 2) + jnp.sum(v ** 2)
            num = all_reduce_sum(num, placement.axes)
            den = all_reduce_sum(den, placement.axes)
            d = jnp.sqrt(num / jnp.maximum(den, 1e-30))
            return i + 1, nu, nv, d

        i, u, v, d = jax.lax.while_loop(
            cond, body, (jnp.int32(0), u_loc, i_loc, jnp.float32(jnp.inf)))
        return u, v, i, d

    specs_u, specs_i = _placed_specs(placement, u_data, i_data)
    return shard_map(
        run, mesh=placement.mesh,
        in_specs=(spec, spec, specs_u, specs_i),
        out_specs=(spec, spec, P(), P()), check_rep=False,
    )(uf, vf, u_data, i_data)


@functools.partial(
    jax.jit,
    static_argnames=("placement", "cfg", "max_sweeps", "min_sweeps"))
def _als_converge_placed(uf, vf, u_data, i_data, tol, *, placement, cfg,
                         max_sweeps: int, min_sweeps: int):
    return _converge_placed_impl(uf, vf, u_data, i_data, tol, placement,
                                 cfg, max_sweeps, min_sweeps)


def _placed_cfg(placement, rank: int, implicit: bool, reg_nnz: bool,
                l2: float, alpha: float, compute_dtype: Any,
                precision: Any, cg_iters: int,
                modes: Optional[Tuple[str, str]] = None) -> _ShardCfg:
    """Resolve every env-dependent selector OUTSIDE the trace (kernel
    probe, fused routing vs shard-local shapes, gather strategy) into
    the hashable static config of one placed run."""
    warm = _CG_WARMSTART
    if modes is None:
        modes = _shard_gather_modes(placement, rank, compute_dtype,
                                    implicit)
    fused_u, fused_i = _fused_sides_placed(
        placement, modes, implicit, warm, compute_dtype, rank)
    return _ShardCfg(
        u_mode=modes[0], i_mode=modes[1], implicit=implicit,
        reg_nnz=reg_nnz, l2=float(l2), alpha=float(alpha),
        compute_dtype=compute_dtype, precision=precision,
        cg_iters=int(cg_iters), cg_tol=_cg_tol_env(),
        use_kernel=_kernel_enabled(implicit, warm=warm),
        kernel_min_d=_KERNEL_MIN_D, kernel_rows=_kernel_rows_default(),
        warmstart=warm, fused_u=fused_u, fused_i=fused_i)


@functools.lru_cache(maxsize=32)
def _replicate_jit(sharding):
    """One compiled gather-to-replicated program per target sharding —
    cached so the profiler's collective sample never re-traces."""
    return jax.jit(
        lambda a: jax.lax.with_sharding_constraint(a, sharding))


def _profile_placed_collectives(placement, uf, vf,
                                modes: Tuple[str, str]) -> None:
    """PIO_PROFILE=1: sample the factor-gather collective under its own
    op label ``als_allgather``. The sweep's gathers execute inside the
    ONE training dispatch and cannot be timed there without breaking the
    zero-host-sync contract; this times one standalone all-gather of
    each gathered table on the same mesh (block-until-ready) — the
    per-half-sweep unit collective cost, separable in /metrics next to
    ``als_fused``/``als_sharded``. Off (the default) costs one enabled()
    check."""
    from incubator_predictionio_tpu.obs import profile as _profile

    if placement.n_shards <= 1 or not _profile.enabled():
        return
    gather = _replicate_jit(placement.replicated())
    for arr in (vf, uf):  # user sweep gathers items, item sweep users
        # untimed warm run: compile/trace cost must not book as the
        # collective's device time
        jax.block_until_ready(gather(arr))
        t0 = _profile.t0()
        out = gather(arr)
        _profile.record(t0, "train", "als_allgather", result=out)


def _book_shard_metrics(placement, cfg: _ShardCfg, rank: int,
                        sweeps: int) -> None:
    """pio_shard_* observability (booked OUTSIDE any trace)."""
    try:
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.REGISTRY
        reg.gauge(
            "pio_shard_mesh_devices",
            "devices in the active factor-table mesh",
        ).set(placement.n_shards)
        rows = reg.gauge(
            "pio_shard_rows", "factor-table rows per shard", labels=("side",))
        rows.labels(side="user").set(placement.shard_rows("user"))
        rows.labels(side="item").set(placement.shard_rows("item"))
        gb = reg.counter(
            "pio_shard_gather_bytes_total",
            "bytes moved by factor-shard collectives, by strategy",
            labels=("strategy",))
        for side, mode in (("item", cfg.u_mode), ("user", cfg.i_mode)):
            n = placement.n_shards
            if n <= 1 or not sweeps:
                continue
            if mode == "allgather":
                gb.labels(strategy="allgather").inc(
                    placement.allgather_bytes(side, sweeps, rank))
            else:
                # ring: every slice visits every device once per sweep,
                # rotated at the sweep's compute dtype (bf16 slices move
                # half the bytes of f32; implicit always rotates f32)
                rows_p = (placement.n_users_padded if side == "user"
                          else placement.n_items_padded)
                item = jnp.dtype(jnp.float32 if cfg.implicit
                                 else cfg.compute_dtype).itemsize
                gb.labels(strategy="ring").inc(
                    rows_p * rank * item * (n - 1) * sweeps)
    except Exception:  # pragma: no cover — telemetry must never fail a train
        pass


def als_train_placed(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    mesh=None,
    placement=None,
    rank: int = 64,
    iterations: int = 10,
    l2: float = 0.1,
    alpha: float = 1.0,
    seed: int = 0,
    reg_nnz: bool = True,
    implicit: bool = False,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    max_width: int = 1 << 16,
    bf16_sweeps: int = 0,
) -> ALSState:
    """Placement-aware training over the mesh → a PLACED ALSState
    (padded, tables sharded, ``state.placement`` set).

    The returned tables stay distributed for sharded serving
    (ops/topk.py per-shard merge) and sharded retrain; slice with
    ``placement.unplace_state`` when a host-shaped model is needed."""
    from incubator_predictionio_tpu.obs import profile as _profile
    from incubator_predictionio_tpu.parallel.placement import (
        make_placement,
    )

    if placement is None:
        placement = make_placement(mesh, n_users, n_items)
    modes = _shard_gather_modes(placement, rank, compute_dtype, implicit)
    u_data, i_data = build_placed_sides(
        users, items, ratings, placement, modes, max_width=max_width)
    state0 = als_init(jax.random.key(seed), n_users, n_items, rank)
    state = placement.place_state(state0)

    _prof_t0 = _profile.t0()
    lo = 0 if implicit else min(max(bf16_sweeps, 0), iterations)
    uf, vf = state.user_factors, state.item_factors
    if lo:
        cfg_lo = _placed_cfg(
            placement, rank, False, reg_nnz, l2, 0.0, jnp.bfloat16,
            jax.lax.Precision.DEFAULT,
            min(_CG_ITERS_BF16, _CG_ITERS), modes=modes)
        uf, vf = _als_run_placed(uf, vf, u_data, i_data,
                                 placement=placement, cfg=cfg_lo,
                                 iterations=lo)
    cfg = _placed_cfg(placement, rank, implicit, reg_nnz, l2, alpha,
                      compute_dtype, precision, _CG_ITERS, modes=modes)
    if iterations - lo:
        uf, vf = _als_run_placed(uf, vf, u_data, i_data,
                                 placement=placement, cfg=cfg,
                                 iterations=iterations - lo)
    out = ALSState(user_factors=uf, item_factors=vf, placement=placement)
    if _prof_t0 is not None:
        _profile.record(
            _prof_t0, "train", "als_sharded", result=out,
            flops_fn=lambda: train_flops(
                len(ratings), n_users, n_items, rank, iterations, lo))
    _profile_placed_collectives(placement, uf, vf, modes)
    # book each leg at ITS dtype: bf16 sweeps rotate bf16 ring slices
    # (half the bytes of the f32 leg)
    if lo:
        _book_shard_metrics(placement, cfg_lo, rank, lo)
    _book_shard_metrics(placement, cfg, rank, iterations - lo)
    from incubator_predictionio_tpu.ops.retrain import _book_sweeps

    _book_sweeps("fresh", iterations)
    return out


def als_train_sharded(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    mesh,                       # jax.sharding.Mesh with (dp, mp) axes
    rank: int = 64,
    iterations: int = 10,
    l2: float = 0.1,
    alpha: float = 1.0,
    seed: int = 0,
    reg_nnz: bool = True,
    implicit: bool = False,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    max_width: int = 1 << 16,
    bf16_sweeps: int = 0,
    keep_placed: bool = False,
) -> ALSState:
    """Mesh-sharded training (the ALX layout) — the historical entry,
    now a thin wrapper over :func:`als_train_placed`.

    Both factor tables shard on rows over the flattened mesh via a
    :class:`~...parallel.placement.FactorPlacement`; half-sweeps run
    under shard_map with each device solving the row buckets it owns.
    Numerics match the unsharded run up to floating-point reduction
    order. ``keep_placed=False`` (the historical contract) slices the
    result back to the true sizes; ``keep_placed=True`` returns the
    distributed state for sharded serving/retrain."""
    from incubator_predictionio_tpu.parallel.placement import (
        make_placement,
    )

    placement = make_placement(mesh, n_users, n_items)
    out = als_train_placed(
        users, items, ratings, n_users, n_items, placement=placement,
        rank=rank, iterations=iterations, l2=l2, alpha=alpha, seed=seed,
        reg_nnz=reg_nnz, implicit=implicit, compute_dtype=compute_dtype,
        precision=precision, max_width=max_width, bf16_sweeps=bf16_sweeps)
    return out if keep_placed else placement.unplace_state(out)


@jax.jit
def _predict_coo(
    user_factors: jax.Array, item_factors: jax.Array,
    users: jax.Array, items: jax.Array,
) -> jax.Array:
    return jnp.sum(user_factors[users] * item_factors[items], axis=-1)


def rmse(
    state: ALSState,
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    chunk: int = 1 << 20,
) -> float:
    """Root-mean-square error over COO ratings (evaluation metric parity with
    the reference recommendation template's eval)."""
    users = np.asarray(users, np.int32)
    items = np.asarray(items, np.int32)
    ratings = np.asarray(ratings, np.float32)
    total, n = 0.0, len(ratings)
    for s in range(0, n, chunk):
        pred = _predict_coo(
            state.user_factors, state.item_factors,
            jnp.asarray(users[s:s + chunk]), jnp.asarray(items[s:s + chunk]),
        )
        total += float(jnp.sum((pred - jnp.asarray(ratings[s:s + chunk])) ** 2))
    return float(np.sqrt(total / max(n, 1)))


# ---------------------------------------------------------------------------
# Fused whole-run training: every sweep of every bucket inside ONE jit.
#
# The per-bucket python loop above costs one device dispatch per
# solve/scatter — ~2·sweeps·buckets dispatches per training run. On a
# tunneled/remote TPU each dispatch is a host round trip, which dominates
# ML-100K-scale training (measured: ~0.6 s of a 0.6 s run). The fused path
# traces the full alternation (lax.fori_loop over sweeps; buckets unrolled
# inside the body, their shapes are static) so the whole `pio train` compute
# is ONE dispatch.
# ---------------------------------------------------------------------------

def _buckets_tree(buckets: Sequence[PaddedRows]):
    return tuple(
        (jnp.asarray(b.row_ids), jnp.asarray(b.cols), jnp.asarray(b.vals),
         jnp.asarray(b.mask))
        for b in buckets
    )


def _heavy_tree(heavy):
    if heavy is None:
        return None
    return (jnp.asarray(heavy.seg_ids), jnp.asarray(heavy.row_ids),
            jnp.asarray(heavy.cols), jnp.asarray(heavy.vals),
            jnp.asarray(heavy.mask))


def _solve_heavy(
    other_factors: jax.Array,
    heavy,                      # (seg_ids[S], row_ids[H], cols, vals, mask)
    l2: float,
    alpha: float,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    yty: Optional[jax.Array],
    cg_iters: int = _CG_ITERS,
    prev_factors: Optional[jax.Array] = None,
    cg_tol: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Partial-Gram combining solve for split rows → (row_ids, sol[H, K]).

    Per-segment normal-equation pieces are computed exactly like a regular
    bucket, then segment-summed per original row before ONE solve per row —
    the reduction ALX does across shards, here across split segments.
    ``prev_factors`` warm-starts the combining CG exactly like the bucket
    path — the heaviest rows share the reduced bf16 budget, so they need
    the warm start most."""
    seg_ids, row_ids, cols, vals, mask = heavy
    n_heavy = row_ids.shape[0]
    pg, prhs, pnnz = _gram_rhs_nnz_chunked(
        other_factors, cols, vals, mask, compute_dtype, precision,
        implicit, alpha)
    gram = jax.ops.segment_sum(pg, seg_ids, num_segments=n_heavy)
    rhs = jax.ops.segment_sum(prhs, seg_ids, num_segments=n_heavy)
    nnz = jax.ops.segment_sum(pnnz, seg_ids, num_segments=n_heavy)
    x0 = (_gather_x0(prev_factors, row_ids)
          if prev_factors is not None else None)
    return row_ids, _reg_solve(
        gram, rhs, nnz, l2, reg_nnz, implicit, yty, cg_iters=cg_iters,
        cg_matvec_dtype=jnp.float32 if implicit else compute_dtype,
        x0=x0, cg_tol=cg_tol)


@functools.partial(
    jax.jit,
    static_argnames=("iterations", "reg_nnz", "compute_dtype", "precision",
                     "implicit", "cg_iters", "use_kernel", "kernel_min_d",
                     "kernel_rows", "warmstart", "use_fused", "cg_tol"),
    donate_argnames=("state",),
)
def _als_run_fused(
    state: ALSState,
    user_tree,
    item_tree,
    l2: float,
    alpha: float,
    iterations: int,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    user_heavy=None,
    item_heavy=None,
    cg_iters: int = _CG_ITERS,
    use_kernel: bool = False,
    kernel_min_d: int = 0,
    kernel_rows: int = 1,
    warmstart: bool = False,
    use_fused: Tuple[bool, bool] = (False, False),
    cg_tol: float = 0.0,
) -> ALSState:
    def body(_, st):
        new_users = _sweep_side(
            st.user_factors.shape[0], st.item_factors, user_tree, user_heavy,
            l2, alpha, reg_nnz, compute_dtype, precision, implicit,
            cg_iters=cg_iters, use_kernel=use_kernel,
            kernel_min_d=kernel_min_d, kernel_rows=kernel_rows,
            prev_factors=st.user_factors if warmstart else None,
            use_fused=use_fused[0], cg_tol=cg_tol)
        new_items = _sweep_side(
            st.item_factors.shape[0], new_users, item_tree, item_heavy,
            l2, alpha, reg_nnz, compute_dtype, precision, implicit,
            cg_iters=cg_iters, use_kernel=use_kernel,
            kernel_min_d=kernel_min_d, kernel_rows=kernel_rows,
            prev_factors=st.item_factors if warmstart else None,
            use_fused=use_fused[1], cg_tol=cg_tol)
        return ALSState(user_factors=new_users, item_factors=new_items)

    return jax.lax.fori_loop(0, iterations, body, state)


def _rel_delta(prev: ALSState, new: ALSState) -> jax.Array:
    """Relative Frobenius factor movement of one sweep → f32 scalar.

    THE plateau criterion of the convergence early-stop: ‖new − prev‖_F
    over ‖prev‖_F across both sides. Scale-free, so one tolerance serves
    every rank/λ/dataset, and an O(rows·K) reduction — noise next to a
    sweep's Gram streams."""
    num = (jnp.sum((new.user_factors - prev.user_factors) ** 2)
           + jnp.sum((new.item_factors - prev.item_factors) ** 2))
    den = (jnp.sum(prev.user_factors ** 2)
           + jnp.sum(prev.item_factors ** 2))
    return jnp.sqrt(num / jnp.maximum(den, 1e-30))


def _converge_impl(
    state: ALSState,
    user_tree,
    item_tree,
    l2: float,
    alpha: float,
    tol,                        # f32 operand — NOT static (no recompiles)
    max_sweeps: int,
    min_sweeps: int,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    user_heavy=None,
    item_heavy=None,
    cg_iters: int = _CG_ITERS,
    use_kernel: bool = False,
    kernel_min_d: int = 0,
    kernel_rows: int = 1,
    warmstart: bool = False,
    use_fused: Tuple[bool, bool] = (False, False),
    cg_tol: float = 0.0,
) -> Tuple[ALSState, jax.Array, jax.Array]:
    """Traced body of :func:`_als_run_converge` — split out so
    ops/retrain.py can fuse the O(delta) plan splice into the SAME
    dispatch (`_converge_spliced`: scatter the tail entries into the
    resident trees, then run this loop, all inside one jit)."""
    def sweep(st):
        new_users = _sweep_side(
            st.user_factors.shape[0], st.item_factors, user_tree, user_heavy,
            l2, alpha, reg_nnz, compute_dtype, precision, implicit,
            cg_iters=cg_iters, use_kernel=use_kernel,
            kernel_min_d=kernel_min_d, kernel_rows=kernel_rows,
            prev_factors=st.user_factors if warmstart else None,
            use_fused=use_fused[0], cg_tol=cg_tol)
        new_items = _sweep_side(
            st.item_factors.shape[0], new_users, item_tree, item_heavy,
            l2, alpha, reg_nnz, compute_dtype, precision, implicit,
            cg_iters=cg_iters, use_kernel=use_kernel,
            kernel_min_d=kernel_min_d, kernel_rows=kernel_rows,
            prev_factors=st.item_factors if warmstart else None,
            use_fused=use_fused[1], cg_tol=cg_tol)
        return ALSState(user_factors=new_users, item_factors=new_items)

    def cond(carry):
        i, _st, d = carry
        return jnp.logical_and(
            i < max_sweeps,
            jnp.logical_or(i < max(min_sweeps, 1), d >= tol))

    def body(carry):
        i, st, _d = carry
        new = sweep(st)
        return i + 1, new, _rel_delta(st, new)

    i, st, d = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), state, jnp.float32(jnp.inf)))
    return st, i, d


@functools.partial(
    jax.jit,
    static_argnames=("max_sweeps", "min_sweeps", "reg_nnz", "compute_dtype",
                     "precision", "implicit", "cg_iters", "use_kernel",
                     "kernel_min_d", "kernel_rows", "warmstart", "use_fused",
                     "cg_tol"),
    donate_argnames=("state",),
)
def _als_run_converge(
    state: ALSState,
    user_tree,
    item_tree,
    l2: float,
    alpha: float,
    tol,                        # f32 operand — NOT static (no recompiles)
    max_sweeps: int,
    min_sweeps: int,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    implicit: bool,
    user_heavy=None,
    item_heavy=None,
    cg_iters: int = _CG_ITERS,
    use_kernel: bool = False,
    kernel_min_d: int = 0,
    kernel_rows: int = 1,
    warmstart: bool = False,
    use_fused: Tuple[bool, bool] = (False, False),
    cg_tol: float = 0.0,
) -> Tuple[ALSState, jax.Array, jax.Array]:
    """Early-stopping fused run → (state, sweeps_run, last_delta).

    ``lax.while_loop`` evaluates the plateau criterion (:func:`_rel_delta`
    below ``tol``) DEVICE-SIDE every sweep, so the whole run is still one
    dispatch and no per-sweep host sync exists (the `host-sync` lint
    rule's contract). Floor: at least ``min_sweeps`` full sweep pairs
    (and always ≥ 1 — the loop must produce a delta before it can judge
    one); ceiling: the fixed ``max_sweeps`` budget. The returned
    ``sweeps_run``/``last_delta`` are device scalars — callers fetch them
    ONCE after the run (one sync per train, not per sweep). Calling with
    ``min_sweeps == max_sweeps`` runs exactly that many sweeps and hands
    back the last delta: the chunked-probe building block of the unfused
    path (ops/retrain.py)."""
    return _converge_impl(
        state, user_tree, item_tree, l2, alpha, tol, max_sweeps,
        min_sweeps, reg_nnz, compute_dtype, precision, implicit,
        user_heavy=user_heavy, item_heavy=item_heavy, cg_iters=cg_iters,
        use_kernel=use_kernel, kernel_min_d=kernel_min_d,
        kernel_rows=kernel_rows, warmstart=warmstart, use_fused=use_fused,
        cg_tol=cg_tol)


def train_flops(
    nnz: int,
    n_users: int,
    n_items: int,
    rank: int,
    iterations: int,
    bf16_sweeps: int = 0,
    solver: Optional[str] = None,
    cg_iters: Optional[int] = None,
    cg_iters_bf16: Optional[int] = None,
    warmstart: Optional[bool] = None,
) -> float:
    """THE analytic FLOP count of one training run — the single formula
    the bench's offline MFU and the live ``pio_mfu{phase="train"}``
    gauge (obs/profile.py) both divide by, so the two figures agree by
    construction when the measured walls agree.

    Per half-sweep over ``nnz`` observations at rank K: the Gram batch
    is 2·nnz·K² MACs = 4·nnz·K² FLOPs at HIGHEST precision (the f32
    multi-pass costs ~3× a bf16 pass; counted at face value —
    conservative), the rhs 2·nnz·K, and each row's CG solve
    ~iters·2·K² FLOPs (about the same count as a direct K³/3 Cholesky
    at K=128, iters=32; bf16 sweeps run the loose ``_CG_ITERS_BF16``
    budget, polish sweeps the full one, warm starts pay one extra
    matvec). Both sides per sweep, ``iterations`` sweeps. Counts USEFUL
    work only — padding waste shows up as lower MFU, not higher FLOPs.
    """
    k = float(rank)
    nnz = float(nnz)
    solver = _SOLVER if solver is None else solver
    cg_iters = _CG_ITERS if cg_iters is None else int(cg_iters)
    cg_iters_bf16 = (_CG_ITERS_BF16 if cg_iters_bf16 is None
                     else int(cg_iters_bf16))
    warmstart = _CG_WARMSTART if warmstart is None else bool(warmstart)
    per_side_gram = 2.0 * nnz * k * k * 2.0   # multiply+add
    per_side_rhs = 2.0 * nnz * k
    if solver == "cg":
        bf16 = min(max(int(bf16_sweeps), 0), int(iterations))
        iters = (bf16 * min(cg_iters_bf16, cg_iters)
                 + (int(iterations) - bf16) * cg_iters) / max(
                     int(iterations), 1)
        if warmstart:
            iters += 1.0  # the warm start's initial-residual matvec
        per_solve = iters * 2.0 * k * k
    else:
        per_solve = k ** 3 / 3.0 + 2.0 * k * k
    solves = (int(n_users) + int(n_items)) * per_solve
    per_sweep = 2.0 * per_side_gram + 2.0 * per_side_rhs + solves
    return per_sweep * int(iterations)


def tree_nnz(tree, heavy=None) -> int:
    """Observed interaction count of one side's device trees — mask
    sums, so it costs a few device reduces + fetches. Only the
    PIO_PROFILE=1 path calls this (the profiler is already blocking on
    walls); production training never pays it."""
    total = 0.0
    for _row_ids, _cols, _vals, mask in tree:
        total += float(jnp.sum(mask))
    if heavy is not None:
        total += float(jnp.sum(heavy[4]))
    return int(total)


def _mixed_run(
    state: ALSState,
    u_tree,
    i_tree,
    l2: float,
    iterations: int,
    bf16_sweeps: int,
    reg_nnz: bool,
    compute_dtype: Any,
    precision: Any,
    user_heavy,
    item_heavy,
    use_kernel: Optional[bool] = None,
    kernel_min_d: Optional[int] = None,
    kernel_rows: Optional[int] = None,
    warmstart: Optional[bool] = None,
    use_fused: "Optional[Tuple[bool, bool]]" = None,
) -> ALSState:
    """Mixed-precision schedule: ``bf16_sweeps`` early sweeps with bf16
    gathers + single-pass MXU matmuls (DEFAULT precision), then the
    remaining sweeps at (compute_dtype, precision) — the f32 HIGHEST
    polish that restores full convergence. Two fused dispatches instead
    of one; explicit feedback only (implicit confidences stay f32).

    Why this is safe: ALS re-solves every factor row from scratch each
    half-sweep (the state is not incrementally perturbed), so low-precision
    early sweeps only affect the *starting point* of the f32 polish — the
    polish sweeps land on the same fixed point (validated by the planted
    low-rank recovery test, tests/test_als.py)."""
    from incubator_predictionio_tpu.obs import profile as _profile

    _prof_t0 = _profile.t0()
    lo = min(max(bf16_sweeps, 0), iterations)
    # resolve the Pallas selector HERE (python level, outside any trace —
    # the Mosaic probe runs a real kernel). Callers pass False explicitly
    # on the mesh-sharded path: pallas_call does not auto-partition under
    # GSPMD, so the sharded program keeps the XLA assembly.
    if warmstart is None:
        warmstart = _CG_WARMSTART
    if use_kernel is None:
        # probe the exact variant this run dispatches (warm adds the x0
        # operand — a different kernel), honoring per-call overrides
        use_kernel = _kernel_enabled(False, warm=bool(warmstart))
    if kernel_min_d is None:
        kernel_min_d = _KERNEL_MIN_D
    if kernel_rows is None:
        kernel_rows = _kernel_rows_default()
    n_u = state.user_factors.shape[0]
    n_i = state.item_factors.shape[0]
    rank = state.user_factors.shape[1]
    cg_tol = _cg_tol_env()

    def fused_for(dtype):
        # per-leg: the VMEM fit depends on the gather table's dtype
        # (a bf16 table is half the f32 footprint)
        if use_fused is not None:
            return use_fused
        if not use_kernel:
            return (False, False)
        return _fused_sides(n_u, n_i, False, bool(warmstart), dtype, rank)

    if lo:
        state = _als_run_fused(
            state, u_tree, i_tree, l2, 0.0, lo, reg_nnz,
            jnp.bfloat16, jax.lax.Precision.DEFAULT, implicit=False,
            user_heavy=user_heavy, item_heavy=item_heavy,
            cg_iters=min(_CG_ITERS_BF16, _CG_ITERS),
            use_kernel=use_kernel, kernel_min_d=kernel_min_d,
            kernel_rows=kernel_rows, warmstart=warmstart,
            use_fused=fused_for(jnp.bfloat16), cg_tol=cg_tol,
        )
    if iterations - lo:
        state = _als_run_fused(
            state, u_tree, i_tree, l2, 0.0, iterations - lo, reg_nnz,
            compute_dtype, precision, implicit=False,
            user_heavy=user_heavy, item_heavy=item_heavy,
            use_kernel=use_kernel, kernel_min_d=kernel_min_d,
            kernel_rows=kernel_rows, warmstart=warmstart,
            use_fused=fused_for(compute_dtype), cg_tol=cg_tol,
        )
    if _prof_t0 is not None:
        # PIO_PROFILE=1: attribute the device wall + analytic FLOPs of
        # this run (blocks on the final state — the profiler's
        # contract). flops_fn defers the tree mask sums until AFTER the
        # wall is captured, so their dispatches/fetches never
        # contaminate the measured device time. Kernel-path runs book
        # under their own op label (`als_fused`) so /metrics separates
        # the fused Gram+solve trajectory from the XLA assembly —
        # `als.train_flops` stays the ONE FLOP formula for both, so
        # pio_mfu{phase="train"} is comparable across the op split.
        _profile.record(
            _prof_t0, "train", "als_fused" if use_kernel else "als_train",
            result=state,
            flops_fn=lambda: train_flops(
                tree_nnz(u_tree, user_heavy),
                state.user_factors.shape[0], state.item_factors.shape[0],
                state.user_factors.shape[1], iterations, lo,
                warmstart=warmstart))
    return state


def als_train(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    rank: int = 64,
    iterations: int = 10,
    l2: float = 0.1,
    seed: int = 0,
    reg_nnz: bool = True,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    max_width: int = 1 << 16,
    track_rmse: bool = False,
    bf16_sweeps: int = 0,
) -> Tuple[ALSState, List[float]]:
    """Full training: build padded buckets once, run ``iterations`` sweeps.

    Rows whose degree exceeds ``max_width`` are split into segments and
    solved via the partial-Gram combining path (ops/sparse.py
    ``split_heavy`` + ``_solve_heavy``), so power users/items of any degree
    train correctly."""
    (user_light, user_heavy), (item_light, item_heavy) = build_both_sides(
        users, items, ratings, n_users, n_items, max_width=max_width)
    u_tree, i_tree = _buckets_tree(user_light), _buckets_tree(item_light)
    u_hv, i_hv = _heavy_tree(user_heavy), _heavy_tree(item_heavy)

    state = als_init(jax.random.key(seed), n_users, n_items, rank)
    history: List[float] = []
    if track_rmse:
        # per-sweep metric needs per-sweep dispatches
        for sweep in range(iterations):
            state = _mixed_run(
                state, u_tree, i_tree, l2, 1,
                1 if sweep < bf16_sweeps else 0,
                reg_nnz, compute_dtype, precision,
                user_heavy=u_hv, item_heavy=i_hv,
            )
            history.append(rmse(state, users, items, ratings))
    else:
        state = _mixed_run(
            state, u_tree, i_tree, l2, iterations, bf16_sweeps,
            reg_nnz, compute_dtype, precision,
            user_heavy=u_hv, item_heavy=i_hv,
        )
    # obs bridge: the sweep counter books for fresh trains too, so
    # /metrics' fresh-vs-continue split stays meaningful (lazy import —
    # ops.retrain imports this module)
    from incubator_predictionio_tpu.ops.retrain import _book_sweeps

    _book_sweeps("fresh", iterations)
    return state, history
