"""Multinomial logistic regression via optax — the second classification
algorithm (the reference's add-algorithm template pairs NaiveBayes with a
second MLlib model, examples/scala-parallel-classification/add-algorithm/;
BASELINE.json designates optax LogReg as the TPU-native counterpart).

The whole optimization loop runs inside one jit via ``lax.scan`` —
no per-step Python dispatch, full-batch gradients on the MXU.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
import optax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LogRegModel:
    weights: Any   # [D, C]
    bias: Any      # [C]


@functools.partial(
    jax.jit, static_argnames=("n_classes", "steps", "learning_rate", "l2")
)
def logreg_fit(
    features: jax.Array,    # [N, D] f32
    labels: jax.Array,      # [N] int32
    n_classes: int,
    steps: int = 300,
    learning_rate: float = 0.1,
    l2: float = 1e-4,
) -> LogRegModel:
    d = features.shape[1]
    params = LogRegModel(
        weights=jnp.zeros((d, n_classes), jnp.float32),
        bias=jnp.zeros((n_classes,), jnp.float32),
    )
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    def loss_fn(p: LogRegModel) -> jax.Array:
        logits = features @ p.weights + p.bias
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        return ce.mean() + l2 * jnp.sum(p.weights ** 2)

    def step(carry, _):
        p, s = carry
        grads = jax.grad(loss_fn)(p)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), None

    (params, _), _ = jax.lax.scan(step, (params, opt_state), None, length=steps)
    return params


@jax.jit
def logreg_predict(model: LogRegModel, features: jax.Array) -> jax.Array:
    return jnp.argmax(features @ model.weights + model.bias, axis=-1)


@jax.jit
def logreg_proba(model: LogRegModel, features: jax.Array) -> jax.Array:
    return jax.nn.softmax(features @ model.weights + model.bias, axis=-1)


def logreg_accuracy(model: LogRegModel, features: np.ndarray,
                    labels: np.ndarray) -> float:
    pred = np.asarray(logreg_predict(model, jnp.asarray(features)))
    return float((pred == np.asarray(labels)).mean())
