"""Background MIPS index rebuild daemon.

One process-wide thread (hosted by the prediction server next to the
overlay poller, refcounted so worker + admin embedding both work) that
watches every registered index and re-clusters OFF the serving path
when a trigger fires:

* ``tail``    — virtual-id tail entries (overlay-published new keys
                served by exact host scan) passed
                ``PIO_MIPS_REBUILD_TAIL`` (default 4096): the exact
                tail is O(tail·K) per query, so it must stay bounded.
* ``age``     — the index is older than ``PIO_MIPS_REBUILD_AGE_S``
                (default 900 s) AND has something to fold (a tail,
                churned rows, or cold-tier pressure). A quiet index
                never rebuilds on age alone.
* ``churn``   — rows published/delta-updated since the last build
                passed ``PIO_MIPS_REBUILD_CHURN`` (default 65536):
                accumulated in-place requantization drifts bucket
                geometry even when the tail stays small.
* ``promote`` — probe pressure on host-tiered cold buckets passed
                ``PIO_MIPS_TIER_PROMOTE_HITS`` (default 64): the
                working set shifted, bring those rows back to device.

Every rebuild is booked under its own trace ID via
:func:`obs.trace.log_stage_span` (span ``mips_rebuild``) like every
other actuation in this repo, counted in
``pio_mips_rebuilds_total{trigger}``, and swapped in atomically by
:func:`ops.mips.rebuild_index` — the overlay ``adopt_keys``
choreography means published ids survive and a publish that races the
swap re-routes to the successor. Serving never blocks: queries on the
old index object finish on the old arrays.

The daemon only ever READS its knob envs (they are KnobController
actuation surface — writing them here would dodge the audit trail).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_POLL_S_DEFAULT = 5.0
_STATS_RING = 8


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def tail_trigger_rows() -> int:
    return max(_env_int("PIO_MIPS_REBUILD_TAIL", 4096), 1)


def age_trigger_s() -> float:
    return max(_env_float("PIO_MIPS_REBUILD_AGE_S", 900.0), 1.0)


def churn_trigger_rows() -> int:
    return max(_env_int("PIO_MIPS_REBUILD_CHURN", 65536), 1)


def promote_trigger_hits() -> int:
    return max(_env_int("PIO_MIPS_TIER_PROMOTE_HITS", 64), 1)


def _poll_s() -> float:
    return max(_env_float("PIO_MIPS_REBUILD_POLL_S", _POLL_S_DEFAULT),
               0.05)


def check_trigger(index: Any) -> Optional[str]:
    """Which trigger (if any) fires for ``index`` right now — pure
    read, shared by the daemon loop and tests."""
    from incubator_predictionio_tpu.ops import mips

    tail = index.tail_virtual_size()
    if tail >= tail_trigger_rows():
        return "tail"
    if index.churn_rows >= churn_trigger_rows():
        return "churn"
    if (index.cold is not None
            and int(index.cold.hits.sum()) >= promote_trigger_hits()):
        return "promote"
    age = mips._now() - index.built_at
    if age >= age_trigger_s() and (
            tail or index.churn_rows or index.cold is not None):
        return "age"
    return None


class _RebuildDaemon:
    def __init__(self) -> None:
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._refs = 0
        self.rebuilds = 0
        self.failures = 0
        self.last: List[Dict[str, Any]] = []

    # -- lifecycle ----------------------------------------------------------
    def acquire(self) -> None:
        with self._lock:
            self._refs += 1
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="mips-rebuild-daemon",
                    daemon=True)
                self._thread.start()
                logger.info("mips rebuild daemon started")

    def release(self) -> None:
        with self._lock:
            self._refs = max(self._refs - 1, 0)
            if self._refs:
                return
            self._stop.set()
            self._wake.set()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)
            logger.info("mips rebuild daemon stopped")

    def running(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def notify(self) -> None:
        """Publish-side nudge (overlay fold-in) — the daemon re-checks
        triggers now instead of at the next poll tick."""
        self._wake.set()

    # -- the loop -----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=_poll_s())
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.sweep()
            except Exception:
                # the daemon must survive anything a rebuild throws —
                # a dead daemon is exactly the runbook's "tail climbs
                # forever" failure mode
                with self._lock:
                    self.failures += 1
                logger.exception("mips rebuild sweep failed")

    def sweep(self, honor_stop: bool = True) -> int:
        """One pass over every registered index; returns rebuilds.

        ``honor_stop=False`` is the synchronous entry (``sweep_now``):
        ``_stop`` stays set after the last ``release()``, and a caller
        sweeping on its own thread must not be silenced by a daemon
        that merely isn't running.
        """
        from incubator_predictionio_tpu.ops import mips

        done = 0
        for table, index in mips.registered_tables():
            if honor_stop and self._stop.is_set():
                break
            trigger = check_trigger(index)
            if trigger is None:
                continue
            done += int(self._rebuild_one(table, index, trigger))
        return done

    def _rebuild_one(self, table: Any, index: Any,
                     trigger: str) -> bool:
        from incubator_predictionio_tpu.obs.trace import (
            log_stage_span,
            new_trace_id,
        )
        from incubator_predictionio_tpu.ops import mips

        trace_id = new_trace_id()
        t0 = time.perf_counter()
        try:
            new = mips.rebuild_index(table, trigger=trigger)
        except Exception:
            with self._lock:
                self.failures += 1
            logger.exception("mips rebuild (%s) failed", trigger)
            return False
        dur = time.perf_counter() - t0
        if new is None:       # sharded / unregistered — not daemon work
            return False
        record = {
            "traceId": trace_id,
            "trigger": trigger,
            "engine": new.engine,
            "durationSec": round(dur, 3),
            "ext": new.n_ext,
            "deviceRows": new.tier_rows()[0],
            "hostRows": new.tier_rows()[1],
        }
        with self._lock:
            self.rebuilds += 1
            self.last.append(record)
            del self.last[:-_STATS_RING]
        log_stage_span("mips_rebuild", trace_id, dur, trigger=trigger,
                       engine=new.engine, ext=new.n_ext,
                       host_rows=new.tier_rows()[1])
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            rebuilds, failures = self.rebuilds, self.failures
            last = list(self.last)
        return {
            "running": self.running(),
            "rebuilds": rebuilds,
            "failures": failures,
            "tailTrigger": tail_trigger_rows(),
            "ageTriggerSec": age_trigger_s(),
            "churnTrigger": churn_trigger_rows(),
            "last": last,
        }


_DAEMON = _RebuildDaemon()


def acquire() -> None:
    """Refcounted start (prediction server load path)."""
    _DAEMON.acquire()


def release() -> None:
    """Refcounted stop (prediction server shutdown)."""
    _DAEMON.release()


def notify_publish() -> None:
    """Overlay fold-in handoff: published rows may have pushed the tail
    past its trigger — wake the daemon without waiting a poll tick."""
    _DAEMON.notify()


def running() -> bool:
    return _DAEMON.running()


def stats() -> Dict[str, Any]:
    """The ``mipsDaemon`` block of the prediction server's /status."""
    return _DAEMON.stats()


def sweep_now() -> int:
    """Synchronous trigger check + rebuilds (tests, bench): same code
    path as the daemon loop, caller's thread — works whether or not
    the background daemon is running."""
    return _DAEMON.sweep(honor_stop=False)
