"""Multinomial Naive Bayes — the MLlib NaiveBayes replacement.

The reference classification template trains ``mllib.NaiveBayes`` on small
numeric feature vectors (examples/scala-parallel-classification/
add-algorithm/src/main/scala/NaiveBayesAlgorithm.scala). Fit is one pass of
segment-sums over the device (one scatter-add per class), predict is a
single matmul + argmax — both MXU/VPU-shaped.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NaiveBayesModel:
    """log-prior pi [C] and log-likelihood theta [C, D] (MLlib layout)."""

    pi: Any
    theta: Any


@functools.partial(jax.jit, static_argnames=("n_classes", "lambda_"))
def nb_fit(
    features: jax.Array,     # [N, D] non-negative counts/values
    labels: jax.Array,       # [N] int32 in [0, n_classes)
    n_classes: int,
    lambda_: float = 1.0,
) -> NaiveBayesModel:
    """Multinomial NB with Laplace smoothing (MLlib semantics)."""
    n, d = features.shape
    one_hot = jax.nn.one_hot(labels, n_classes, dtype=features.dtype)  # [N, C]
    class_counts = one_hot.sum(axis=0)                                 # [C]
    pi = jnp.log(class_counts + lambda_) - jnp.log(n + n_classes * lambda_)
    feature_sums = one_hot.T @ features                                # [C, D]
    theta = jnp.log(feature_sums + lambda_) - jnp.log(
        feature_sums.sum(axis=1, keepdims=True) + d * lambda_
    )
    return NaiveBayesModel(pi=pi, theta=theta)


@jax.jit
def nb_log_scores(model: NaiveBayesModel, features: jax.Array) -> jax.Array:
    """[B, D] → [B, C] joint log-scores."""
    return features @ model.theta.T + model.pi[None, :]


@jax.jit
def nb_predict(model: NaiveBayesModel, features: jax.Array) -> jax.Array:
    """[B, D] → [B] predicted class ids."""
    return jnp.argmax(nb_log_scores(model, features), axis=-1)


def nb_accuracy(model: NaiveBayesModel, features: np.ndarray,
                labels: np.ndarray) -> float:
    pred = np.asarray(nb_predict(model, jnp.asarray(features)))
    return float((pred == np.asarray(labels)).mean())
