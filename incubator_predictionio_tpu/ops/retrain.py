"""Continuation retrain — O(delta) steady-state training.

The reference's Lambda loop re-runs `pio train` from zero on every
refresh; the traincache tail fold (data/storage/traincache.py) already
made the *scan* O(delta). This module makes the rest of the retrain wall
scale with the event delta too:

1. **Factor continuation** (`ops/als.continue_state`): the traincache
   fold interns ids in stable first-seen order, so the previous model's
   factor rows map onto the new index space as an exact prefix —
   retraining seeds from them (device-side prefix copy) with random
   rows appended for new ids only.
2. **Convergence early-stop** (`ops/als._als_run_converge`): a warm
   start converts directly into fewer sweeps only if the sweep budget is
   adaptive — the fused path evaluates a relative-factor-delta plateau
   criterion device-side inside ``lax.while_loop`` (no per-sweep host
   sync; the `host-sync` lint contract), floored at one full sweep pair
   and ceilinged at the fixed budget. The unfused path runs
   ``PIO_RETRAIN_PROBE_EVERY``-sweep fused chunks and fetches the
   in-trace delta once per chunk (the chunked probe).
3. **Prep/plan reuse** (:class:`PrepPlan`): the degree histograms and
   the padded bucket plan persist across retrains (process-resident,
   keyed on the caller's plan key + a COO prefix digest). When only a
   tail was appended, rows whose degree class is unchanged get their new
   entries spliced into their existing padded slots — host-side in
   place, device-side via pointwise scatters whose H2D payload is
   O(delta) — and only rows that moved width class (or appeared) are
   rebuilt, as small appended delta buckets. Unchanged buckets keep
   their device trees resident across retrains.

Correctness never depends on the reuse: any shape the plan cannot prove
equivalent (prefix digest mismatch — e.g. the preparator's
latest-wins dedup dropped an interior row — deletes, heavy/split rows,
a row outgrowing ``max_width``) falls back to the fresh
``build_both_sides`` path, which is byte-identical to a cold train.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.ops import als
from incubator_predictionio_tpu.ops.sparse import (
    PaddedRows,
    build_both_sides,
    build_padded_rows,
)

logger = logging.getLogger(__name__)


def continue_enabled() -> bool:
    """`PIO_RETRAIN_CONTINUE` (default on) — read per call, never frozen
    at import (the env-import lint contract)."""
    return os.environ.get("PIO_RETRAIN_CONTINUE", "1") not in (
        "0", "off", "false")


def retrain_tol() -> float:
    """Plateau tolerance for the early-stop (relative factor delta per
    sweep). 0 disables early stop (fixed budget).

    Default 2e-2 is the measured plateau knee: on the planted bench
    workload a warm continuation's per-sweep delta falls under 2e-2 by
    sweep ~2-4 while its fit RMSE is already flat (0.2715 vs 0.2693
    after the full 10-sweep budget — inside any noise floor), whereas a
    FRESH run's delta stays above 3e-2 for its whole budget — so the
    criterion cuts warm retrains hard without truncating cold trains
    (docs/performance.md "Steady-state retrain")."""
    return float(os.environ.get("PIO_RETRAIN_TOL", "2e-2"))


def retrain_min_sweeps() -> int:
    return max(int(os.environ.get("PIO_RETRAIN_MIN_SWEEPS", "1")), 1)


def retrain_probe_every() -> int:
    return max(int(os.environ.get("PIO_RETRAIN_PROBE_EVERY", "2")), 1)


def _fused_early_stop() -> bool:
    """1 (default): device-side lax.while_loop plateau; 0: host loop of
    probe-sized fused chunks (one sync per chunk, never per sweep)."""
    return os.environ.get("PIO_RETRAIN_FUSED", "1") not in (
        "0", "off", "false")


def plan_reuse_enabled() -> bool:
    return os.environ.get("PIO_RETRAIN_PLAN", "1") not in (
        "0", "off", "false")


# ---------------------------------------------------------------------------
# prep/plan reuse
# ---------------------------------------------------------------------------

def _coo_digest(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                upto: int) -> bytes:
    """Digest of the first ``upto`` COO triplets — the prefix-equality
    witness. O(upto) memory-bandwidth work (~0.3 s at 20M rows), paid
    once per retrain to make reuse unconditionally safe."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.ascontiguousarray(rows[:upto], np.int64).tobytes())
    h.update(np.ascontiguousarray(cols[:upto], np.int64).tobytes())
    h.update(np.ascontiguousarray(vals[:upto], np.float32).tobytes())
    return h.digest()


def _width_classes(deg: np.ndarray, min_width: int) -> np.ndarray:
    """Power-of-two bucket ceiling per row (0 for absent rows) — must
    match build_padded_rows' width assignment exactly."""
    d = np.maximum(deg, 1).astype(np.float64)
    w = (1 << np.ceil(np.log2(d)).astype(np.int64)).astype(np.int64)
    w = np.maximum(w, min_width)
    return np.where(deg > 0, w, 0)


@jax.jit
def _set_entries(arr: jax.Array, pos: jax.Array, slot: jax.Array,
                 val: jax.Array) -> jax.Array:
    """Pointwise in-place splice of tail entries into a resident device
    bucket: the H2D payload is the three O(delta) index/value vectors,
    never the bucket itself."""
    return arr.at[pos, slot].set(val)


def _pad_pow2(arr: np.ndarray, fill) -> np.ndarray:
    """Pad a 1-D splice vector to the next power of two (min 8) so the
    spliced-converge jit sees a BOUNDED set of shapes across retrains
    (log2 many per bucket, not one per delta size). ``fill`` for index
    vectors is an out-of-range sentinel that ``mode="drop"`` scatters
    ignore."""
    n = len(arr)
    target = max(8, 1 << max(n - 1, 0).bit_length())
    if target == n:
        return arr
    return np.concatenate(
        [arr, np.full(target - n, fill, arr.dtype)])


@jax.jit
def _clear_rows(cols, vals, mask, row_ids, pos):
    """Detach rows that moved to another width class: padding semantics
    (row_id −1, zero mask) exactly like ``PaddedRows.pad_rows_to``."""
    return (cols.at[pos].set(0), vals.at[pos].set(0.0),
            mask.at[pos].set(0.0), row_ids.at[pos].set(-1))


@dataclasses.dataclass
class _SidePlan:
    """One training orientation's bucket plan (host mirror + device
    trees). The host arrays are the mutable source of truth; the device
    tuples mirror them bucket-for-bucket."""

    n_rows: int
    degrees: np.ndarray                 # int64[n_rows]
    buckets: List[PaddedRows]           # host mirror, spliced in place
    trees: List[Tuple[Any, Any, Any, Any]]  # device (row_ids, cols, vals, mask)
    row_bucket: np.ndarray              # int32[n_rows], -1 = absent
    row_pos: np.ndarray                 # int32[n_rows]
    min_width: int = 8
    #: mesh-sharded plans (FactorPlacement layout): buckets are
    #: shard-blocked (rows grouped into n_shards equal contiguous
    #: blocks, parallel/sharding.py), device trees carry SHARD-LOCAL row
    #: ids and are device_put with the table sharding. All the splice
    #: bookkeeping is flat-position based and therefore layout-blind:
    #: a stay-row's (pos, slot) scatter lands on the owning shard by
    #: construction (GSPMD routes the pointwise update to the device
    #: holding that block).
    n_shards: int = 1
    shard_rows: int = 0
    put_sharding: Any = None            # NamedSharding | None
    #: compaction bookkeeping: cleared (moved-away) slots never shrink a
    #: bucket and every retrain may append delta buckets — past these
    #: thresholds apply_tail refuses and the caller rebuilds a compact
    #: fresh plan, bounding creep across long retrain sequences
    dead_rows: int = 0
    init_buckets: int = 0
    #: deferred device-splice specs from the last ``apply_tail(defer=
    #: True)``: per-bucket ``None | (clear_pos, (pos, slot, cols, vals))``
    #: with pow2-padded device arrays (puts already issued — the
    #: double-buffered H2D), consumed by retrain's spliced converge
    pending: Optional[List[Any]] = None

    def _tree_of(self, b: PaddedRows):
        """Host bucket → device tree: shard-local ids + table sharding
        for sharded plans, the plain single-chip tree otherwise."""
        if self.n_shards > 1:
            from incubator_predictionio_tpu.parallel.sharding import (
                localize_tree,
            )

            t = localize_tree([b], self.n_shards, self.shard_rows)[0]
        else:
            t = als._buckets_tree([b])[0]
        if self.put_sharding is not None:
            t = tuple(jax.device_put(a, self.put_sharding) for a in t)
        return t

    def _build_delta(self, rows, cols, vals, n_rows, max_width,
                     row_multiple) -> List[PaddedRows]:
        delta = build_padded_rows(
            rows, cols, vals, n_rows, min_width=self.min_width,
            max_width=max_width, row_multiple=row_multiple)
        if self.n_shards > 1:
            from incubator_predictionio_tpu.parallel.sharding import (
                shard_block_buckets,
            )

            delta = shard_block_buckets(delta, self.n_shards,
                                        self.shard_rows)
        return delta

    @staticmethod
    def build(buckets: List[PaddedRows], degrees: np.ndarray,
              n_rows: int, min_width: int = 8, n_shards: int = 1,
              shard_rows: int = 0, put_sharding: Any = None) -> "_SidePlan":
        row_bucket = np.full(n_rows, -1, np.int32)
        row_pos = np.full(n_rows, -1, np.int32)
        for bi, b in enumerate(buckets):
            ids = np.asarray(b.row_ids)
            live = np.flatnonzero(ids >= 0)
            row_bucket[ids[live]] = bi
            row_pos[ids[live]] = live.astype(np.int32)
        plan = _SidePlan(
            n_rows=n_rows, degrees=np.asarray(degrees, np.int64),
            buckets=list(buckets), trees=[],
            row_bucket=row_bucket, row_pos=row_pos, min_width=min_width,
            init_buckets=len(buckets), n_shards=n_shards,
            shard_rows=shard_rows, put_sharding=put_sharding)
        plan.trees = [plan._tree_of(b) for b in buckets]
        return plan

    def _grow_to(self, n_rows: int) -> None:
        if n_rows > self.n_rows:
            pad = n_rows - self.n_rows
            self.degrees = np.concatenate(
                [self.degrees, np.zeros(pad, np.int64)])
            self.row_bucket = np.concatenate(
                [self.row_bucket, np.full(pad, -1, np.int32)])
            self.row_pos = np.concatenate(
                [self.row_pos, np.full(pad, -1, np.int32)])
            self.n_rows = n_rows

    def apply_tail(self, tail_rows, tail_cols, tail_vals,
                   full_rows, full_cols, full_vals,
                   n_rows: int, max_width: int, row_multiple: int,
                   stats: Dict[str, Any], defer: bool = False) -> bool:
        """Splice a tail into the resident plan; False → caller rebuilds.

        Rows touched by the tail whose width class is unchanged keep
        their padded slot — the new entries land in the padding region
        (host fancy-index write + device pointwise scatter). Rows that
        moved class (including newly-appeared rows) are cleared from
        their old bucket and rebuilt from the full COO into appended
        delta buckets. Untouched buckets are not touched at all.

        ``defer=True`` (the one-dispatch retrain path): the host mirror
        updates eagerly as always, but instead of dispatching per-bucket
        device scatters the splice vectors are pow2-padded, their H2D
        puts issued IMMEDIATELY (async — the transfers overlap whatever
        host work follows, and are long done when the training dispatch
        consumes them: the double-buffered device-put contract), and the
        specs parked in ``self.pending`` for retrain's `_converge_
        spliced` to scatter inside the SAME dispatch as the sweeps."""
        self._grow_to(n_rows)
        pending: List[Any] = [None] * len(self.buckets) if defer else None
        tail_deg = np.bincount(tail_rows, minlength=n_rows).astype(np.int64)
        new_deg = self.degrees + tail_deg
        if len(tail_rows) and int(new_deg.max()) > max_width:
            return False  # a row outgrew the plan: split-row territory
        touched = np.flatnonzero(tail_deg)
        old_w = _width_classes(self.degrees[touched], self.min_width)
        new_w = _width_classes(new_deg[touched], self.min_width)
        stay = touched[(old_w == new_w) & (self.degrees[touched] > 0)]
        moved = touched[(old_w != new_w) | (self.degrees[touched] == 0)]

        # compaction bound: refuse (→ fresh compact rebuild) once dead
        # slots or appended delta buckets would dominate — otherwise a
        # long retrain sequence creeps in padded solve work and memory
        live = int((self.row_bucket >= 0).sum())
        if (self.dead_rows + len(moved) > max(live, 1) // 4
                or len(self.buckets) > 2 * self.init_buckets + 16):
            return False

        # -- stay rows: splice tail entries into their existing slots ----
        if len(stay):
            stay_lut = np.zeros(n_rows, bool)
            stay_lut[stay] = True
            sel = stay_lut[tail_rows]
            rs, cs, vs = tail_rows[sel], tail_cols[sel], tail_vals[sel]
            order = np.argsort(rs, kind="stable")  # keep scan order per row
            rs, cs, vs = rs[order], cs[order], vs[order]
            _uniq, first, counts = np.unique(
                rs, return_index=True, return_counts=True)
            within = np.arange(len(rs)) - np.repeat(first, counts)
            slots = (self.degrees[rs] + within).astype(np.int32)
            b_arr = self.row_bucket[rs]
            p_arr = self.row_pos[rs]
            for bi in np.unique(b_arr):
                m = b_arr == bi
                b = self.buckets[bi]
                p, s = p_arr[m], slots[m]
                b.cols[p, s] = cs[m]
                b.vals[p, s] = vs[m]
                b.mask[p, s] = 1.0
                if defer:
                    # sentinel = one past the bucket's row count — the
                    # in-dispatch mode="drop" scatter ignores padding
                    sentinel = np.int32(b.row_ids.shape[0])
                    pending[bi] = (
                        None,
                        tuple(jax.device_put(a) for a in (
                            _pad_pow2(p.astype(np.int32), sentinel),
                            _pad_pow2(s.astype(np.int32), 0),
                            _pad_pow2(cs[m].astype(np.int32), 0),
                            _pad_pow2(vs[m].astype(np.float32), 0.0))))
                    continue
                rids, dcols, dvals, dmask = self.trees[bi]
                jp, js = jnp.asarray(p), jnp.asarray(s)
                self.trees[bi] = (
                    rids,
                    _set_entries(dcols, jp, js, jnp.asarray(cs[m])),
                    _set_entries(dvals, jp, js, jnp.asarray(vs[m])),
                    _set_entries(dmask, jp, js,
                                 jnp.ones(len(s), jnp.float32)),
                )
            stats["prep_spliced_entries"] = stats.get(
                "prep_spliced_entries", 0) + int(len(rs))

        # -- moved rows: clear old slots, rebuild into delta buckets -----
        moved_present = moved[self.row_bucket[moved] >= 0]
        if len(moved_present):
            b_arr = self.row_bucket[moved_present]
            p_arr = self.row_pos[moved_present]
            for bi in np.unique(b_arr):
                m = b_arr == bi
                b = self.buckets[bi]
                p = p_arr[m]
                b.row_ids[p] = -1
                b.cols[p, :] = 0
                b.vals[p, :] = 0.0
                b.mask[p, :] = 0.0
                if defer:
                    sentinel = np.int32(b.row_ids.shape[0])
                    prev = pending[bi]
                    pending[bi] = (
                        jax.device_put(
                            _pad_pow2(p.astype(np.int32), sentinel)),
                        prev[1] if prev is not None else None)
                    continue
                rids, dcols, dvals, dmask = self.trees[bi]
                jp = jnp.asarray(p)
                dcols, dvals, dmask, rids = _clear_rows(
                    dcols, dvals, dmask, rids, jp)
                self.trees[bi] = (rids, dcols, dvals, dmask)
            self.row_bucket[moved_present] = -1
            self.row_pos[moved_present] = -1
            self.dead_rows += int(len(moved_present))
        if len(moved):
            lut = np.zeros(n_rows, bool)
            lut[moved] = True
            sel = lut[full_rows]
            delta = self._build_delta(
                full_rows[sel], full_cols[sel], full_vals[sel], n_rows,
                max_width, row_multiple)
            for b in delta:
                bi = len(self.buckets)
                self.buckets.append(b)
                self.trees.append(self._tree_of(b))
                if defer:
                    pending.append(None)  # fresh upload, nothing to splice
                ids = np.asarray(b.row_ids)
                live = np.flatnonzero(ids >= 0)
                self.row_bucket[ids[live]] = bi
                self.row_pos[ids[live]] = live.astype(np.int32)
            stats["prep_rebuilt_rows"] = stats.get(
                "prep_rebuilt_rows", 0) + int(len(moved))

        self.degrees = new_deg
        if defer:
            self.pending = pending
        return True


@dataclasses.dataclass
class PrepPlan:
    """Process-resident bucket plan for one (plan_key) training stream,
    persisted across retrains alongside the traincache's scan state and
    keyed on the COO prefix digest (the same append-only contract the
    tail fold relies on)."""

    key: str
    nnz: int
    digest: bytes
    n_users: int
    n_items: int
    max_width: int
    row_multiple: int
    user: _SidePlan
    item: _SidePlan
    #: FactorPlacement.cache_key() of the mesh geometry this plan's
    #: buckets are blocked for (None = single-chip). A retrain under a
    #: DIFFERENT placement (resharding) invalidates rather than splices:
    #: correctness survives the reshard, the plan rebuilds once.
    placement_key: Optional[str] = None

    def trees(self):
        """→ (u_tree, i_tree) in the ops/als fused-run format."""
        return tuple(self.user.trees), tuple(self.item.trees)


#: at most this many plans stay resident (each holds the padded host
#: mirror of its dataset — hundreds of MB at ML-20M shape)
_PLAN_CACHE_CAP = 2
_PLAN_CACHE: Dict[str, PrepPlan] = {}


def drop_plans() -> None:
    """Tests / memory pressure: forget every resident plan."""
    _PLAN_CACHE.clear()
    _RING_CACHE.clear()


# ---------------------------------------------------------------------------
# ring-layout plan cache — the ring-mode twin of PrepPlan
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _RingSidePlan:
    """One side's resident HOST ring layout (pure classes + mixed), with
    the hole bookkeeping that bounds degradation: removed rows leave
    masked-out padding slots behind, and past the compaction threshold
    the side rebuilds fresh rather than carry dead weight forever."""

    pure: tuple                 # per width class: (rid, col, val, msk)
    mixed: Optional[tuple]      # (rid_m, sid, col, val, msk) or None
    holes: int = 0

    def live_slots(self) -> int:
        n = sum(int((t[0] >= 0).sum()) for t in self.pure)
        if self.mixed is not None:
            n += int((self.mixed[0] >= 0).sum())
        return n


@dataclasses.dataclass
class _RingPlan:
    """Ring-layout sibling of :class:`PrepPlan` (a ring-mode retrain
    never builds the shard-blocked buckets PrepPlan holds, so the ring
    layouts get their own resident plan, same keying discipline: plan
    key + COO prefix digest + placement geometry + gather modes)."""

    key: str
    nnz: int
    digest: bytes
    placement_key: Optional[str]
    modes: Tuple[str, str]
    max_width: int
    user: Optional[_RingSidePlan]   # None = side not in ring mode
    item: Optional[_RingSidePlan]


_RING_CACHE: Dict[str, _RingPlan] = {}
#: tail fraction past which incremental ring splicing stops paying
#: (touched rows approach a full rebuild's work anyway)
_RING_REBUILD_FRAC = 0.25


def _ring_remove_rows(side: _RingSidePlan, touched: np.ndarray,
                      n: int, sr_self: int) -> _RingSidePlan:
    """Mask the touched rows out of one cached side (vectorized over the
    whole layout — no per-row Python): pure slots flip to padding
    (rid −1, mask 0), mixed row slots clear and their segments re-point
    at the drop sentinel. Cols/vals stay in place — masked entries never
    reach a Gram."""
    removed = 0
    pure_out = []
    for rid, col, val, msk in side.pure:
        glob = rid.astype(np.int64) + (
            np.arange(n, dtype=np.int64)[:, None, None] * sr_self)
        rem = (rid >= 0) & np.isin(glob, touched)
        if rem.any():
            rid = rid.copy()
            msk = msk.copy()
            rid[rem] = -1
            msk[rem] = 0.0
            removed += int(rem.sum())
        pure_out.append((rid, col, val, msk))
    mixed = side.mixed
    if mixed is not None:
        rid_m, sid, colm, valm, mskm = mixed
        h = rid_m.shape[1]
        glob = rid_m.astype(np.int64) + (
            np.arange(n, dtype=np.int64)[:, None] * sr_self)
        bad = (rid_m >= 0) & np.isin(glob, touched)
        if bad.any():
            rid_m = rid_m.copy()
            sid = sid.copy()
            mskm = mskm.copy()
            rid_m[bad] = -1
            # segments of a removed row re-point at the sentinel (h) —
            # sentinel rows are dropped after the segment sum
            bad_ext = np.concatenate(
                [bad, np.zeros((n, 1), bool)], axis=1)
            seg_bad = bad_ext[
                np.arange(n)[:, None, None], sid]
            sid[seg_bad] = h
            mskm[seg_bad] = 0.0
            removed += int(bad.sum())
        mixed = (rid_m, sid, colm, valm, mskm)
    return _RingSidePlan(pure=tuple(pure_out), mixed=mixed,
                         holes=side.holes + removed)


def _ring_merge(side: _RingSidePlan, delta: tuple) -> _RingSidePlan:
    """Append a freshly built delta layout (the touched rows' full
    histories) onto the hole-masked resident layout: pure classes concat
    on the B axis per width class, mixed row lists concat (delta slot
    ids shift by the resident h, both sentinels re-point at the merged
    h), segment widths zero-pad to the wider of the two."""
    d_pure, d_mixed = delta
    by_w = {t[1].shape[3]: t for t in side.pure}
    for t in d_pure:
        w = t[1].shape[3]
        if w in by_w:
            c = by_w[w]
            by_w[w] = tuple(
                np.concatenate([a, b], axis=2)
                for a, b in zip(c, t))
        else:
            by_w[w] = t
    pure = tuple(by_w[w] for w in sorted(by_w))
    mixed = side.mixed
    if d_mixed is not None and mixed is None:
        mixed = d_mixed
    elif d_mixed is not None:
        rid_m, sid, colm, valm, mskm = mixed
        rid_d, sid_d, cold, vald, mskd = d_mixed
        n = rid_m.shape[0]
        h, hd = rid_m.shape[1], rid_d.shape[1]
        h_new = h + hd
        w, wd = colm.shape[3], cold.shape[3]
        wn = max(w, wd)

        def pad_w(a):
            return (a if a.shape[3] == wn else np.pad(
                a, ((0, 0), (0, 0), (0, 0), (0, wn - a.shape[3]))))

        sid = np.where(sid == h, h_new, sid)
        sid_d = np.where(sid_d == hd, h_new, sid_d + h)
        mixed = (
            np.concatenate([rid_m, rid_d], axis=1),
            np.concatenate([sid, sid_d], axis=2),
            np.concatenate([pad_w(colm), pad_w(cold)], axis=2),
            np.concatenate([pad_w(valm), pad_w(vald)], axis=2),
            np.concatenate([pad_w(mskm), pad_w(mskd)], axis=2),
        )
    return _RingSidePlan(pure=pure, mixed=mixed, holes=side.holes)


def _ring_sides_with_reuse(
    users: np.ndarray,
    items: np.ndarray,
    vals: np.ndarray,
    placement,
    modes: Tuple[str, str],
    max_width: int,
    plan_key: Optional[str],
    verify_prefix: bool,
    stats: Dict[str, Any],
):
    """Placed (u_data, i_data) for a ring-mode retrain, splicing the
    appended tail into the resident host ring layouts instead of paying
    the full-COO prep every retrain (ROADMAP item 1's remaining host
    cost). The device put still covers the whole layout — what the
    cache removes is the O(nnz·pairs) host construction.

    Reuse applies when the COO prefix digest matches the resident plan
    (same append-only contract as :func:`prepare_with_reuse`): rows
    touched by the tail are hole-masked out of the resident layout
    (vectorized), their FULL histories rebuild through the vectorized
    :func:`~...parallel.sharding.build_ring_side` as a small delta
    layout, and the delta appends. Anything unprovable — reshard, mode
    flip, oversized tail, hole pressure past the compaction threshold —
    rebuilds fresh (byte-identical to a cold prep)."""
    nnz = len(vals)
    pkey = placement.cache_key()
    modes = tuple(modes)
    enabled = bool(plan_key) and plan_reuse_enabled()
    plan = _RING_CACHE.get(plan_key) if enabled else None
    prebuilt = {"user": None, "item": None}
    if plan is not None:
        tail_n = nnz - plan.nnz
        ok = (tail_n >= 0 and plan.placement_key == pkey
              and plan.modes == modes and plan.max_width == max_width
              and tail_n <= max(plan.nnz, 1) * _RING_REBUILD_FRAC)
        if ok and verify_prefix:
            ok = _coo_digest(users, items, vals, plan.nnz) == plan.digest
        if ok:
            for side_name, rows, cols, side_plan in (
                    ("user", users, items, plan.user),
                    ("item", items, users, plan.item)):
                if side_plan is None:
                    continue
                touched = np.unique(
                    np.asarray(rows[plan.nnz:], np.int64))
                n = placement.n_shards
                sr_self = placement.shard_rows(side_name)
                sr_other = placement.shard_rows(
                    "item" if side_name == "user" else "user")
                cleared = _ring_remove_rows(side_plan, touched, n,
                                            sr_self)
                if cleared.holes > max(cleared.live_slots(), 1):
                    # hole pressure: more padding than live rows —
                    # compact via a fresh build of this side
                    continue
                from incubator_predictionio_tpu.parallel.sharding import (
                    build_ring_side,
                )

                sel = np.isin(np.asarray(rows, np.int64), touched)
                delta = build_ring_side(
                    np.asarray(rows)[sel], np.asarray(cols)[sel],
                    vals[sel], n, sr_self, sr_other,
                    max_width=max_width)
                prebuilt[side_name] = _ring_merge(cleared, delta)
            if any(p is not None for p in prebuilt.values()):
                stats["prep_plan"] = "ring-reused"
                stats["prep_delta_rows"] = int(nnz - plan.nnz)
                # the O(delta) seam of the serving MIPS index
                # (ops/mips.update_index): exactly the factor rows
                # whose interactions changed this retrain
                stats["touched_item_rows"] = np.unique(
                    np.asarray(items[plan.nnz:], np.int64))
        if stats.get("prep_plan") != "ring-reused":
            _RING_CACHE.pop(plan_key, None)
            stats["prep_plan"] = "ring-fresh"
    else:
        stats["prep_plan"] = "ring-fresh"

    host_out: Dict[str, Any] = {}
    u_data, i_data = als.build_placed_sides(
        users, items, vals, placement, modes, max_width=max_width,
        ring_layouts=(
            None if prebuilt["user"] is None
            else (prebuilt["user"].pure, prebuilt["user"].mixed),
            None if prebuilt["item"] is None
            else (prebuilt["item"].pure, prebuilt["item"].mixed)),
        ring_host_out=host_out)
    if enabled:
        while len(_RING_CACHE) >= _PLAN_CACHE_CAP:
            _RING_CACHE.pop(next(iter(_RING_CACHE)))

        def side_plan(name):
            if name not in host_out:
                return None  # allgather side: PrepPlan-free fresh build
            if prebuilt[name] is not None:
                return prebuilt[name]  # keep hole bookkeeping
            pure, mixed = host_out[name]
            return _RingSidePlan(pure=pure, mixed=mixed)

        _RING_CACHE[plan_key] = _RingPlan(
            key=plan_key, nnz=nnz,
            digest=_coo_digest(users, items, vals, nnz),
            placement_key=pkey, modes=modes, max_width=max_width,
            user=side_plan("user"), item=side_plan("item"))
    return u_data, i_data


def prepare_with_reuse(
    users: np.ndarray,
    items: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    max_width: int = 1 << 16,
    row_multiple: int = 8,
    plan_key: Optional[str] = None,
    verify_prefix: bool = True,
    user_degrees: Optional[np.ndarray] = None,
    item_degrees: Optional[np.ndarray] = None,
    stats: Optional[Dict[str, Any]] = None,
    defer_splice: bool = False,
    placement=None,
):
    """Degree-bucketed padded trees, reusing a resident plan when only a
    tail was appended → (u_tree, i_tree, u_heavy, i_heavy).

    ``placement`` (a FactorPlacement) switches every structure to the
    mesh-sharded layout: shard-blocked buckets with shard-local device
    ids, sharded heavy segments, and a plan keyed on the placement's
    shard geometry — a retrain at a different mesh shape invalidates
    (rebuild once) instead of splicing into a stale layout.

    ``plan_key`` names the training stream (e.g. the event-log path);
    None disables reuse entirely (byte-identical to the fresh path).
    ``verify_prefix=False`` skips the O(prefix) digest check for callers
    that already hold the append-only guarantee (the traincache fold).

    ``defer_splice=True`` (the one-dispatch retrain path): when the plan
    is reused, the returned trees are the PRE-splice residents and the
    splice vectors land in ``stats["pending_splices"]`` (their H2D puts
    already in flight) for :func:`als_retrain` to scatter inside the
    training dispatch itself; the caller MUST apply them (and write the
    updated trees back via ``commit_spliced_trees``) or drop the plan —
    the host mirror is already updated either way."""
    stats = {} if stats is None else stats
    users = np.asarray(users)
    items = np.asarray(items)
    vals = np.asarray(vals, np.float32)
    nnz = len(vals)
    pkey = placement.cache_key() if placement is not None else None
    plan = _PLAN_CACHE.get(plan_key) if (
        plan_key and plan_reuse_enabled()) else None
    if plan is not None:
        ok = (nnz >= plan.nnz and n_users >= plan.n_users
              and n_items >= plan.n_items
              and plan.max_width == max_width
              and plan.row_multiple == row_multiple
              and plan.placement_key == pkey)
        if ok and verify_prefix:
            ok = _coo_digest(users, items, vals, plan.nnz) == plan.digest
        if ok:
            tr, tc, tv = users[plan.nnz:], items[plan.nnz:], vals[plan.nnz:]
            u_ok = plan.user.apply_tail(
                tr, tc, tv, users, items, vals, n_users, max_width,
                row_multiple, stats, defer=defer_splice)
            i_ok = u_ok and plan.item.apply_tail(
                tc, tr, tv, items, users, vals, n_items, max_width,
                row_multiple, stats, defer=defer_splice)
            if u_ok and i_ok:
                plan.nnz = nnz
                plan.n_users, plan.n_items = n_users, n_items
                plan.digest = _coo_digest(users, items, vals, nnz)
                stats["prep_plan"] = "reused"
                stats["prep_delta_rows"] = int(len(tr))
                # the O(delta) seam of the serving MIPS index
                # (ops/mips.update_index): rows whose interactions the
                # tail touched re-quantize/re-assign, everything else
                # keeps its bucket
                stats["touched_item_rows"] = np.unique(
                    np.asarray(tc, np.int64))
                if defer_splice:
                    u_pend = plan.user.pending or []
                    i_pend = plan.item.pending or []
                    plan.user.pending = plan.item.pending = None
                    if any(s is not None for s in (*u_pend, *i_pend)):
                        stats["pending_splices"] = (
                            tuple(u_pend), tuple(i_pend))
                u_tree, i_tree = plan.trees()
                return u_tree, i_tree, None, None
            # a side bailed mid-splice: the plan's host/device state may
            # be half-updated — drop it and rebuild fresh
            _PLAN_CACHE.pop(plan_key, None)
            stats["prep_plan"] = "rebuilt"
        else:
            _PLAN_CACHE.pop(plan_key, None)
            stats["prep_plan"] = "invalidated"
    else:
        stats.setdefault(
            "prep_plan",
            "miss" if (plan_key and plan_reuse_enabled()) else "off")

    (u_light, u_heavy), (i_light, i_heavy) = build_both_sides(
        users, items, vals, n_users, n_items, max_width=max_width,
        row_multiple=row_multiple,
        # histograms from the scan's prep-plan sidecar (cpplog stats
        # ``plan_user_degrees``/``plan_item_degrees``) skip the native
        # degree pass; a wrong histogram is detected natively and redone
        user_degrees=user_degrees, item_degrees=item_degrees)

    def _adopt_plan(u_buckets, i_buckets, u_side_kw=None, i_side_kw=None):
        """Insert a fresh PrepPlan (cap eviction, prefix digest, side
        plans) and hand back its resident trees — ONE insert shared by
        the placed and unplaced paths so the eviction/digest/field
        logic cannot drift between them."""
        while len(_PLAN_CACHE) >= _PLAN_CACHE_CAP:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        new_plan = PrepPlan(
            key=plan_key, nnz=nnz,
            digest=_coo_digest(users, items, vals, nnz),
            n_users=n_users, n_items=n_items, max_width=max_width,
            row_multiple=row_multiple, placement_key=pkey,
            user=_SidePlan.build(
                u_buckets,
                (user_degrees if user_degrees is not None
                 else np.bincount(users, minlength=n_users)), n_users,
                **(u_side_kw or {})),
            item=_SidePlan.build(
                i_buckets,
                (item_degrees if item_degrees is not None
                 else np.bincount(items, minlength=n_items)), n_items,
                **(i_side_kw or {})),
        )
        _PLAN_CACHE[plan_key] = new_plan
        return new_plan.trees()

    if placement is not None:
        from incubator_predictionio_tpu.parallel.sharding import (
            shard_block_buckets,
            shard_block_heavy,
        )

        n_sh = placement.n_shards
        sr_u = placement.shard_rows("user")
        sr_i = placement.shard_rows("item")
        sharding = placement.table_sharding()
        u_blocks = shard_block_buckets(u_light, n_sh, sr_u)
        i_blocks = shard_block_buckets(i_light, n_sh, sr_i)

        def put_hv(hv):
            if hv is None:
                return None
            return tuple(jax.device_put(jnp.asarray(a), sharding)
                         for a in hv)

        if plan_key and plan_reuse_enabled() and u_heavy is None \
                and i_heavy is None:
            u_tree, i_tree = _adopt_plan(
                u_blocks, i_blocks,
                dict(n_shards=n_sh, shard_rows=sr_u,
                     put_sharding=sharding),
                dict(n_shards=n_sh, shard_rows=sr_i,
                     put_sharding=sharding))
            return u_tree, i_tree, None, None
        from incubator_predictionio_tpu.parallel.sharding import (
            localize_tree,
        )

        def put_tree(tree):
            return tuple(
                tuple(jax.device_put(a, sharding) for a in b)
                for b in tree)

        return (put_tree(localize_tree(u_blocks, n_sh, sr_u)),
                put_tree(localize_tree(i_blocks, n_sh, sr_i)),
                put_hv(shard_block_heavy(u_heavy, n_sh, sr_u)),
                put_hv(shard_block_heavy(i_heavy, n_sh, sr_i)))
    if plan_key and plan_reuse_enabled() and u_heavy is None \
            and i_heavy is None:
        u_tree, i_tree = _adopt_plan(u_light, i_light)
        return u_tree, i_tree, None, None
    return (als._buckets_tree(u_light), als._buckets_tree(i_light),
            als._heavy_tree(u_heavy), als._heavy_tree(i_heavy))


def commit_spliced_trees(plan_key: str, u_tree, i_tree) -> None:
    """Adopt the in-dispatch-spliced device trees as the plan's new
    residents (the deferred-splice counterpart of apply_tail's eager
    device scatters). The host mirror was already updated eagerly."""
    plan = _PLAN_CACHE.get(plan_key)
    if plan is not None:
        plan.user.trees = list(u_tree)
        plan.item.trees = list(i_tree)


# ---------------------------------------------------------------------------
# early-stopping training drivers
# ---------------------------------------------------------------------------

def _splice_tree(tree, splices):
    """Scatter deferred splice specs into a bucket tree — TRACED (the
    body of the one-dispatch retrain). Per bucket: ``None`` (untouched)
    or ``(clear_pos, (pos, slot, cols, vals))``. All index vectors are
    pow2-padded with out-of-range sentinels; ``mode="drop"`` makes the
    padding a no-op, exactly like the -1 row-id scatter in ops/als.
    Produces trees bitwise-identical to apply_tail's eager
    ``_set_entries``/``_clear_rows`` scatters (pinned by
    tests/test_fused_gram.py)."""
    out = []
    for (rids, cols, vals, mask), sp in zip(tree, splices):
        if sp is not None:
            clear_pos, sets = sp
            if clear_pos is not None:
                cols = cols.at[clear_pos].set(0, mode="drop")
                vals = vals.at[clear_pos].set(0.0, mode="drop")
                mask = mask.at[clear_pos].set(0.0, mode="drop")
                rids = rids.at[clear_pos].set(-1, mode="drop")
            if sets is not None:
                pos, slot, c, v = sets
                cols = cols.at[pos, slot].set(c, mode="drop")
                vals = vals.at[pos, slot].set(v, mode="drop")
                mask = mask.at[pos, slot].set(1.0, mode="drop")
        out.append((rids, cols, vals, mask))
    return tuple(out)


@jax.jit
def _apply_splices(tree, splices):
    """Standalone splice application (one dispatch per side) — the
    unfused-probe path's fallback when the spliced converge cannot
    carry it."""
    return _splice_tree(tree, splices)


@functools.partial(
    jax.jit,
    static_argnames=("max_sweeps", "min_sweeps", "reg_nnz", "compute_dtype",
                     "precision", "implicit", "cg_iters", "use_kernel",
                     "kernel_min_d", "kernel_rows", "warmstart", "use_fused",
                     "cg_tol"),
    donate_argnames=("state",),
)
def _converge_spliced(
    state, u_tree, i_tree, u_splice, i_splice, l2, alpha, tol,
    max_sweeps, min_sweeps, reg_nnz, compute_dtype, precision, implicit,
    u_hv, i_hv, cg_iters, use_kernel, kernel_min_d, kernel_rows,
    warmstart, use_fused, cg_tol,
):
    """THE one-dispatch continuation retrain: splice the O(delta) tail
    into the resident trees, run every sweep, and evaluate the
    early-stop plateau — all inside a single jit, so a steady-state
    retrain costs exactly one device dispatch end to end (splice
    scatters included; the H2D puts were issued back in apply_tail and
    have long overlapped the host work since). Returns the spliced
    trees so the caller re-adopts them as the plan's residents."""
    u_tree = _splice_tree(u_tree, u_splice)
    i_tree = _splice_tree(i_tree, i_splice)
    st, n, d = als._converge_impl(
        state, u_tree, i_tree, l2, alpha, tol, max_sweeps, min_sweeps,
        reg_nnz, compute_dtype, precision, implicit,
        user_heavy=u_hv, item_heavy=i_hv, cg_iters=cg_iters,
        use_kernel=use_kernel, kernel_min_d=kernel_min_d,
        kernel_rows=kernel_rows, warmstart=warmstart, use_fused=use_fused,
        cg_tol=cg_tol)
    return st, n, d, u_tree, i_tree


def _converge_leg(state, u_tree, i_tree, l2, alpha, tol, budget, floor,
                  reg_nnz, compute_dtype, precision, implicit,
                  u_hv, i_hv, cg_iters, use_kernel, kernel_min_d,
                  kernel_rows, warmstart, use_fused=(False, False),
                  cg_tol=0.0, splices=None, counter=None):
    """One precision leg with early stop → (state, sweeps, delta,
    u_tree, i_tree).

    Fused mode: the whole leg is one dispatch (`_als_run_converge`, or
    `_converge_spliced` when a deferred plan splice rides along);
    sweeps/delta are fetched once after it. Unfused mode: fused chunks
    of PIO_RETRAIN_PROBE_EVERY sweeps, each returning its in-trace
    last-sweep delta — the host fetches ONE scalar per chunk (the
    chunked probe), never one per sweep. ``counter`` (a ``{"n": int}``
    dict) books every device dispatch this leg issues — the
    one-dispatch contract's measured pin."""
    def count(k=1):
        if counter is not None:
            counter["n"] += k

    if _fused_early_stop():
        if splices is not None:
            state, n, d, u_tree, i_tree = _converge_spliced(
                state, u_tree, i_tree, splices[0], splices[1], l2, alpha,
                tol, budget, floor, reg_nnz, compute_dtype, precision,
                implicit, u_hv, i_hv, cg_iters, use_kernel, kernel_min_d,
                kernel_rows, warmstart, use_fused, cg_tol)
            count()
            return state, int(n), float(d), u_tree, i_tree
        state, n, d = als._als_run_converge(
            state, u_tree, i_tree, l2, alpha, tol, budget, floor,
            reg_nnz, compute_dtype, precision, implicit,
            user_heavy=u_hv, item_heavy=i_hv, cg_iters=cg_iters,
            use_kernel=use_kernel, kernel_min_d=kernel_min_d,
            kernel_rows=kernel_rows, warmstart=warmstart,
            use_fused=use_fused, cg_tol=cg_tol)
        count()
        return state, int(n), float(d), u_tree, i_tree
    if splices is not None:
        # the chunked probe re-enters the jit per chunk — apply the
        # splice once, up front (one extra dispatch per side)
        u_tree = _apply_splices(u_tree, splices[0])
        i_tree = _apply_splices(i_tree, splices[1])
        count(2)
    probe = retrain_probe_every()
    done, d = 0, float("inf")
    while done < budget:
        chunk = min(probe, budget - done)
        state, _n, dd = als._als_run_converge(
            state, u_tree, i_tree, l2, alpha, 0.0, chunk, chunk,
            reg_nnz, compute_dtype, precision, implicit,
            user_heavy=u_hv, item_heavy=i_hv, cg_iters=cg_iters,
            use_kernel=use_kernel, kernel_min_d=kernel_min_d,
            kernel_rows=kernel_rows, warmstart=warmstart,
            use_fused=use_fused, cg_tol=cg_tol)
        count()
        done += chunk
        d = float(dd)  # ONE host sync per chunk — the probe boundary
        if done >= floor and tol > 0 and d < tol:
            break
    return state, done, d, u_tree, i_tree


@functools.partial(
    jax.jit,
    static_argnames=("placement", "cfg", "max_sweeps", "min_sweeps"),
)
def _converge_spliced_placed(
    uf, vf, u_tree, i_tree, u_splice, i_splice, u_hv, i_hv, tol, *,
    placement, cfg, max_sweeps, min_sweeps,
):
    """THE one-dispatch continuation retrain under a mesh placement:
    scatter the O(delta) splice vectors into the resident SHARDED trees
    (GSPMD routes each pointwise update to the owning shard — the flat
    positions live in that shard's block by construction), then run the
    early-stopping shard_map sweep loop — all one jit, one dispatch per
    shard group, zero host crossings. Returns the spliced trees so the
    caller re-adopts them as the plan's residents."""
    from jax.sharding import NamedSharding

    u_tree = _splice_tree(u_tree, u_splice)
    i_tree = _splice_tree(i_tree, i_splice)
    sharding = NamedSharding(placement.mesh, placement.table_spec)
    constrain = functools.partial(
        jax.tree_util.tree_map,
        lambda a: jax.lax.with_sharding_constraint(a, sharding))
    u_tree, i_tree = constrain(u_tree), constrain(i_tree)
    uf, vf, n, d = als._converge_placed_impl(
        uf, vf, (u_tree, u_hv), (i_tree, i_hv), tol, placement, cfg,
        max_sweeps, min_sweeps)
    return uf, vf, n, d, u_tree, i_tree


def _als_retrain_placed(
    users, items, vals, n_users, n_items, rank, iterations, l2, alpha,
    seed, reg_nnz, implicit, bf16_sweeps, compute_dtype, precision,
    max_width, prev_state, tol, floor, plan_key, verify_prefix, stats,
    placement,
):
    """Continuation retrain with mesh-sharded factor tables → a PLACED
    ALSState. The sharded twin of the ``als_retrain`` body: plan-reuse
    prep in the shard-blocked layout, deferred splices scattered inside
    the training dispatch, device-side early stop with the factor-delta
    plateau psum'd across shards. A previous model trained at ANY mesh
    shape (including single-chip) seeds the continuation —
    ``place_state`` re-distributes its true-size prefix under this
    placement. The sharded path always runs the fused while_loop
    schedule (the chunked ``PIO_RETRAIN_FUSED=0`` probe would cost one
    sync per chunk per shard group).

    Gather strategy: the plan-reuse splice layout is allgather-only
    (splices scatter into resident shard-blocked trees). When the auto
    strategy resolves RING for either half-sweep — the table too wide
    to replicate transiently, exactly the catalog scale sharding exists
    for — a full-table all-gather here would undo slice residency, so
    the retrain preps fresh placed sides in the ring layout instead:
    still the continuation warm start, still one dispatch, only the
    O(delta) splice reuse is traded away."""
    import time

    modes = als._shard_gather_modes(placement, rank, compute_dtype,
                                    implicit)
    ring = "ring" in modes
    t_prep = time.perf_counter()
    if ring:
        # ring-layout plan reuse (_RING_CACHE): the appended tail
        # splices into the resident host layouts instead of paying the
        # full-COO ring prep per retrain; stats["prep_plan"] reports
        # "ring-reused" or "ring-fresh"
        u_data, i_data = _ring_sides_with_reuse(
            users, items, vals, placement, modes, max_width=max_width,
            plan_key=plan_key, verify_prefix=verify_prefix, stats=stats)
        (u_tree, u_hv), (i_tree, i_hv) = u_data, i_data
        splices = None
    else:
        u_tree, i_tree, u_hv, i_hv = prepare_with_reuse(
            users, items, vals, n_users, n_items, max_width=max_width,
            plan_key=plan_key, verify_prefix=verify_prefix, stats=stats,
            defer_splice=True, placement=placement)
        splices = stats.pop("pending_splices", None)
    stats["prep_wall_s"] = time.perf_counter() - t_prep

    state = None
    if prev_state is not None:
        state = als.continue_state(
            prev_state.user_factors, prev_state.item_factors,
            n_users, n_items, seed=seed)
        if state is not None and state.user_factors.shape[1] != rank:
            state = None
    mode = "continue" if state is not None else "fresh"
    if state is None:
        state = als.als_init(jax.random.key(seed), n_users, n_items, rank)
    state = placement.place_state(state)

    from incubator_predictionio_tpu.obs import profile as _profile

    lo = 0 if implicit else min(max(bf16_sweeps, 0), iterations)
    counter = {"n": 0}
    sweeps, delta, bf16_used = 0, float("inf"), 0
    uf, vf = state.user_factors, state.item_factors
    spliced = splices is not None
    # the last leg's cfg doubles as the metrics-booking cfg (no third
    # gather-strategy/VMEM/probe resolution just to book telemetry)
    cfg_book = als._placed_cfg(
        placement, rank, implicit, reg_nnz, l2, alpha, compute_dtype,
        precision, als._CG_ITERS, modes=modes)
    _prof_t0 = _profile.t0()
    try:
        def leg(uf, vf, u_tree, i_tree, budget, leg_floor, cfg, splices):
            if splices is not None:
                uf, vf, n, d, u_tree, i_tree = _converge_spliced_placed(
                    uf, vf, u_tree, i_tree, splices[0], splices[1],
                    u_hv, i_hv, jnp.float32(tol), placement=placement,
                    cfg=cfg, max_sweeps=budget, min_sweeps=leg_floor)
            else:
                uf, vf, n, d = als._als_converge_placed(
                    uf, vf, (u_tree, u_hv), (i_tree, i_hv),
                    jnp.float32(tol), placement=placement, cfg=cfg,
                    max_sweeps=budget, min_sweeps=leg_floor)
            counter["n"] += 1
            return uf, vf, u_tree, i_tree, int(n), float(d)

        if lo:
            cfg_lo = als._placed_cfg(
                placement, rank, False, reg_nnz, l2, 0.0, jnp.bfloat16,
                jax.lax.Precision.DEFAULT,
                min(als._CG_ITERS_BF16, als._CG_ITERS),
                modes=modes)
            uf, vf, u_tree, i_tree, n, delta = leg(
                uf, vf, u_tree, i_tree, lo, min(floor, lo), cfg_lo,
                splices)
            splices = None
            sweeps += n
            bf16_used = n
        if iterations - lo > 0:
            uf, vf, u_tree, i_tree, n, delta = leg(
                uf, vf, u_tree, i_tree, iterations - lo,
                max(floor - sweeps, 1), cfg_book, splices)
            splices = None
            sweeps += n
        if splices is not None:
            u_tree = _apply_splices(u_tree, splices[0])
            i_tree = _apply_splices(i_tree, splices[1])
            counter["n"] += 2
            splices = None
        if spliced and plan_key:
            commit_spliced_trees(plan_key, u_tree, i_tree)
    except BaseException:
        if plan_key:
            _PLAN_CACHE.pop(plan_key, None)
            _RING_CACHE.pop(plan_key, None)
        raise
    if _prof_t0 is not None and sweeps:
        # PIO_PROFILE=1: device-time/MFU attribution over the sweeps
        # actually run, under the SAME op label as als_train_placed so
        # sharded training stays separable in /metrics next to the
        # single-chip als_retrain/als_fused labels
        _profile.record(
            _prof_t0, "train", "als_sharded",
            als.train_flops(len(vals), n_users, n_items, rank, sweeps,
                            bf16_used, warmstart=cfg_book.warmstart),
            uf)
    stats.update(sweeps_used=sweeps, mode=mode, final_delta=delta,
                 train_dispatches=counter["n"],
                 one_dispatch=counter["n"] == 1)
    _book_sweeps(mode, sweeps)
    als._profile_placed_collectives(placement, uf, vf, modes)
    # book each leg at ITS dtype (bf16 ring slices move half the bytes)
    if bf16_used:
        als._book_shard_metrics(placement, cfg_lo, rank, bf16_used)
    als._book_shard_metrics(placement, cfg_book, rank,
                            sweeps - bf16_used)
    return als.ALSState(user_factors=uf, item_factors=vf,
                        placement=placement)


def als_retrain(
    users: np.ndarray,
    items: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    rank: int = 64,
    iterations: int = 10,
    l2: float = 0.1,
    alpha: float = 1.0,
    seed: int = 0,
    reg_nnz: bool = True,
    implicit: bool = False,
    bf16_sweeps: int = 0,
    compute_dtype: Any = jnp.float32,
    precision: Any = jax.lax.Precision.HIGHEST,
    max_width: int = 1 << 16,
    prev_state: Optional[als.ALSState] = None,
    tol: Optional[float] = None,
    min_sweeps: Optional[int] = None,
    plan_key: Optional[str] = None,
    verify_prefix: bool = True,
    stats: Optional[Dict[str, Any]] = None,
    placement=None,
) -> als.ALSState:
    """Continuation-aware training: warm factors + early stop + plan
    reuse. With ``prev_state=None``, ``tol=0`` and ``plan_key=None``
    this runs exactly the fixed-budget schedule of ``als_train`` /
    ``als_train_implicit`` (their fresh paths stay byte-stable — this
    entry point exists so they don't have to change).

    ``placement`` (a FactorPlacement) routes the whole retrain through
    the mesh-sharded path (:func:`_als_retrain_placed`): sharded plan,
    in-dispatch splices on the owning shards, psum'd early stop — and
    returns a PLACED state.

    ``stats`` (a dict) receives ``sweeps_used``, ``mode``
    ("fresh"|"continue"), ``final_delta``, the prep-reuse counters, and
    the one-dispatch pins ``train_dispatches``/``one_dispatch`` (every
    device dispatch the train phase issued — splice included; steady
    state is exactly 1)."""
    import time

    stats = {} if stats is None else stats
    tol = retrain_tol() if tol is None else float(tol)
    floor = retrain_min_sweeps() if min_sweeps is None else max(
        int(min_sweeps), 1)
    if placement is not None:
        return _als_retrain_placed(
            users, items, vals, n_users, n_items, rank, iterations, l2,
            alpha, seed, reg_nnz, implicit, bf16_sweeps, compute_dtype,
            precision, max_width, prev_state, tol, floor, plan_key,
            verify_prefix, stats, placement)
    t_prep = time.perf_counter()
    u_tree, i_tree, u_hv, i_hv = prepare_with_reuse(
        users, items, vals, n_users, n_items, max_width=max_width,
        plan_key=plan_key, verify_prefix=verify_prefix, stats=stats,
        defer_splice=True)
    stats["prep_wall_s"] = time.perf_counter() - t_prep
    splices = stats.pop("pending_splices", None)

    state = None
    if prev_state is not None:
        state = als.continue_state(
            prev_state.user_factors, prev_state.item_factors,
            n_users, n_items, seed=seed)
        if state is not None and state.user_factors.shape[1] != rank:
            state = None  # rank changed: the prior factors are unusable
    mode = "continue" if state is not None else "fresh"
    if state is None:
        state = als.als_init(jax.random.key(seed), n_users, n_items, rank)

    from incubator_predictionio_tpu.obs import profile as _profile

    _prof_t0 = _profile.t0()
    warmstart = als._CG_WARMSTART
    use_kernel = als._kernel_enabled(implicit, warm=warmstart)
    kernel_min_d = als._KERNEL_MIN_D
    kernel_rows = als._kernel_rows_default()
    cg_tol = als._cg_tol_env()

    def fused_for(dtype):
        if not use_kernel:
            return (False, False)
        return als._fused_sides(n_users, n_items, implicit, warmstart,
                                dtype, rank)

    lo = 0 if implicit else min(max(bf16_sweeps, 0), iterations)
    sweeps = 0
    delta = float("inf")
    bf16_used = 0
    counter = {"n": 0}
    spliced = splices is not None
    try:
        if lo:
            state, n, delta, u_tree, i_tree = _converge_leg(
                state, u_tree, i_tree, l2, 0.0, tol, lo, min(floor, lo),
                reg_nnz, jnp.bfloat16, jax.lax.Precision.DEFAULT, False,
                u_hv, i_hv, min(als._CG_ITERS_BF16, als._CG_ITERS),
                use_kernel, kernel_min_d, kernel_rows, warmstart,
                use_fused=fused_for(jnp.bfloat16), cg_tol=cg_tol,
                splices=splices, counter=counter)
            splices = None
            sweeps += n
            bf16_used = n
        if iterations - lo > 0:
            state, n, delta, u_tree, i_tree = _converge_leg(
                state, u_tree, i_tree, l2, alpha, tol, iterations - lo,
                max(floor - sweeps, 1), reg_nnz, compute_dtype, precision,
                implicit, u_hv, i_hv, als._CG_ITERS, use_kernel,
                kernel_min_d, kernel_rows, warmstart,
                use_fused=fused_for(compute_dtype), cg_tol=cg_tol,
                splices=splices, counter=counter)
            splices = None
            sweeps += n
        if splices is not None:
            # no training leg consumed the deferred splice (a
            # zero-iteration call) — apply it now, or the commit below
            # would adopt PRE-splice trees while the plan's digest
            # already covers the tail, silently dropping the tail's
            # interactions from every future reuse
            u_tree = _apply_splices(u_tree, splices[0])
            i_tree = _apply_splices(i_tree, splices[1])
            counter["n"] += 2
            splices = None
        if spliced and plan_key:
            # the splice ran inside the training dispatch — adopt its
            # output trees as the plan's residents for the next retrain
            commit_spliced_trees(plan_key, u_tree, i_tree)
    except BaseException:
        # a failure between the deferred host-mirror update and the
        # device-tree adoption leaves the plan split-brained — drop it
        # (the next retrain rebuilds fresh; reuse is an optimization)
        if plan_key:
            _PLAN_CACHE.pop(plan_key, None)
        raise
    if _prof_t0 is not None and sweeps:
        # PIO_PROFILE=1: device-time/MFU attribution over the sweeps
        # actually run (the early stop makes the count data-dependent;
        # nnz is in hand here — no device mask sums needed)
        _profile.record(
            _prof_t0, "train", "als_fused" if use_kernel else "als_retrain",
            als.train_flops(len(vals), n_users, n_items, rank, sweeps,
                            bf16_used, warmstart=warmstart),
            state)
    stats.update(sweeps_used=sweeps, mode=mode, final_delta=delta,
                 train_dispatches=counter["n"],
                 one_dispatch=counter["n"] == 1)
    _book_sweeps(mode, sweeps)
    return state


def _book_sweeps(mode: str, sweeps: int) -> None:
    """pio_train_sweeps_total{mode} — the obs bridge for the retrain
    path (booked OUTSIDE any trace; the metric-in-trace contract)."""
    try:
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        obs_metrics.REGISTRY.counter(
            "pio_train_sweeps_total",
            "ALS sweeps actually run by training, by schedule mode",
            labels=("mode",),
        ).labels(mode=mode).inc(sweeps)
    except Exception:  # telemetry must never fail a train
        logger.exception("sweep-counter export failed")
