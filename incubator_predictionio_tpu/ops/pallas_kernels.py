"""Hand-written Pallas TPU kernels for the serving/training hot ops.

The reference delegates all compute to Spark MLlib and serves predictions
with driver-side Scala loops (examples/.../ALSAlgorithm.scala predict,
core/.../workflow/CreateServer.scala:498-650 query path); it has no custom
kernels of any kind. This module is the TPU-native analogue of "the code the
hot loop actually runs": Mosaic kernels that keep the MXU busy and cut HBM
traffic where XLA's default lowering leaves bandwidth on the table.

Two kernels:

- :func:`score_and_top_k_pallas` — full-catalog recommendation scoring.
  Grid over item blocks; each program computes a [B, block] score tile on
  the MXU, applies the serve-time allow/deny mask in-register, and reduces
  the tile to its block-local top-k **before** touching HBM. Only
  ``num_blocks × 128`` candidates are ever written back instead of the full
  ``[B, n_items]`` score matrix — for catalogs ≥100k items the HBM write
  traffic drops by >100× and the final merge is a tiny ``lax.top_k``.
- :func:`flash_attention` — FlashAttention-style fused attention for the
  sequence model family (models/sequence). One kernel program per
  (batch·head, query-block, KV-block) grid cell; K/V stream through VMEM
  one tile at a time with the online-softmax state in VMEM scratch, so
  VMEM use is S-independent and the [S, S] logit matrix never
  materializes. Numerics are kept bit-compatible with
  ops/attention.py (same MASK_VALUE, same zero-for-fully-masked-row rule)
  so the single-chip path and the ring-attention path agree.

Both kernels run under ``interpret=True`` on CPU for the test suite and
compile with Mosaic on real TPU. Callers gate on the PER-FAMILY probes —
:func:`topk_kernel_available` / :func:`flash_available` — never on
:func:`pallas_available` alone: Mosaic support is not all-or-nothing (a
backend can compile the top-k kernel yet reject flash attention's
lowering), so each family probes its own real kernel at the call sites'
block shapes before production code selects it.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from incubator_predictionio_tpu.ops.attention import MASK_VALUE  # noqa: E402
# (imported, not duplicated: flash numerics must stay bit-identical to the
# dense/blockwise/ring paths in ops/attention.py)

NEG_INF = -3.4e38   # python float: pallas kernels may not close over arrays
_LANES = 128


_mosaic_ok: "bool | None" = None


def pallas_available() -> bool:
    """True when the default backend compiles Mosaic kernels.

    Platform name alone is not enough: experimental backends may report
    ``tpu`` without full Mosaic support, and serving calls the kernels with
    no per-query fallback — so probe once by compiling a trivial kernel and
    cache the result."""
    global _mosaic_ok
    if _mosaic_ok is None:
        _mosaic_ok = _probe_mosaic()
    return _mosaic_ok


def _probe_mosaic() -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False

        def _probe_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        x = jnp.zeros((8, _LANES), jnp.float32)
        out = pl.pallas_call(
            _probe_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
        jax.block_until_ready(out)
        return True
    except Exception as exc:  # pragma: no cover - Mosaic unsupported
        import logging

        logging.getLogger(__name__).warning(
            "Mosaic probe failed on backend %r; Pallas kernels disabled "
            "for this process (XLA fallback paths will serve): %s",
            jax.default_backend(), exc)
        return False


# Mosaic support is NOT all-or-nothing: a backend can accept the trivial
# probe and the blocked top-k kernel yet reject flash attention's lowering
# (observed on the tunneled v5e: top-k compiles and runs, flash attention's
# remote compile crashes). Each kernel family that production code selects
# at runtime therefore probes ITSELF — compile + one real execution, with
# the same block shapes the call sites use — and the result is cached for
# the process. A failed probe logs once and the caller's XLA path serves.

_topk_ok: "bool | None" = None
_flash_ok: "bool | None" = None


def topk_kernel_available() -> bool:
    """The serving top-k family: probe the real blocked kernel."""
    global _topk_ok
    if _topk_ok is None:
        if not pallas_available():
            _topk_ok = False
        else:
            _topk_ok = _probe_kernel_runs(
                # exclude/allowed_mask fold into the always-present
                # `allowed` operand before pallas_call — the probed
                # kernel is identical with or without them
                # pio-lint: disable=probe-arity
                lambda: score_and_top_k_pallas(
                    jnp.zeros((_LANES,), jnp.float32),
                    jnp.zeros((2 * 8192, _LANES), jnp.float32),
                    8, block_items=8192),
                "blocked top-k")
    return _topk_ok


def flash_available() -> bool:
    """The attention family: probe the real flash kernel FORWARD AND
    BACKWARD (training differentiates through it) at the call sites' block
    shapes. First probe compiles two small kernels (seconds, once per
    process, only when a long-sequence workload actually asks)."""
    global _flash_ok
    if _flash_ok is None:
        if not pallas_available():
            _flash_ok = False
        else:
            def probe():
                # [B, S, H, D] with S large enough that the q/kv blocks are
                # the REAL 512-wide call-site shapes, not clamped stubs
                q = jnp.zeros((1, 1024, 1, 64), jnp.float32)
                # kv_valid folds into the always-present `valid` operand
                # (ones when None) — the probed kernel is identical
                # pio-lint: disable=probe-arity
                out = flash_attention(q, q, q, q_block=512, kv_block=512)
                grad = jax.grad(
                    # pio-lint: disable=probe-arity
                    lambda x: jnp.sum(flash_attention(
                        x, x, x, q_block=512, kv_block=512)))(q)
                return out, grad

            _flash_ok = _probe_kernel_runs(probe, "flash attention")
    return _flash_ok


def _probe_kernel_runs(fn, what: str) -> bool:
    import numpy as np

    try:
        out = fn()
        # force real execution (block_until_ready may return early on
        # tunneled backends; a dependent fetch cannot)
        for leaf in jax.tree_util.tree_leaves(out):
            np.asarray(leaf.ravel()[0:1])
        return True
    except Exception as exc:
        import logging

        logging.getLogger(__name__).warning(
            "%s Pallas kernel unsupported on backend %r; the XLA fallback "
            "path serves instead: %s", what, jax.default_backend(),
            str(exc)[:500])
        return False


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# Kernel 1: blocked full-catalog top-K scoring
# ---------------------------------------------------------------------------


def _topk_tile_kernel(q_ref, it_ref, al_ref, out_s_ref, out_i_ref,
                      *, k: int, block_items: int):
    """Score one item block and keep its local top-k.

    q_ref:  [B, Kp]      query factors (replicated across the grid)
    it_ref: [blk, Kp]    this block's item factors
    al_ref: [1, blk]     allow mask (0 = excluded / padding)
    out_*:  [1, B, 128]  this block's candidate slots (first k valid)
    """
    i = pl.program_id(0)
    scores = jax.lax.dot_general(
        q_ref[:], it_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                    # [B, blk]
    b = scores.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    gid = i * block_items + col                          # global item ids
    allowed = al_ref[:] > 0.0                            # [1, blk] → bcast
    scores = jnp.where(allowed, scores, NEG_INF)

    cand_s = jnp.full((b, _LANES), NEG_INF, jnp.float32)
    cand_i = jnp.full((b, _LANES), -1, jnp.int32)
    slot_iota = jax.lax.broadcasted_iota(jnp.int32, (b, _LANES), 1)
    big = jnp.int32(2**31 - 1)
    # k is small and static: unrolled iterative max-select, all VPU work on
    # an in-register [B, blk] tile — no HBM traffic until the final store
    for j in range(k):
        m = jnp.max(scores, axis=1, keepdims=True)       # [B, 1]
        at_max = scores == m
        sel = jnp.min(jnp.where(at_max, gid, big), axis=1, keepdims=True)
        slot = slot_iota == j
        cand_s = jnp.where(slot, m, cand_s)
        cand_i = jnp.where(slot, sel, cand_i)
        scores = jnp.where(gid == sel, NEG_INF, scores)
    out_s_ref[0] = cand_s
    out_i_ref[0] = cand_i


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_items", "interpret"),
)
def _score_topk_pallas(
    queries: jax.Array,             # [B, K] f32
    item_factors: jax.Array,        # [I, K] f32
    allowed: jax.Array,             # [I] f32, 1 = allowed
    k: int,
    block_items: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, rank = queries.shape
    n_items = item_factors.shape[0]
    blk = block_items
    i_pad = _round_up(max(n_items, blk), blk)
    k_pad = _round_up(max(rank, _LANES), _LANES)
    b_pad = _round_up(max(b, 8), 8)

    # shapes are static at trace time: skip the pad-copy entirely when the
    # caller's arrays are already tile-aligned (the serving path stores
    # factors pre-aligned, so the hot path is copy-free)
    q = queries.astype(jnp.float32)
    if (b_pad, k_pad) != q.shape:
        q = jnp.zeros((b_pad, k_pad), jnp.float32).at[:b, :rank].set(q)
    it = item_factors.astype(jnp.float32)
    if (i_pad, k_pad) != it.shape:
        it = jnp.zeros((i_pad, k_pad), jnp.float32).at[:n_items, :rank].set(it)
    al = allowed.astype(jnp.float32)[None]
    if i_pad != n_items:
        al = jnp.zeros((1, i_pad), jnp.float32).at[0, :n_items].set(al[0])

    n_blocks = i_pad // blk
    cand_s, cand_i = pl.pallas_call(
        functools.partial(_topk_tile_kernel, k=k, block_items=blk),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b_pad, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk, k_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, b_pad, _LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b_pad, _LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, b_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_blocks, b_pad, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(q, it, al)

    # merge: [n_blocks, B, 128] → per-query candidate row → exact top-k.
    # Correctness: every global top-k item is, within its own block, among
    # that block's top-k (k ≤ 128 slots kept), so the union of block
    # candidates always contains the exact answer.
    flat_s = cand_s.transpose(1, 0, 2).reshape(b_pad, n_blocks * _LANES)
    flat_i = cand_i.transpose(1, 0, 2).reshape(b_pad, n_blocks * _LANES)
    top_s, pos = jax.lax.top_k(flat_s, k)
    top_i = jnp.take_along_axis(flat_i, pos, axis=1)
    # when fewer than k items are allowed, exhausted blocks select padding
    # columns (gid >= n_items); mark those slots -1 so no out-of-range item
    # id ever escapes to the caller
    top_i = jnp.where(top_s <= NEG_INF / 2, -1, top_i)
    return top_s[:b], top_i[:b]


@functools.partial(
    jax.jit, static_argnames=("k", "block_items", "interpret"))
def _score_and_top_k_pallas_jit(
    user_vector, item_factors, k, exclude, allowed_mask, block_items,
    interpret,
):
    n_items = item_factors.shape[0]
    allowed = (jnp.ones((n_items,), jnp.float32) if allowed_mask is None
               else allowed_mask.astype(jnp.float32))
    if exclude is not None:
        safe = jnp.where(exclude < 0, n_items, exclude)
        allowed = allowed.at[safe].set(0.0, mode="drop")
    top_s, top_i = _score_topk_pallas(
        user_vector[None, :], item_factors, allowed,
        k=k, block_items=block_items, interpret=interpret,
    )
    return jnp.stack([top_s[0], top_i[0].astype(jnp.float32)])


def score_and_top_k_pallas(
    user_vector: jax.Array,         # [K]
    item_factors: jax.Array,        # [I, K]
    k: int,
    exclude: Optional[jax.Array] = None,       # [E] int32, -1 = no-op
    allowed_mask: Optional[jax.Array] = None,  # [I] bool
    block_items: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in Pallas variant of ops.topk.score_and_top_k.

    Returns the same packed [2, k] array (row 0 = scores, row 1 = indices as
    f32) so serving still pays exactly one device→host fetch per query.
    Exclusions are folded into a dense allow-mask (a [n_items] vector is
    bytes even at million-item scale) applied inside the kernel, so an
    excluded item can never displace a real candidate.
    """
    if interpret is None:
        interpret = not pallas_available()
    k = min(k, item_factors.shape[0], _LANES)
    # one fully-jitted dispatch per query: on a tunneled/remote TPU each
    # un-jitted op is a host round trip, which would dwarf the kernel time
    return _score_and_top_k_pallas_jit(
        user_vector, item_factors, k, exclude, allowed_mask, block_items,
        bool(interpret),
    )


# ---------------------------------------------------------------------------
# Kernel 2: fused flash attention
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, val_ref, o_ref,
                  m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, q_block: int,
                  kv_block: int, n_kv_blocks: int):
    """One (batch·head, q-block, kv-block) program — the KV scan is the
    grid's MINOR dimension, so VMEM holds only one [kb, D] K/V tile at a
    time (the full-KV-resident layout capped sequence length at ~6k before
    scoped-VMEM OOM; this scales to any S). The online-softmax state
    (m, l, acc) lives in VMEM scratch, which Mosaic persists across grid
    steps that revisit the same output block.

    q_ref:   [1, qb, D]   this q block (constant across the kv dim)
    k_ref:   [1, kb, D]   this kv block
    v_ref:   [1, kb, D]
    val_ref: [1, 1, kb]   key validity (padding/ragged mask)
    o_ref:   [1, qb, D]   revisited; written on the last kv step
    """
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    qb = q_ref.shape[1]
    # causal: kv blocks fully in this q block's future contribute nothing
    live = (not causal) or (j * kv_block <= qi * q_block + qb - 1)

    @pl.when(live)
    def _step():
        q_tile = q_ref[0].astype(jnp.float32) * scale    # [qb, D]
        q_pos = qi * q_block + jax.lax.broadcasted_iota(
            jnp.int32, (qb, 1), 0)                       # [qb, 1]
        k_blk = k_ref[0].astype(jnp.float32)             # [kb, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_tile, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [qb, kb]
        kv_pos = j * kv_block + jax.lax.broadcasted_iota(
            jnp.int32, (1, kv_block), 1)
        mask = val_ref[0, 0, :][None, :] > 0.0
        if causal:
            mask = mask & (q_pos >= kv_pos)
        s = jnp.where(mask, s, MASK_VALUE)
        # online softmax — identical update rule to ops/attention.py
        # _online_block so sharded and single-chip numerics agree
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_kv_blocks - 1)
    def _finish():
        l = l_ref[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)             # fully masked → 0
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "q_block", "kv_block", "interpret",
                     "n_heads"),
)
def _flash_bhsd(
    q: jax.Array,                   # [BH, Sq, D]
    k: jax.Array,                   # [BH, Skv, D]
    v: jax.Array,
    valid: jax.Array,               # [B, 1, Skv] f32
    n_heads: int,
    causal: bool,
    scale: float,
    q_block: int,
    kv_block: int,
    interpret: bool,
) -> jax.Array:
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    qb = min(q_block, _round_up(s_q, 8))
    kb = min(kv_block, _round_up(s_kv, 8))
    sq_pad = _round_up(s_q, qb)
    skv_pad = _round_up(s_kv, kb)
    qp = jnp.pad(q, ((0, 0), (0, sq_pad - s_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, skv_pad - s_kv), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, skv_pad - s_kv), (0, 0)))
    valp = jnp.pad(valid, ((0, 0), (0, 0), (0, skv_pad - s_kv)))  # pads invalid
    n_q_blocks = sq_pad // qb
    n_kv_blocks = skv_pad // kb

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, q_block=qb,
            kv_block=kb, n_kv_blocks=n_kv_blocks),
        # kv is the MINOR grid dim: programs revisiting one (bh, q-block)
        # output run consecutively, carrying the softmax state in scratch
        grid=(bh, n_q_blocks, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, qb, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kb, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kb, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            # [B, 1, S] so the trailing block dims satisfy Mosaic's
            # (sublane, lane) tiling rule for any batch size
            pl.BlockSpec((1, 1, kb), lambda b, i, j: (b // n_heads, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, qb, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((qb, 1), jnp.float32),   # running max m
            pltpu.VMEM((qb, 1), jnp.float32),   # running sum l
            pltpu.VMEM((qb, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp, valp)
    return out[:, :s_q, :]


@functools.lru_cache(maxsize=None)
def _flash_with_vjp(causal: bool, scale: float, q_block: int, kv_block: int,
                    interpret: bool):
    """custom_vjp closure over the static config.

    Mosaic kernels are not reverse-differentiable, but the sequence engines
    train through their attention op (ops/transformer.py _fit_scan), so the
    fused kernel must be usable under ``value_and_grad``. Forward runs the
    Pallas kernel; backward differentiates the XLA blockwise path
    (ops/attention.py), which implements the *same* online-softmax update
    rule — a recompute-based backward with O(S·block) memory, no [S, S]
    residuals."""
    from incubator_predictionio_tpu.ops.attention import blockwise_attention

    def forward(q, k, v, valid):
        b, s_q, h, d = q.shape

        def to_bhsd(x):
            return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

        out = _flash_bhsd(
            to_bhsd(q), to_bhsd(k), to_bhsd(v), valid[:, None, :],
            n_heads=h, causal=causal, scale=scale,
            q_block=q_block, kv_block=kv_block, interpret=interpret,
        )
        return out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)

    @jax.custom_vjp
    def f(q, k, v, valid):
        return forward(q, k, v, valid)

    def fwd(q, k, v, valid):
        return forward(q, k, v, valid), (q, k, v, valid)

    def bwd(res, g):
        q, k, v, valid = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(
                q_, k_, v_, causal=causal, block_size=kv_block, scale=scale,
                kv_valid=valid > 0.0),
            q, k, v,
        )
        dq, dk, dv = vjp(g)
        return dq, dk, dv, jnp.zeros_like(valid)

    f.defvjp(fwd, bwd)
    return f


#: measured per-length block optima on v5e (scripts/flash_tune.py,
#: dispatch-amortized, jitted both sides; re-run after kernel/toolchain
#: changes). Keys are the smallest sweep length ≥ S; larger S reuse the
#: longest entry. Override per deployment:
#: PIO_FLASH_BLOCKS="8192:2048x512,16384:1024x1024,32768:1024x1024"
_FLASH_BLOCK_TABLE: "tuple" = (
    # (max_seq, q_block, kv_block)
    (8192, 2048, 512),      # 3.99 ms vs 13.10 ms XLA blockwise (3.3×)
    (16384, 1024, 1024),    # 9.06 ms vs 38.51 ms (4.3×)
    (1 << 62, 1024, 1024),  # 27.97 ms vs 161 ms at 32k (5.8×)
)


def _parse_block_env() -> "Optional[tuple]":
    raw = os.environ.get("PIO_FLASH_BLOCKS", "").strip()
    if not raw:
        return None
    try:
        entries = []
        for part in raw.split(","):
            s, _, qk = part.partition(":")
            qb, _, kb = qk.partition("x")
            entry = (int(s), int(qb), int(kb))
            if min(entry) <= 0:
                raise ValueError("block sizes must be positive")
            entries.append(entry)
        entries.sort()
        # the last entry also covers every longer sequence
        entries[-1] = (1 << 62, entries[-1][1], entries[-1][2])
        return tuple(entries)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring malformed PIO_FLASH_BLOCKS=%r "
            "(want e.g. 8192:2048x512,16384:1024x1024)", raw)
        return None


_FLASH_BLOCKS_ACTIVE = _parse_block_env() or _FLASH_BLOCK_TABLE


def default_flash_blocks(s_q: int) -> "tuple":
    """(q_block, kv_block) for sequence length ``s_q`` from the measured
    table (or the PIO_FLASH_BLOCKS override)."""
    for max_s, qb, kb in _FLASH_BLOCKS_ACTIVE:
        if s_q <= max_s:
            return qb, kb
    return 1024, 1024


def flash_attention(
    q: jax.Array,                   # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_valid: Optional[jax.Array] = None,   # [S] or [B, S] bool
    q_block: Optional[int] = None,
    kv_block: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention on BSHD arrays; same contract as
    ops.attention.dot_product_attention / blockwise_attention.

    K/V stream through VMEM one [kv_block, D] tile at a time (the kv scan
    is a grid dimension; the online-softmax state rides in VMEM scratch),
    so VMEM use is S-independent — any sequence length fits, and causal
    query blocks skip their strictly-future KV blocks. The [S, S] logit
    matrix never exists in HBM. Block defaults come from the measured
    per-length table (:data:`_FLASH_BLOCK_TABLE`, scripts/flash_tune.py
    sweep on v5e; PIO_FLASH_BLOCKS overrides): with them flash beats the
    XLA blockwise scan 3.3× at S=8k, 4.3× at 16k and 5.8× at 32k —
    transformer._default_attn routes to flash above FLASH_MIN_SEQ.
    Differentiable: backward runs through the XLA blockwise reference
    (see :func:`_flash_with_vjp`).
    """
    if interpret is None:
        interpret = not pallas_available()
    b, _s_q, _h, d = q.shape
    s_kv = k.shape[1]
    sc = scale if scale is not None else d ** -0.5
    if q_block is None or kv_block is None:
        dq, dk = default_flash_blocks(_s_q)
        q_block = dq if q_block is None else q_block
        kv_block = dk if kv_block is None else kv_block

    if kv_valid is None:
        valid = jnp.ones((b, s_kv), jnp.float32)
    elif kv_valid.ndim == 1:
        valid = jnp.broadcast_to(
            kv_valid.astype(jnp.float32)[None, :], (b, s_kv))
    else:
        valid = kv_valid.astype(jnp.float32)

    fn = _flash_with_vjp(bool(causal), float(sc), int(q_block),
                         int(kv_block), bool(interpret))
    return fn(q, k, v, valid)


# ---------------------------------------------------------------------------
# Kernel 3: fused ALS bucket solve (Gram + CG entirely in VMEM)
# ---------------------------------------------------------------------------
#
# The ALS half-sweep's HBM profile under the XLA path (ops/als.py) is
# dominated by the [rows, K, K] Gram batch: one write at assembly plus one
# full re-read per CG iteration — (1 + iters)·rows·K² elements per side
# (~32 GB of the ~42 GB user-side stream at ML-20M/bf16). This kernel
# removes that stream entirely: each program streams one row's gathered
# factor blocks [dt, K] through VMEM, accumulates the K×K Gram and the rhs
# in VMEM scratch, then runs ALL Jacobi-PCG iterations against the
# VMEM-resident Gram and writes only the [K] solution back to HBM. Per-row
# HBM traffic drops from (1+iters)·K² + D·K to D·K — the gathered blocks,
# read exactly once.
#
# (The verdict-suggested alternative — Gram-free CG as two thin einsums
# per iteration — RAISES traffic at bench shapes: its per-iteration stream
# is 2·nnz·K vs the Gram re-read's rows·K², a ratio of 2·D̄/K ≈ 2.3× on
# the ML-20M user side and ≈ 11.7× on the item side. Keeping the Gram but
# pinning it in VMEM beats both.)


def _als_cg_kernel(g_ref, wv_ref, lam_ref, x0_ref, o_ref, gram_ref,
                   rhs_ref, *, iters: int, n_d_blocks: int, precise: bool,
                   warm: bool):
    """One (row, d-block) program of the fused bucket solve.

    Mosaic block-shape note: the TPU lowering requires each of the last
    two block dims to be sublane/lane aligned (8/128) OR equal to the
    array dim. A [B, dt]-shaped aux with block (1, dt) violates the
    sublane rule, so every per-row aux rides as [B, 1, x] with block
    (1, 1, x) — last-two dims (1, x) equal the array dims exactly.

    g_ref:   [1, dt, Kp]  this row's masked gathered factors, one d tile
                          (bf16 on the fast schedule; mask already applied,
                          so gram = gᵗg and rhs = wvᵗg need no masking here
                          — mask² == mask)
    wv_ref:  [1, 1, dt]   vals·mask d tile, f32 (legal under the rule
                          above: sublane dim 1 equals the array dim 1,
                          lane dim dt is a 128 multiple)
    lam_ref: [1, 1, Kp]   per-row ridge λ(+λ·nnz), broadcast across K
                          (f32; applied INSIDE the matvec so the Gram can
                          stay in its compute dtype without rounding the
                          regularizer)
    x0_ref:  [1, 1, Kp]   CG warm start (zeros + ``warm=False`` → cold)
    o_ref:   [1, 1, Kp]   solution, written on the last d step
    gram/rhs scratch persist across the d-minor grid steps (flash-kernel
    accumulator pattern).
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        gram_ref[...] = jnp.zeros_like(gram_ref)
        rhs_ref[...] = jnp.zeros_like(rhs_ref)

    g = g_ref[0]                                         # [dt, Kp]
    wv = wv_ref[0]                                       # [1, dt]
    # bf16 inputs take the MXU single-pass (DEFAULT); the f32 polish path
    # pins HIGHEST so its Gram never silently truncates to bf16 passes —
    # the exact failure mode the XLA path documents (_solve_bucket:
    # "DEFAULT precision stalls ALS convergence around RMSE 0.6")
    prec = (jax.lax.Precision.HIGHEST if precise
            else jax.lax.Precision.DEFAULT)
    gram_ref[...] += jax.lax.dot_general(
        g, g, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )
    rhs_ref[...] += jax.lax.dot_general(
        wv.astype(g.dtype), g,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )

    @pl.when(j == n_d_blocks - 1)
    def _solve():
        gram = gram_ref[...]                             # [Kp, Kp] f32
        lam = lam_ref[0]                                 # [1, Kp]
        kp = gram.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 1)
        diag = jnp.sum(jnp.where(row == col, gram, 0.0), axis=0,
                       keepdims=True) + lam              # [1, Kp]
        minv = jnp.where(diag > 0, 1.0 / diag, 0.0)
        b = rhs_ref[...]                                 # [1, Kp]

        # Jacobi-PCG, numerics matching ops/als.py _cg_solve_spd:
        # cold x = 0 start or warm start from the previous sweep
        # (one extra matvec for the initial residual); division guards
        # make converged/empty systems fixed points (rank-padding coords
        # have b = 0, gram row 0 → they stay exactly 0: a zero x0 row
        # keeps the cold fixed point)
        def matvec(p):
            return jax.lax.dot_general(
                p, gram, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            ) + lam * p                                  # [1, Kp]

        def body(_, carry):
            x, r, p, rz = carry
            ap = matvec(p)
            pap = jnp.sum(p * ap, keepdims=True)[..., :1]   # [1, 1]
            alpha = jnp.where(pap > 0, rz / pap, 0.0)
            x = x + alpha * p
            r = r - alpha * ap
            z = minv * r
            rz2 = jnp.sum(r * z, keepdims=True)[..., :1]
            beta = jnp.where(rz > 0, rz2 / rz, 0.0)
            p = z + beta * p
            return x, r, p, rz2

        if warm:
            x0 = x0_ref[0]                               # [1, Kp]
            r0 = b - matvec(x0)
        else:
            x0 = jnp.zeros_like(b)
            r0 = b
        z0 = minv * r0
        rz0 = jnp.sum(r0 * z0, keepdims=True)[..., :1]
        x, _r, _p, _rz = jax.lax.fori_loop(
            0, iters, body, (x0, r0, z0, rz0))
        o_ref[0] = x


def _als_cg_kernel_rows(g_ref, wv_ref, lam_ref, x0_ref, o_ref, gram_ref,
                        rhs_ref, *, iters: int, n_d_blocks: int,
                        precise: bool, warm: bool):
    """Row-grouped variant of :func:`_als_cg_kernel`: R rows per program.

    The one-row kernel is per-program-overhead-bound at ML-20M shape
    (~165k programs per half-sweep, each with ~0.1 µs of real work);
    grouping R=8 sublane-aligned rows cuts the program count 8× and
    batches the CG across the group. Aux arrays are plain 2-D here —
    an R-row block satisfies Mosaic's sublane rule directly.

    g_ref:   [R, dt, Kp]  row group's masked gathered factors, one d tile
    wv_ref:  [R, dt]      vals·mask tile, f32
    lam_ref: [R, Kp]      per-row ridge, broadcast across K
    x0_ref:  [R, Kp]      CG warm start (zeros + ``warm=False`` → cold)
    o_ref:   [R, Kp]      solutions, written on the last d step
    gram/rhs scratch: [R, Kp, Kp] / [R, Kp], persist across d steps.
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        gram_ref[...] = jnp.zeros_like(gram_ref)
        rhs_ref[...] = jnp.zeros_like(rhs_ref)

    g = g_ref[...]                                       # [R, dt, Kp]
    wv = wv_ref[...].astype(g.dtype)                     # [R, dt]
    prec = (jax.lax.Precision.HIGHEST if precise
            else jax.lax.Precision.DEFAULT)
    # Mosaic's dot lowering is 2-D only (batched dot_general fails to
    # parse) — unroll the static R rows; each Gram update stays one
    # [dt,Kp]ᵗ[dt,Kp] MXU pass
    for r in range(g.shape[0]):
        g_r = g[r]                                       # [dt, Kp]
        gram_ref[r] += jax.lax.dot_general(
            g_r, g_r, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        rhs_ref[r:r + 1] += jax.lax.dot_general(
            wv[r:r + 1], g_r, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )

    @pl.when(j == n_d_blocks - 1)
    def _solve():
        gram = gram_ref[...]                             # [R, Kp, Kp] f32
        lam = lam_ref[...]                               # [R, Kp]
        r_n, kp = gram.shape[0], gram.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (r_n, kp, kp), 1)
        col = jax.lax.broadcasted_iota(jnp.int32, (r_n, kp, kp), 2)
        diag = jnp.sum(jnp.where(row == col, gram, 0.0), axis=1) + lam
        minv = jnp.where(diag > 0, 1.0 / diag, 0.0)      # [R, Kp]
        b = rhs_ref[...]                                 # [R, Kp]

        def matvec(p):
            # gram is symmetric; [R,Kp,Kp]·[R,Kp] as a VPU
            # broadcast-reduce (8·128² f32 — tiny), sidestepping
            # Mosaic's 2-D-only dots for the batched case
            return jnp.sum(gram * p[:, :, None], axis=1) + lam * p

        # batched Jacobi-PCG, numerics per ops/als.py _cg_solve_spd;
        # every reduction is per-row so groups never mix. Cold x = 0 or
        # warm start from the previous sweep (one extra matvec); zero
        # padding rows keep the cold fixed point either way
        def body(_, carry):
            x, r, p, rz = carry
            ap = matvec(p)
            pap = jnp.sum(p * ap, axis=1, keepdims=True)    # [R, 1]
            alpha = jnp.where(pap > 0, rz / pap, 0.0)
            x = x + alpha * p
            r = r - alpha * ap
            z = minv * r
            rz2 = jnp.sum(r * z, axis=1, keepdims=True)
            beta = jnp.where(rz > 0, rz2 / rz, 0.0)
            p = z + beta * p
            return x, r, p, rz2

        if warm:
            x0 = x0_ref[...]                             # [R, Kp]
            r0 = b - matvec(x0)
        else:
            x0 = jnp.zeros_like(b)
            r0 = b
        z0 = minv * r0
        rz0 = jnp.sum(r0 * z0, axis=1, keepdims=True)
        x, _r, _p, _rz = jax.lax.fori_loop(
            0, iters, body, (x0, r0, z0, rz0))
        o_ref[...] = x


def als_padded_dims(d: int, k: int) -> Tuple[int, int]:
    """(dp, kp) padding of :func:`als_solve_cg_pallas` — THE single copy
    of its padding math; the kernel and its chunk-sizing callers both
    derive from this so they can never drift."""
    return max(_LANES, _round_up(d, _LANES)), _round_up(k, _LANES)


def als_padded_row_elems(d: int, k: int) -> int:
    """Per-row element footprint of the [B, dp, kp] gather the kernel
    materializes (ops/als.py _solve_bucket_chunked sizes HBM chunks with
    this)."""
    dp, kp = als_padded_dims(d, k)
    return dp * kp


#: rows per program for the fused ALS solve. 1 = the proven one-program-
#: per-row layout; 8 = sublane-aligned row groups (8× fewer programs,
#: batched CG) — the per-program-overhead lever. Sweep on chip with
#: scripts/als_kernel_bench.py (PIO_TUNE_ROWS) before changing the
#: default.
_ALS_ROWS = int(os.environ.get("PIO_ALS_KERNEL_ROWS", "1"))


def als_solve_cg_pallas(
    table: jax.Array,              # [M, K] factor table (bf16 fast path)
    cols: jax.Array,               # [B, D] int32
    vals: jax.Array,               # [B, D] f32
    mask: jax.Array,               # [B, D] f32 in {0, 1}
    l2: float,
    reg_nnz: bool = True,
    iters: int = 16,
    interpret: Optional[bool] = None,
    rows_per_program: Optional[int] = None,
    x0: Optional[jax.Array] = None,   # [B, K] f32 CG warm start
) -> jax.Array:
    """Fused normal-equation solve for one bucket chunk → [B, K] f32.

    Drop-in for the explicit-feedback CG leg of ops/als.py _solve_bucket
    (same regularization semantics: λ·max(nnz,1) ridge when ``reg_nnz``,
    plain λ otherwise; empty rows solve to 0). The gather stays in XLA —
    one [B, D, K] masked-gather pass — and this kernel consumes it in one
    streamed read; the [B, K, K] Gram batch never touches HBM.

    D is padded to a lane multiple (min 128) and K to a 128 multiple;
    padding columns carry zero mask/vals and padding rank coordinates
    solve to exactly 0 (see kernel docstring), so the slice-back is
    exact. ``rows_per_program`` > 1 (sublane multiples only) pads the row
    count and runs the row-grouped kernel; padding rows carry zero
    mask/vals and solve to exactly 0, sliced away on return. ``x0``
    warm-starts the in-VMEM CG from the previous sweep's factors (rank
    padding rides as zero columns, which stay exact fixed points).
    """
    if interpret is None:
        interpret = not pallas_available()
    rows = _ALS_ROWS if rows_per_program is None else int(rows_per_program)
    # group sizes must satisfy Mosaic's sublane rule: 1 (the [B,1,x] aux
    # layout) or a multiple of 8 (a (rows, dt) block). Anything else is
    # rounded UP to the next legal group instead of crashing the
    # lowering mid-training.
    rows = 1 if rows <= 1 else _round_up(rows, 8)
    B, d = cols.shape
    k = table.shape[1]
    dp, kp = als_padded_dims(d, k)
    # dt must DIVIDE dp or the floored grid would silently skip the
    # remainder tile (dp is always a multiple of 128, so 128 divides)
    dt = next(t for t in (512, 256, 128) if dp % t == 0)

    gathered = table[cols]                               # [B, D, K]
    g = gathered * mask[..., None].astype(gathered.dtype)
    wv2 = jnp.pad((vals * mask).astype(jnp.float32),
                  ((0, 0), (0, dp - d)))
    nnz = jnp.sum(mask, axis=-1)
    lam = l2 * (jnp.maximum(nnz, 1.0) if reg_nnz
                else jnp.ones_like(nnz))
    warm = x0 is not None
    x0p = (jnp.pad(x0.astype(jnp.float32), ((0, 0), (0, kp - k)))
           if warm else None)
    n_d = dp // dt

    if rows > 1:
        bp = _round_up(B, rows)
        g = jnp.pad(g, ((0, bp - B), (0, dp - d), (0, kp - k)))
        wv2 = jnp.pad(wv2, ((0, bp - B), (0, 0)))
        # padding rows get λ of an empty system (b = 0, gram = 0 → x = 0)
        lam_b = jnp.pad(jnp.broadcast_to(lam[:, None], (B, kp)),
                        ((0, bp - B), (0, 0)), constant_values=1.0)
        # the x0 operand exists only on the warm path — cold kernels
        # never read it, so a zeros buffer would be pure padding traffic
        ops = [g, wv2, lam_b]
        in_specs = [
            pl.BlockSpec((rows, dt, kp), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, dt), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ]
        if warm:
            ops.append(jnp.pad(x0p, ((0, bp - B), (0, 0))))
            in_specs.append(pl.BlockSpec((rows, kp), lambda i, j: (i, 0),
                                         memory_space=pltpu.VMEM))
        body = functools.partial(_als_cg_kernel_rows, iters=int(iters),
                                 n_d_blocks=n_d,
                                 precise=table.dtype == jnp.float32,
                                 warm=warm)
        if warm:
            kfn = body
        else:
            # positional ref alignment: without the x0 operand the
            # kernel signature's x0_ref slot must not swallow o_ref
            def kfn(g_ref, wv_ref, lam_ref, o_ref, gram_ref, rhs_ref):
                return body(g_ref, wv_ref, lam_ref, None, o_ref,
                            gram_ref, rhs_ref)
        out = pl.pallas_call(
            kfn,
            grid=(bp // rows, n_d),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((rows, kp), lambda i, j: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((bp, kp), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((rows, kp, kp), jnp.float32),  # gram acc
                pltpu.VMEM((rows, kp), jnp.float32),      # rhs acc
            ],
            interpret=interpret,
        )(*ops)
        return out[:B, :k]

    g = jnp.pad(g, ((0, 0), (0, dp - d), (0, kp - k)))
    # per-row auxes ride as [B, 1, x] — see kernel docstring block note
    wv = wv2[:, None, :]
    lam_b = jnp.broadcast_to(lam[:, None, None], (B, 1, kp))

    ops = [g, wv, lam_b]
    in_specs = [
        pl.BlockSpec((1, dt, kp), lambda i, j: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, dt), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    if warm:
        # cold kernels never read x0 — the operand only exists warm
        ops.append(x0p[:, None, :])
        in_specs.append(pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                                     memory_space=pltpu.VMEM))
    body1 = functools.partial(_als_cg_kernel, iters=int(iters),
                              n_d_blocks=n_d,
                              precise=table.dtype == jnp.float32,
                              warm=warm)
    if warm:
        kfn1 = body1
    else:
        def kfn1(g_ref, wv_ref, lam_ref, o_ref, gram_ref, rhs_ref):
            return body1(g_ref, wv_ref, lam_ref, None, o_ref, gram_ref,
                         rhs_ref)
    out = pl.pallas_call(
        kfn1,
        # d is the MINOR grid dim: programs revisiting one row's output
        # run consecutively, carrying gram/rhs in scratch
        grid=(B, n_d),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 1, kp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((kp, kp), jnp.float32),   # gram accumulator
            pltpu.VMEM((1, kp), jnp.float32),    # rhs accumulator
        ],
        interpret=interpret,
    )(*ops)
    return out[:, 0, :k]


# ---------------------------------------------------------------------------
# Kernel 4: fully fused ALS bucket solve (gather + Gram + CG in VMEM)
# ---------------------------------------------------------------------------
#
# Kernel 3 removed the [rows, K, K] Gram stream but still consumes an
# XLA-materialized [B, D, K] gather — one full HBM write + read of
# nnz·K elements per half-sweep. When the OTHER side's factor table fits
# VMEM (the ML-20M item table: 26.7k × 128 bf16 ≈ 6.9 MB), this kernel
# removes that stream too: the whole table rides into VMEM once per
# program chain, each program gathers its row's factor blocks directly
# from the VMEM-resident table (jnp.take on the loaded block), weights
# them, accumulates the K×K Gram and rhs in scratch, and runs every CG
# iteration in VMEM. Per-row HBM traffic drops from dp·K (the gather
# read) + 3·dp (cols/vals/mask) to just 3·dp + K — the interaction
# triplets and the solution.
#
# One kernel covers all three production variants: explicit ALS-WR
# (λ(·nnz) ridge), implicit Hu-Koren-Volinsky (the batch-shared YᵗY term
# rides as one [K, K] operand added inside the matvec — never
# materialized per row), and CG warm start (``x0``). The per-entry
# weights are folded host/XLA-side into two [B, D] vectors so the kernel
# body is variant-free:
#
#   gram_w  = mask            (explicit)   | α·r·mask        (implicit)
#   rhs_w   = vals·mask       (explicit)   | (1 + α·r)·mask  (implicit)
#   gram   += Σ_d gram_w_d · t_d t_dᵀ ;  rhs += Σ_d rhs_w_d · t_d
#
# (identical to ops/als._gram_rhs_nnz term-for-term: mask² == mask and
# the implicit confidences already carry the mask factor).


def _als_fused_kernel(tab_ref, cols_ref, gw_ref, rw_ref, lam_ref, yty_ref,
                      x0_ref, o_ref, gram_ref, rhs_ref, *, iters: int,
                      n_d_blocks: int, precise: bool, warm: bool,
                      shared: bool):
    """One (row, d-block) program of the fused gather+Gram+CG solve.

    tab_ref:  [Mp, Kp]    the WHOLE other-side factor table (block == array
                          → trivially Mosaic-legal; the index map is
                          constant so the pipeline keeps it VMEM-resident
                          across grid steps)
    cols_ref: [1, 1, dt]  this row's interaction column ids, one d tile
    gw_ref:   [1, 1, dt]  per-entry Gram weight (see module comment)
    rw_ref:   [1, 1, dt]  per-entry rhs weight, f32
    lam_ref:  [1, 1, Kp]  per-row ridge, broadcast across K
    yty_ref:  [Kp, Kp]    batch-shared implicit term (``shared`` only)
    x0_ref:   [1, 1, Kp]  CG warm start (``warm`` only)
    o_ref:    [1, 1, Kp]  solution, written on the last d step
    gram/rhs scratch persist across the d-minor grid steps."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        gram_ref[...] = jnp.zeros_like(gram_ref)
        rhs_ref[...] = jnp.zeros_like(rhs_ref)

    idx = cols_ref[0, 0]                                 # [dt] int32
    tab = tab_ref[...]                                   # [Mp, Kp]
    g = jnp.take(tab, idx, axis=0)                       # [dt, Kp] in VMEM
    # weights ∈ {0,1}·stuff with the mask already folded in, so padding
    # entries (idx 0) contribute exactly 0 to gram AND rhs
    gw = gw_ref[0, 0].astype(g.dtype)                    # [dt]
    rw = rw_ref[0]                                       # [1, dt] f32
    prec = (jax.lax.Precision.HIGHEST if precise
            else jax.lax.Precision.DEFAULT)
    gram_ref[...] += jax.lax.dot_general(
        g * gw[:, None], g, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )
    rhs_ref[...] += jax.lax.dot_general(
        rw.astype(g.dtype), g, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )

    @pl.when(j == n_d_blocks - 1)
    def _solve():
        gram = gram_ref[...]                             # [Kp, Kp] f32
        lam = lam_ref[0]                                 # [1, Kp]
        kp = gram.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 1)
        diag = jnp.sum(jnp.where(row == col, gram, 0.0), axis=0,
                       keepdims=True) + lam              # [1, Kp]
        if shared:
            yty = yty_ref[...]                           # [Kp, Kp] f32
            diag = diag + jnp.sum(jnp.where(row == col, yty, 0.0),
                                  axis=0, keepdims=True)
        minv = jnp.where(diag > 0, 1.0 / diag, 0.0)
        b = rhs_ref[...]                                 # [1, Kp]

        # Jacobi-PCG, numerics matching ops/als.py _cg_solve_spd: the
        # ridge (and the shared YᵗY) stay OUT of the matrix, applied
        # inside the matvec in f32; division guards make converged/empty
        # systems fixed points (zero rows/rank padding stay exactly 0)
        def matvec(p):
            ap = jax.lax.dot_general(
                p, gram, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            ) + lam * p                                  # [1, Kp]
            if shared:
                ap = ap + jax.lax.dot_general(
                    p, yty, dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
            return ap

        def body(_, carry):
            x, r, p, rz = carry
            ap = matvec(p)
            pap = jnp.sum(p * ap, keepdims=True)[..., :1]   # [1, 1]
            alpha = jnp.where(pap > 0, rz / pap, 0.0)
            x = x + alpha * p
            r = r - alpha * ap
            z = minv * r
            rz2 = jnp.sum(r * z, keepdims=True)[..., :1]
            beta = jnp.where(rz > 0, rz2 / rz, 0.0)
            p = z + beta * p
            return x, r, p, rz2

        if warm:
            x0 = x0_ref[0]                               # [1, Kp]
            r0 = b - matvec(x0)
        else:
            x0 = jnp.zeros_like(b)
            r0 = b
        z0 = minv * r0
        rz0 = jnp.sum(r0 * z0, keepdims=True)[..., :1]
        x, _r, _p, _rz = jax.lax.fori_loop(
            0, iters, body, (x0, r0, z0, rz0))
        o_ref[0] = x


def als_fused_row_elems(d: int, k: int) -> int:
    """Per-row HBM element footprint of the fused-gather path: the
    cols/gram-weight/rhs-weight tiles plus the lam/x0/out vectors — the
    [B, dp, kp] gather of the two-stage path never materializes, so
    chunk sizing (ops/als.py _solve_bucket_chunked) keys on this much
    smaller figure."""
    dp, kp = als_padded_dims(d, k)
    return 3 * dp + 3 * kp


def als_fused_table_bytes(m_rows: int, rank: int, dtype=jnp.float32) -> int:
    """VMEM bytes of the padded gather table the fused kernel pins."""
    kp = _round_up(max(rank, 1), _LANES)
    mp = _round_up(max(m_rows, 8), 8)
    return mp * kp * jnp.dtype(dtype).itemsize


def als_fused_vmem_budget_bytes() -> int:
    """Table budget for the fused-gather kernel (``PIO_ALS_FUSED_VMEM_MB``,
    default 10 MB). VMEM is ~16 MB/core on current TPUs; the budget
    covers the resident table only — the double-buffered [dt, Kp] tiles,
    the [Kp, Kp] Gram scratch and the CG vectors ride in the remainder
    (≲ 0.5 MB at dt=512, K=128). Read per call, never frozen at import."""
    try:
        mb = float(os.environ.get("PIO_ALS_FUSED_VMEM_MB", "") or 10.0)
    except ValueError:
        mb = 10.0
    return int(mb * (1 << 20))


def als_fused_fits(m_rows: int, rank: int, dtype=jnp.float32) -> bool:
    """True when the other-side table fits the fused kernel's VMEM
    budget. At ML-20M shape: the item table (26.7k × 128 bf16 ≈ 6.9 MB)
    fits — the USER half-sweep (the heavy side) runs fully fused; the
    user table (138k × 128 ≈ 35 MB bf16) does not — the item half-sweep
    keeps the two-stage kernel. The check is pure host arithmetic on
    static shapes, resolved OUTSIDE any trace."""
    return als_fused_table_bytes(m_rows, rank, dtype) \
        <= als_fused_vmem_budget_bytes()


def als_fused_solve_cg_pallas(
    table: jax.Array,              # [M, K] gather source (bf16 fast path)
    cols: jax.Array,               # [B, D] int32
    vals: jax.Array,               # [B, D] f32
    mask: jax.Array,               # [B, D] f32 in {0, 1}
    l2,
    reg_nnz: bool = True,
    iters: int = 16,
    implicit: bool = False,
    alpha: float = 1.0,
    yty: Optional[jax.Array] = None,   # [K, K] f32 — implicit only
    x0: Optional[jax.Array] = None,    # [B, K] f32 CG warm start
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused gather+normal-equation solve for one bucket chunk → [B, K].

    Same contract as the explicit-CG leg of ops/als.py ``_solve_bucket``
    (and, with ``implicit=True`` + ``yty``, as ``_solve_bucket_implicit``
    at the caller's doubled budget): λ·max(nnz,1) / λ ridge, empty rows
    solve to exactly 0. Unlike :func:`als_solve_cg_pallas`, the gather
    happens INSIDE the kernel against the VMEM-resident table — callers
    must gate on :func:`als_fused_fits` for the table's shape/dtype.
    Padding (D → lane multiple, K → 128 multiple, padding cols id 0 with
    zero weights) is exact: padded coordinates stay fixed at 0.

    The in-kernel gather is a ``jnp.take`` on the loaded table block —
    exact in interpret mode; on real Mosaic backends the per-variant
    probe (:func:`als_kernel_available` ``fused=True``) decides whether
    this lowering compiles before production selects it."""
    if interpret is None:
        interpret = not pallas_available()
    B, d = cols.shape
    m, k = table.shape
    dp, kp = als_padded_dims(d, k)
    mp = _round_up(max(m, 8), 8)
    # dt must DIVIDE dp (dp is always a 128 multiple, so 128 divides)
    dt = next(t for t in (512, 256, 128) if dp % t == 0)
    n_d = dp // dt

    tab = table
    if (mp, kp) != tab.shape:
        tab = jnp.zeros((mp, kp), table.dtype).at[:m, :k].set(tab)
    maskf = mask.astype(jnp.float32)
    if implicit:
        gw = alpha * vals * maskf          # (c − 1), 0 on padding
        rw = maskf + gw                    # (1 + α·r)·mask
    else:
        gw = maskf
        rw = vals * maskf
    colsp = jnp.pad(cols, ((0, 0), (0, dp - d)))[:, None, :]
    gw = jnp.pad(gw, ((0, 0), (0, dp - d)))[:, None, :]
    rw = jnp.pad(rw, ((0, 0), (0, dp - d)))[:, None, :]
    nnz = jnp.sum(maskf, axis=-1)
    if implicit:
        lam = jnp.full_like(nnz, l2)
    else:
        lam = l2 * (jnp.maximum(nnz, 1.0) if reg_nnz
                    else jnp.ones_like(nnz))
    lam_b = jnp.broadcast_to(lam[:, None, None], (B, 1, kp))
    shared = implicit
    warm = x0 is not None

    ops = [tab, colsp, gw, rw, lam_b]
    in_specs = [
        pl.BlockSpec((mp, kp), lambda i, j: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, dt), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, dt), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, dt), lambda i, j: (i, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    if shared:
        ytyp = yty.astype(jnp.float32)
        if (kp, kp) != ytyp.shape:
            ytyp = jnp.zeros((kp, kp), jnp.float32).at[:k, :k].set(ytyp)
        ops.append(ytyp)
        in_specs.append(pl.BlockSpec((kp, kp), lambda i, j: (0, 0),
                                     memory_space=pltpu.VMEM))
    if warm:
        ops.append(jnp.pad(x0.astype(jnp.float32),
                           ((0, 0), (0, kp - k)))[:, None, :])
        in_specs.append(pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                                     memory_space=pltpu.VMEM))
    body = functools.partial(_als_fused_kernel, iters=int(iters),
                             n_d_blocks=n_d,
                             precise=table.dtype == jnp.float32,
                             warm=warm, shared=shared)
    # positional ref alignment: absent optional operands must not let a
    # later ref slot swallow o_ref (same pattern as als_solve_cg_pallas)
    if shared and warm:
        kfn = body
    elif shared:
        def kfn(t, c, g, r, l, y, o, gr, rh):
            return body(t, c, g, r, l, y, None, o, gr, rh)
    elif warm:
        def kfn(t, c, g, r, l, x, o, gr, rh):
            return body(t, c, g, r, l, None, x, o, gr, rh)
    else:
        def kfn(t, c, g, r, l, o, gr, rh):
            return body(t, c, g, r, l, None, None, o, gr, rh)
    out = pl.pallas_call(
        kfn,
        # d is the MINOR grid dim: programs revisiting one row's output
        # run consecutively, carrying gram/rhs in scratch
        grid=(B, n_d),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, kp), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 1, kp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((kp, kp), jnp.float32),   # gram accumulator
            pltpu.VMEM((1, kp), jnp.float32),    # rhs accumulator
        ],
        interpret=interpret,
    )(*ops)
    # empty rows solve to EXACTLY 0 (the _reg_solve where-guard): the
    # cold kernel holds that fixed point by construction, but a warm
    # start on a zero-nnz row would leave a converging-to-zero residue
    return jnp.where(nnz[:, None] > 0, out[:, 0, :k], 0.0)


_als_ok: "dict[tuple, bool]" = {}


def als_kernel_available(warm: "bool | None" = None, fused: bool = False,
                         implicit: bool = False) -> bool:
    """The ALS bucket-solve family: probe the real kernel at a shape that
    exercises rank padding (rank 64 → 128), a row count that is not a
    sublane multiple, and multi-tile D streaming.

    The probe must compile the variant the caller will actually run:
    a warm-start bucket solve passes an ``x0`` operand, which is a
    DIFFERENT kernel (extra input spec + the initial-residual matvec),
    so a cold-only probe would green-light a warm kernel that was never
    compiled on the real Mosaic backend — the interpret-passes/
    hardware-fails class ROUND5.md documents. The same rule covers the
    fused-gather generation: ``fused=True`` probes
    :func:`als_fused_solve_cg_pallas` (in-kernel ``jnp.take`` gather —
    a lowering the two-stage kernel never exercises) and
    ``implicit=True`` its shared-YᵗY variant (an extra operand + matvec
    term). ``warm`` is the caller's resolved warm-start setting
    (als._mixed_run passes its per-call override; None falls back to
    the PIO_ALS_CG_WARMSTART process default), and results cache per
    (warm, fused, implicit) variant."""
    if warm is None:
        from incubator_predictionio_tpu.ops.als import _CG_WARMSTART

        warm = _CG_WARMSTART
    key = (bool(warm), bool(fused), bool(implicit))
    if key not in _als_ok:
        if not pallas_available():
            _als_ok[key] = False
        else:
            warm_b, fused_b, implicit_b = key
            x0 = jnp.zeros((12, 64), jnp.float32) if warm_b else None
            if fused_b:
                table = jnp.zeros(
                    (60, 64),
                    jnp.float32 if implicit_b else jnp.bfloat16)
                yty = (jnp.zeros((64, 64), jnp.float32)
                       if implicit_b else None)
                what = ("ALS fused gather+Gram CG solve ("
                        + ("warm" if warm_b else "cold")
                        + (", implicit" if implicit_b else "") + ")")
                _als_ok[key] = _probe_kernel_runs(
                    lambda: als_fused_solve_cg_pallas(
                        table,
                        jnp.zeros((12, 1024), jnp.int32),
                        jnp.ones((12, 1024), jnp.float32),
                        jnp.ones((12, 1024), jnp.float32),
                        0.1, True, 6, implicit=implicit_b, alpha=1.0,
                        yty=yty, x0=x0, interpret=False),
                    what)
            else:
                _als_ok[key] = _probe_kernel_runs(
                    lambda: als_solve_cg_pallas(
                        jnp.zeros((64, 64), jnp.bfloat16),
                        jnp.zeros((12, 1024), jnp.int32),
                        jnp.ones((12, 1024), jnp.float32),
                        jnp.ones((12, 1024), jnp.float32),
                        0.1, True, 6, interpret=False, x0=x0),
                    f"ALS bucket CG solve ({'warm' if warm_b else 'cold'})")
    return _als_ok[key]
