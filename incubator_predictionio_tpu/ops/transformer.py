"""Causal transformer for next-item prediction (the sequence engines).

No reference counterpart exists — the reference's only sequence behavior is
MarkovChain top-N transitions (e2/.../MarkovChain.scala:33); this is the
TPU-native upgrade of that capability: a SASRec-style self-attentive
session model over event-store item sequences.

TPU design notes:
- Layers are *stacked* pytrees scanned with ``lax.scan`` — one compiled
  block body regardless of depth, no Python-loop unrolling.
- Attention is pluggable: dense/blockwise on one chip
  (ops/attention.py), ring or Ulysses sequence parallelism on an ``sp``
  mesh axis (parallel/ring.py) for long sessions.
- The full fit loop (epochs × minibatches) runs inside one jit via a
  nested ``lax.scan`` over a pre-batched [steps, B, L] tensor; weights are
  donated so optimizer state lives on device across the whole run.
- Embedding/projection matmuls accumulate in f32 via
  ``preferred_element_type`` and are MXU-shaped ([B·L, D] × [D, V]).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

#: attention callable: (q, k, v, causal) -> out, all [B, S, H, Dh]
AttnFn = Callable[..., jax.Array]

PAD = 0  # padding token; real items are 1..n_items


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TransformerWeights:
    item_emb: Any    # [V, D]  (tied output projection)
    pos_emb: Any     # [L, D]
    # stacked per-layer weights, leading axis = layer
    ln1_scale: Any   # [N, D]
    ln2_scale: Any   # [N, D]
    wq: Any          # [N, D, D]
    wk: Any          # [N, D, D]
    wv: Any          # [N, D, D]
    wo: Any          # [N, D, D]
    w_up: Any        # [N, D, 4D]
    w_down: Any      # [N, 4D, D]
    lnf_scale: Any   # [D]


def transformer_init(
    key: jax.Array,
    n_items: int,
    max_len: int,
    d_model: int = 64,
    n_layers: int = 2,
) -> TransformerWeights:
    ks = jax.random.split(key, 8)
    v = n_items + 1  # + PAD
    d, h = d_model, 4 * d_model

    def init(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    return TransformerWeights(
        item_emb=init(ks[0], (v, d), d ** -0.5),
        pos_emb=init(ks[1], (max_len, d), 0.02),
        ln1_scale=jnp.ones((n_layers, d)),
        ln2_scale=jnp.ones((n_layers, d)),
        wq=init(ks[2], (n_layers, d, d), d ** -0.5),
        wk=init(ks[3], (n_layers, d, d), d ** -0.5),
        wv=init(ks[4], (n_layers, d, d), d ** -0.5),
        wo=init(ks[5], (n_layers, d, d), d ** -0.5),
        w_up=init(ks[6], (n_layers, d, h), d ** -0.5),
        w_down=init(ks[7], (n_layers, h, d), h ** -0.5),
        lnf_scale=jnp.ones((d,)),
    )


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


#: sequence length from which (inclusive) the Pallas flash kernel serves
#: instead of the XLA blockwise scan. Measured on v5e with dispatch
#: amortized (scripts/flash_tune.py sweeps block shapes and re-measures
#: this): with the per-length block table (pallas_kernels.py) flash wins
#: 3.3x at 8k, 4.3x at 16k, 5.8x at 32k. Below 8k is unmeasured on
#: chip, so the scan keeps it for now. Re-run the sweep after
#: kernel/toolchain changes and update here (or override via env).
def _flash_min_seq() -> int:
    raw = os.environ.get("PIO_FLASH_MIN_SEQ", "")
    try:
        return int(raw) if raw.strip() else 8192
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring malformed PIO_FLASH_MIN_SEQ=%r; using 8192", raw)
        return 8192


FLASH_MIN_SEQ = _flash_min_seq()


def _default_attn(q, k, v, causal=True, kv_valid=None):
    from incubator_predictionio_tpu.ops.attention import (
        blockwise_attention, dot_product_attention,
    )
    # flash streams KV block-by-block (kv is a grid dimension), so VMEM use
    # is S-independent — no length cap; the crossover constant above picks
    # the faster implementation per length.
    if FLASH_MIN_SEQ <= q.shape[1]:
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            flash_attention, flash_available)
        if flash_available():
            return flash_attention(q, k, v, causal=causal, kv_valid=kv_valid)
    if q.shape[1] > 1024:
        return blockwise_attention(q, k, v, causal=causal, kv_valid=kv_valid)
    return dot_product_attention(q, k, v, causal=causal, kv_valid=kv_valid)


def transformer_apply(
    w: TransformerWeights,
    tokens: jax.Array,          # [B, L] int32
    n_heads: int,
    attn_fn: Optional[AttnFn] = None,
) -> jax.Array:
    """Hidden states [B, L, D] after the final norm."""
    attn = attn_fn or _default_attn
    b, l = tokens.shape
    d = w.item_emb.shape[1]
    dh = d // n_heads
    x = w.item_emb[tokens] + w.pos_emb[:l]
    # padding keys are masked out of every attention softmax
    kv_valid = tokens != PAD

    layer_stack = (w.ln1_scale, w.ln2_scale, w.wq, w.wk, w.wv, w.wo,
                   w.w_up, w.w_down)

    def block(x, layer):
        ln1, ln2, wq, wk, wv, wo, w_up, w_down = layer
        h = _rms_norm(x, ln1)
        q = (h @ wq).reshape(b, l, n_heads, dh)
        k = (h @ wk).reshape(b, l, n_heads, dh)
        v = (h @ wv).reshape(b, l, n_heads, dh)
        o = attn(q, k, v, causal=True, kv_valid=kv_valid).reshape(b, l, d)
        x = x + o @ wo
        h = _rms_norm(x, ln2)
        x = x + jax.nn.gelu(h @ w_up) @ w_down
        return x, None

    x, _ = jax.lax.scan(block, x, layer_stack)
    return _rms_norm(x, w.lnf_scale)


def next_item_logits(
    w: TransformerWeights, tokens: jax.Array, n_heads: int,
    attn_fn: Optional[AttnFn] = None,
) -> jax.Array:
    """[B, L, V] logits with the output projection tied to item_emb."""
    h = transformer_apply(w, tokens, n_heads, attn_fn)
    return jnp.einsum(
        "bld,vd->blv", h, w.item_emb, preferred_element_type=jnp.float32
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_heads", "learning_rate", "epochs", "attn_fn"),
    donate_argnames=("w", "tx_state"),
)
def _fit_scan(w, batches, tx_state, n_heads, learning_rate, epochs,
              attn_fn=None):
    tx = optax.adamw(learning_rate)

    def loss_fn(w, batch):
        logits = next_item_logits(w, batch[:, :-1], n_heads, attn_fn)
        targets = batch[:, 1:]
        mask = (targets != PAD) & (batch[:, :-1] != PAD)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.sum(ce * mask) / jnp.maximum(mask.sum(), 1)

    def step(carry, batch):
        w, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(w, batch)
        updates, s = tx.update(grads, s, w)
        return (optax.apply_updates(w, updates), s), loss

    def epoch(carry, _):
        carry, losses = jax.lax.scan(step, carry, batches)
        return carry, losses.mean()

    (w, tx_state), losses = jax.lax.scan(
        epoch, (w, tx_state), None, length=epochs
    )
    return w, losses


def sasrec_fit(
    sequences: np.ndarray,      # [N, L] int32, PAD-padded, items 1..n_items
    n_items: int,
    d_model: int = 64,
    n_heads: int = 2,
    n_layers: int = 2,
    epochs: int = 20,
    batch_size: int = 128,
    learning_rate: float = 1e-3,
    seed: int = 0,
    attn_fn: Optional[AttnFn] = None,
) -> tuple[TransformerWeights, np.ndarray]:
    """Train on next-item prediction; returns (weights, per-epoch loss).

    ``attn_fn`` selects the attention backend — e.g. a
    ``functools.partial(ring_attention, mesh=mesh)`` for sequence-parallel
    training of long sessions. It must be hashable (jit-static).
    """
    seqs = np.asarray(sequences, np.int32)
    n, max_len = seqs.shape
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by {n_heads} heads")
    w = transformer_init(
        jax.random.key(seed), n_items, max_len, d_model, n_layers
    )
    # pre-batch into [steps, B, L]; ragged tail is padded with PAD-only rows
    # (masked out of the loss)
    bs = min(batch_size, n)
    steps = -(-n // bs)
    pad_rows = steps * bs - n
    if pad_rows:
        seqs = np.concatenate(
            [seqs, np.zeros((pad_rows, max_len), np.int32)]
        )
    rng = np.random.default_rng(seed)
    seqs = seqs[rng.permutation(len(seqs))]
    batches = jnp.asarray(seqs.reshape(steps, bs, max_len))
    tx_state = optax.adamw(learning_rate).init(w)
    w, losses = _fit_scan(w, batches, tx_state, n_heads,
                          learning_rate, epochs, attn_fn)
    return w, np.asarray(losses)


@functools.partial(jax.jit, static_argnames=("n_heads", "k"))
def sasrec_topk(
    w: TransformerWeights,
    tokens: jax.Array,          # [B, L] recent history, PAD-padded LEFT
    n_heads: int,
    k: int = 10,
) -> tuple[jax.Array, jax.Array]:
    """Top-k next items from the last position's hidden state.

    Returns (scores [B, k], item ids [B, k]); PAD is never returned.
    """
    h = transformer_apply(w, tokens, n_heads)
    last = h[:, -1]                                       # [B, D]
    scores = jnp.einsum(
        "bd,vd->bv", last, w.item_emb, preferred_element_type=jnp.float32
    )
    # never recommend PAD or items already in the history (PAD ∈ history
    # columns, so the vmap covers it)
    scores = jax.vmap(lambda s, t: s.at[t].set(-jnp.inf))(scores, tokens)
    scores = scores.at[:, PAD].set(-jnp.inf)
    return jax.lax.top_k(scores, k)
