"""Linear regression on device — the regression-template solvers.

Two fits mirroring the reference's pair of regression examples:

- :func:`linreg_fit` — closed-form ridge via the normal equations, the
  local example's exact solve (examples/experimental/scala-local-regression/
  Run.scala: breeze + nak LinearRegression.regress). One K×K Cholesky on
  the MXU; K = feature count is small, the cost is the [N, K] Gram.
- :func:`linreg_fit_sgd` — gradient descent, the parallel example's
  LinearRegressionWithSGD (scala-parallel-regression/Run.scala:
  numIterations/stepSize params). ``lax.scan`` over full-batch gradient
  steps: one fused device program, no per-step dispatch.

Both learn an intercept by augmenting features with a constant column,
and return the weight vector [K+1] (intercept last).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _augment(x: jax.Array) -> jax.Array:
    return jnp.concatenate(
        [x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)


@functools.partial(jax.jit, static_argnames=())
def linreg_fit(x: jax.Array, y: jax.Array, l2: float = 0.0) -> jax.Array:
    """Ridge normal equations: (XᵗX + λI) w = Xᵗy → w [K+1]."""
    xa = _augment(x.astype(jnp.float32))
    k = xa.shape[1]
    gram = xa.T @ xa + l2 * jnp.eye(k, dtype=jnp.float32)
    rhs = xa.T @ y.astype(jnp.float32)
    chol = jax.scipy.linalg.cho_factor(gram)
    return jax.scipy.linalg.cho_solve(chol, rhs)


@functools.partial(jax.jit, static_argnames=("steps",))
def linreg_fit_sgd(
    x: jax.Array,
    y: jax.Array,
    steps: int = 200,
    step_size: float = 0.1,
    l2: float = 0.0,
) -> jax.Array:
    """Full-batch gradient descent on MSE (LinearRegressionWithSGD's role;
    full-batch because the whole design matrix sits in HBM — minibatching
    would only add dispatch overhead at template scale)."""
    xa = _augment(x.astype(jnp.float32))
    ya = y.astype(jnp.float32)
    n = xa.shape[0]

    def step(w, _):
        grad = xa.T @ (xa @ w - ya) / n + l2 * w
        return w - step_size * grad, None

    w0 = jnp.zeros((xa.shape[1],), jnp.float32)
    w, _ = jax.lax.scan(step, w0, None, length=steps)
    return w


@jax.jit
def linreg_predict(w: jax.Array, x: jax.Array) -> jax.Array:
    """Predictions for a [N, K] feature batch → [N]."""
    return _augment(x.astype(jnp.float32)) @ w
