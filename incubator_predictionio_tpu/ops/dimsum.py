"""Item-item cosine column similarities — the DIMSUM-variant solver.

The reference's similarproduct-dimsum template calls
``RowMatrix.columnSimilarities(threshold)`` (examples/experimental/
scala-parallel-similarproduct-dimsum/src/main/scala/
DIMSUMAlgorithm.scala:133), Spark's sampling-based DIMSUM approximation
of the item-item cosine matrix — sampling exists there because the Gram
must be shuffled across executors. On a TPU the Gram IS the MXU's native
operation, so the rebuild computes it exactly: user-chunked dense
scatter → one ``[C, I]ᵀ·[C, I]`` matmul per chunk accumulated under
``lax.scan`` in one fused program, then cosine normalization,
thresholding (exact, where DIMSUM's is probabilistic), and a per-row
top-N. No sampling error, deterministic output.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

#: dense [I, I] similarity ceiling: above this the Gram no longer fits
#: comfortably (16k² f32 = 1 GB) and the ALS-factor variant is the right
#: tool anyway — this solver targets the template's catalog scale
MAX_ITEMS = 16384


@functools.partial(jax.jit, static_argnames=("n_items", "top_n"))
def _gram_cosine_topk(
    chunks_u: jax.Array,     # [S, C] int32 row-in-chunk (or C = padding)
    chunks_i: jax.Array,     # [S, C] int32 item index
    chunks_w: jax.Array,     # [S, C] f32 weight (0 on padding)
    n_items: int,
    threshold: float,
    top_n: int,
) -> Tuple[jax.Array, jax.Array]:
    # row-in-chunk ids are < _CHUNK_ROWS by construction
    # (column_cosine_topk packs them), so every chunk scatters into the
    # same static [_CHUNK_ROWS, n_items] buffer; padding triples carry
    # weight 0 and add nothing
    def step(gram, xs):
        u, i, w = xs
        dense = jnp.zeros((_CHUNK_ROWS, n_items), jnp.float32)
        dense = dense.at[u, i].add(w)
        return gram + dense.T @ dense, None

    gram0 = jnp.zeros((n_items, n_items), jnp.float32)
    gram, _ = jax.lax.scan(step, gram0, (chunks_u, chunks_i, chunks_w))
    norms = jnp.sqrt(jnp.maximum(jnp.diagonal(gram), 1e-12))
    sim = gram / (norms[:, None] * norms[None, :])
    sim = jnp.where(sim >= threshold, sim, 0.0)
    sim = sim * (1.0 - jnp.eye(n_items, dtype=jnp.float32))  # no self-sim
    scores, indices = jax.lax.top_k(sim, min(top_n, n_items))
    return scores, indices


_CHUNK_ROWS = 2048


def column_cosine_topk(
    users: np.ndarray,
    items: np.ndarray,
    weights: np.ndarray,
    n_items: int,
    threshold: float = 0.1,
    top_n: int = 100,
) -> Tuple[np.ndarray, np.ndarray]:
    """→ (scores [I, T], indices [I, T]): per-item top-T cosine neighbors
    with similarity ≥ threshold (0-padded; an index whose score is 0 is
    absent). Exact where the reference's DIMSUM samples."""
    if n_items > MAX_ITEMS:
        raise ValueError(
            f"dimsum similarity targets catalogs ≤ {MAX_ITEMS} items "
            f"(got {n_items}); use the ALS similarproduct algorithm for "
            "larger catalogs")
    users = np.asarray(users, np.int64)
    items = np.asarray(items, np.int32)
    weights = np.asarray(weights, np.float32)
    order = np.argsort(users, kind="stable")
    users, items, weights = users[order], items[order], weights[order]
    # pack users into chunks of _CHUNK_ROWS distinct users: row-in-chunk
    # ids stay < _CHUNK_ROWS so every chunk scatters into the same static
    # [_CHUNK_ROWS, I] buffer
    _, user_dense = np.unique(users, return_inverse=True)
    chunk_of = user_dense // _CHUNK_ROWS
    row_in_chunk = (user_dense % _CHUNK_ROWS).astype(np.int32)
    n_chunks = int(chunk_of.max()) + 1 if len(users) else 1
    # split nnz by chunk, pad each chunk's triple list to the max length
    counts = np.bincount(chunk_of, minlength=n_chunks)
    width = max(int(counts.max()), 1) if len(users) else 1
    cu = np.zeros((n_chunks, width), np.int32)
    ci = np.zeros((n_chunks, width), np.int32)
    cw = np.zeros((n_chunks, width), np.float32)
    starts = np.zeros(n_chunks + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    for c in range(n_chunks):
        lo, hi = starts[c], starts[c + 1]
        cu[c, :hi - lo] = row_in_chunk[lo:hi]
        ci[c, :hi - lo] = items[lo:hi]
        cw[c, :hi - lo] = weights[lo:hi]
    scores, indices = _gram_cosine_topk(
        jnp.asarray(cu), jnp.asarray(ci), jnp.asarray(cw),
        n_items=n_items, threshold=float(threshold), top_n=int(top_n))
    return np.asarray(scores), np.asarray(indices)
