"""Attention kernels for the sequence model family.

The reference has no attention anywhere (SURVEY.md §5 "Long-context");
sequence behavior tops out at MarkovChain transitions. This framework's
sequence engines (models/sequence/) are transformer-based, so attention is a
first-class hot op designed for the MXU:

- :func:`dot_product_attention` — dense reference implementation (and the
  fast path for short sequences: one fused softmax(QKᵀ)V per head).
- :func:`blockwise_attention` — FlashAttention-style online-softmax over KV
  blocks via ``lax.scan``: O(S) memory in sequence length, static shapes,
  MXU-sized [block × head_dim] matmuls. This is the single-device
  long-context path; the distributed path wraps it per-shard
  (parallel/ring.py ring attention).

All functions take [batch, seq, heads, head_dim] ("BSHD") arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

#: scores at masked positions — large-negative instead of -inf so a fully
#: masked row exps to exactly 0 without NaNs from (-inf) - (-inf)
MASK_VALUE = -1e30


def _scale(q, scale: Optional[float]) -> float:
    return scale if scale is not None else q.shape[-1] ** -0.5


def _combine_masks(causal, q_pos, kv_pos, kv_valid):
    """Broadcastable [B|1, 1, Q, K] boolean mask, or None if unmasked.

    ``kv_valid`` is a per-key padding mask, [K] or [B, K].
    """
    mask = None
    if causal:
        mask = (q_pos[:, None] >= kv_pos[None, :])[None, None]
    if kv_valid is not None:
        vm = kv_valid if kv_valid.ndim == 2 else kv_valid[None]
        vm = vm[:, None, None, :]
        mask = vm if mask is None else (mask & vm)
    return mask


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    kv_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Dense softmax(QKᵀ)V on [B, S, H, D] inputs.

    ``q_offset``/``kv_offset`` are the global positions of the first query /
    key row — this is what lets sequence-sharded callers (ring attention)
    reuse the same masking rule on local blocks. ``kv_valid`` ([K] or
    [B, K]) masks padding keys.
    """
    s = _scale(q, scale)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * s
    q_pos = q_offset + jnp.arange(q.shape[1])
    kv_pos = kv_offset + jnp.arange(k.shape[1])
    mask = _combine_masks(causal, q_pos, kv_pos, kv_valid)
    if mask is not None:
        logits = jnp.where(mask, logits, MASK_VALUE)
    p = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    if mask is not None:
        # zero (not softmax-uniform) output for fully masked rows — the
        # invariant the sequence-sharded kernels rely on when a shard's
        # whole KV block is in the future
        p = jnp.where(mask, p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    probs = (p / jnp.where(l == 0.0, 1.0, l)).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _online_block(q, k_blk, v_blk, m, l, o, scale, causal, q_pos, kv_pos,
                  kv_valid=None):
    """One online-softmax accumulation step against a single KV block.

    Carries (m, l, o) = running rowmax, normalizer, unnormalized output in
    f32. Shared by blockwise_attention and ring attention so the numerics
    are identical on one chip and on a sequence-sharded mesh. ``kv_valid``
    masks padded tail keys independently of causality.
    """
    s_blk = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * scale
    mask = _combine_masks(causal, q_pos, kv_pos, kv_valid)
    if mask is not None:
        s_blk = jnp.where(mask, s_blk, MASK_VALUE)
    # m_new is always finite (masked scores are MASK_VALUE), so the exps
    # below never see (-inf) - (-inf); the initial m = -inf just makes the
    # first block's correction factor exp(-inf - m_new) = 0
    m_new = jnp.maximum(m, s_blk.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s_blk - m_new[..., None])
    if mask is not None:
        # zero masked probabilities so a fully-masked block adds no mass
        # (exp(MASK_VALUE - MASK_VALUE) would otherwise be 1)
        p = jnp.where(mask, p, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def _finalize(m, l, o, dtype):
    # fully-masked rows (l == 0) produce 0 output rather than NaN
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return jnp.einsum("bhqd->bqhd", o / l_safe[..., None]).astype(dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_size", "scale"))
def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_size: int = 512,
    scale: Optional[float] = None,
    kv_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Online-softmax attention scanning KV in blocks ([B, S, H, D] in/out).

    Memory is O(S·block) instead of O(S²); the scan is a static-length
    ``lax.scan`` so XLA pipelines the per-block matmuls on the MXU.
    ``kv_valid`` ([K] or [B, K]) masks padding keys.
    """
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    blk = min(block_size, s_kv)
    n_blocks = -(-s_kv // blk)
    pad = n_blocks * blk - s_kv
    if pad or kv_valid is not None:
        # fold ragged-tail padding into one per-key validity mask
        if kv_valid is None:
            valid = jnp.ones((1, s_kv), bool)
        else:
            valid = jnp.broadcast_to(
                kv_valid if kv_valid.ndim == 2 else kv_valid[None],
                (kv_valid.shape[0] if kv_valid.ndim == 2 else 1, s_kv),
            )
        valid = jnp.pad(valid, ((0, 0), (0, pad)))  # pads with False
    else:
        valid = None
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    sc = _scale(q, scale)
    q_pos = jnp.arange(s_q)

    k_blocks = k.reshape(b, n_blocks, blk, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_blocks, blk, h, d).transpose(1, 0, 2, 3, 4)
    valid_blocks = (
        None if valid is None
        else valid.reshape(valid.shape[0], n_blocks, blk).transpose(1, 0, 2)
    )

    def step(carry, xs):
        m, l, o = carry
        i, k_blk, v_blk, valid_blk = xs
        kv_pos = i * blk + jnp.arange(blk)
        m, l, o = _online_block(
            q, k_blk, v_blk, m, l, o, sc, causal, q_pos, kv_pos,
            kv_valid=valid_blk,
        )
        return (m, l, o), None

    init = (
        jnp.full((b, h, s_q), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, s_q), jnp.float32),
        jnp.zeros((b, h, s_q, d), jnp.float32),
    )
    if valid_blocks is None:
        def step_novalid(carry, xs):
            return step(carry, (*xs, None))
        (m, l, o), _ = lax.scan(
            step_novalid, init, (jnp.arange(n_blocks), k_blocks, v_blocks)
        )
    else:
        (m, l, o), _ = lax.scan(
            step, init,
            (jnp.arange(n_blocks), k_blocks, v_blocks, valid_blocks),
        )
    return _finalize(m, l, o, q.dtype)
