"""Host-resident serving helpers for small models.

The deployed environment may reach the TPU through a network tunnel whose
blocking dispatch+fetch round trip is tens of milliseconds — the latency
floor for ANY per-query device call. Models whose factor tables are a few
MB serve faster from a host copy (numpy matvec + argpartition — the
reference's driver-local serving locality, CreateServer.scala:498-650);
big catalogs keep the device path, where compute dominates the round trip.

Used by the recommendation / similarproduct / ecommerce serving code.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

NEG_INF = -3.4e38

#: models up to this many cached elements serve from the host copy
HOST_SERVE_MAX_ELEMS = 1 << 22


def host_arrays(model, *field_names: str,
                max_elems: int = HOST_SERVE_MAX_ELEMS):
    """Lazy host copies of the named model fields, or None for big models.

    The copy is cached on the model object itself (``_np_cache``, keyed by
    the requested field names) so reloads naturally invalidate it. A benign
    race under concurrent first queries computes the same value twice."""
    cache = getattr(model, "_np_cache", None)
    if cache is False:   # host serving disabled for this model
        return None
    if cache is None:
        cache = {}
        object.__setattr__(model, "_np_cache", cache)
    entry = cache.get(field_names)
    if entry is None:
        arrays = tuple(np.asarray(getattr(model, f)) for f in field_names)
        entry = arrays if sum(a.size for a in arrays) <= max_elems else False
        cache[field_names] = entry
    return entry or None


def host_top_k(
    scores: np.ndarray,
    k: int,
    allowed_mask: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """numpy equivalent of ops.topk.top_k_with_exclusions: returns
    (top_scores[k], top_indices[k]) descending; masked slots score
    ``NEG_INF`` (callers already filter ``<= -1e37``)."""
    if allowed_mask is not None:
        scores = np.where(allowed_mask, scores, NEG_INF)
    k = min(k, scores.shape[-1])
    if k <= 0:
        return np.empty(0, scores.dtype), np.empty(0, np.int64)
    top = np.argpartition(scores, -k)[-k:]
    top = top[np.argsort(scores[top])[::-1]]
    return scores[top], top
