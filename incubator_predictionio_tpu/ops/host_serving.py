"""Host-resident serving helpers (the reference's driver-local locality).

The deployed environment may reach the TPU through a network tunnel whose
blocking dispatch+fetch round trip is tens of milliseconds — the latency
floor for ANY per-query device call. Models whose factor tables fit a host
mirror serve singleton queries faster from numpy (matvec + argpartition —
the reference's driver-local serving locality, CreateServer.scala:498-650).

How big "fits" is is ADAPTIVE: the first caller measures the device
dispatch+fetch overhead once (a dependent 1-element fetch — on this
platform `block_until_ready` returns before execution finishes, so only a
fetch observes the true round trip). When the round trip is expensive
(≥5 ms: tunneled or remote device), the mirror budget grows to 64M
elements (256 MB f32) so even an ML-20M-scale catalog (~21M elems) serves
from the host at sub-ms instead of paying the tunnel per query; when the
device is local (sub-ms dispatch), the budget stays at 4M elements and
large catalogs keep the device path, where the MXU wins.

``PIO_HOST_SERVE_MAX_ELEMS`` overrides the measurement entirely
(0 disables host serving).

Used by the recommendation / similarproduct / ecommerce serving code.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import numpy as np

NEG_INF = -3.4e38

#: mirror budget when the device round trip is cheap (local chip)
HOST_SERVE_MAX_ELEMS = 1 << 22
#: mirror budget when every device call pays an expensive round trip
HOST_SERVE_BIG_ELEMS = 1 << 26
#: dispatch+fetch round trip above this means "expensive device"
DISPATCH_EXPENSIVE_S = 5e-3

_dispatch_overhead: Optional[float] = None


def dispatch_overhead_s() -> float:
    """Measured device dispatch+fetch round trip (cached; best of 3)."""
    global _dispatch_overhead
    if _dispatch_overhead is None:
        try:
            import jax
            import jax.numpy as jnp

            fn = jax.jit(lambda v: v + 1)
            x = jnp.zeros(8, jnp.float32)
            np.asarray(fn(x))  # compile + warm outside the timed window
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(fn(x))
                samples.append(time.perf_counter() - t0)
            _dispatch_overhead = min(samples)
        except Exception:
            _dispatch_overhead = 0.0
    return _dispatch_overhead


def host_serve_limit() -> int:
    """Current mirror budget in elements (env override, else adaptive)."""
    env = os.environ.get("PIO_HOST_SERVE_MAX_ELEMS", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "ignoring malformed PIO_HOST_SERVE_MAX_ELEMS=%r "
                "(want an integer element count); using the adaptive "
                "budget", env)
    if dispatch_overhead_s() >= DISPATCH_EXPENSIVE_S:
        return HOST_SERVE_BIG_ELEMS
    return HOST_SERVE_MAX_ELEMS


def warm_host_arrays(model, **field_arrays: np.ndarray) -> None:
    """Seed the host mirror from numpy copies already in hand (e.g. inside
    ``prepare_model`` before factors are device_put), so the first query
    never pays a device→host fetch. Owns the same cache-key contract as
    :func:`host_arrays`; respects the budget and any disabled cache."""
    cache = getattr(model, "_np_cache", None)
    if cache is False:
        return
    names = tuple(field_arrays)
    arrays = tuple(field_arrays.values())
    if sum(a.size for a in arrays) > host_serve_limit():
        return
    if cache is None:
        cache = {}
        object.__setattr__(model, "_np_cache", cache)
    cache[names] = arrays


def host_arrays(model, *field_names: str, max_elems: Optional[int] = None):
    """Lazy host copies of the named model fields, or None for big models.

    ``max_elems=None`` uses the adaptive budget (``host_serve_limit``).
    The copy is cached on the model object itself (``_np_cache``, keyed by
    the requested field names) so reloads naturally invalidate it. A benign
    race under concurrent first queries computes the same value twice."""
    cache = getattr(model, "_np_cache", None)
    if cache is False:   # host serving disabled for this model
        return None
    if cache is None:
        cache = {}
        object.__setattr__(model, "_np_cache", cache)
    entry = cache.get(field_names)
    if entry is None:
        if max_elems is None:
            max_elems = host_serve_limit()
        total = sum(
            int(np.prod(getattr(model, f).shape)) for f in field_names)
        if total <= max_elems:
            # one device→host fetch per field, paid once per deploy
            entry = tuple(
                np.asarray(getattr(model, f)) for f in field_names)
        else:
            entry = False
        cache[field_names] = entry
    return entry or None


def host_batch_top_k(
    scores: np.ndarray,      # [B, I]
    k: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized :func:`host_top_k` over a [B, I] score block: one
    argpartition + one argsort for the whole batch (both GIL-released) —
    per-row calls cost ~0.1 ms of serialized Python each on the
    concurrent-serving hot path. Returns ([B, k] scores, [B, k] indices)
    descending, row-for-row IDENTICAL to host_top_k (the [::-1] after an
    ascending argsort reproduces its tie ordering exactly; the serving
    byte-identity tests pin this)."""
    k = min(k, scores.shape[-1])
    if k <= 0:
        b = scores.shape[0]
        return (np.empty((b, 0), scores.dtype), np.empty((b, 0), np.int64))
    part = np.argpartition(scores, -k, axis=1)[:, -k:]
    ps = np.take_along_axis(scores, part, axis=1)
    order = np.argsort(ps, axis=1)[:, ::-1]
    return (np.take_along_axis(ps, order, axis=1),
            np.take_along_axis(part, order, axis=1))


def host_top_k(
    scores: np.ndarray,
    k: int,
    allowed_mask: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """numpy equivalent of ops.topk.top_k_with_exclusions: returns
    (top_scores[k], top_indices[k]) descending; masked slots score
    ``NEG_INF`` (callers already filter ``<= -1e37``)."""
    if allowed_mask is not None:
        scores = np.where(allowed_mask, scores, NEG_INF)
    k = min(k, scores.shape[-1])
    if k <= 0:
        return np.empty(0, scores.dtype), np.empty(0, np.int64)
    top = np.argpartition(scores, -k)[-k:]
    top = top[np.argsort(scores[top])[::-1]]
    return scores[top], top
