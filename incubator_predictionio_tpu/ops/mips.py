"""Quantized two-stage MIPS serving: coarse bucket scan + exact rerank.

Exhaustive serving dot-products the full (sharded) item table per query
(ops/topk.py) — fine at ML-20M's ~27k items, a linear wall at catalogue
scale. This module is the approximate-MIPS path the top-k auto-routers
fall forward to when an index is registered:

1. **Coarse stage.** Spherical k-means centroid buckets are computed at
   train/retrain time (per shard under ``FactorPlacement`` — the
   centroid scan and the candidate gather never cross a shard
   boundary). A query scans the tiny centroid table (C×K f32, ~0.5 MB
   at C=1024/rank=128 — VMEM-resident), weighted by each bucket's max
   row norm (an upper bound on the bucket's best inner product — plain
   cosine probing under-ranks buckets holding popular high-norm items),
   probes the top ``nprobe`` buckets and scores their member rows with
   the int8 (symmetric per-row scale) or bf16 quantized view — 4×/2×
   less HBM than the f32 scan it replaces.
2. **Exact rerank.** The top ``candidates`` coarse survivors are
   re-scored against the exact f32 factor rows and ranked. Both stage
   widths are static pow2 knobs, so steady state compiles once per
   (batch rung, k) exactly like the exhaustive ladder — zero
   steady-state recompiles, counted by ``mips_compile_cache_size`` in
   ``ops.topk.serve_compile_cache_size``.

Exhaustive stays the FALLBACK and the ORACLE: ``PIO_SERVE_MIPS=off``,
an unregistered table, a filtered query (``allowed_mask``), or a
small-catalogue ``auto`` route all take the exhaustive path unchanged,
and the recall@k gate (tests/test_mips.py, ``bench_mips``) compares the
two-stage result against it.

Speed-overlay seam: fold-in vectors published for ITEM-side keys are
not in the quantized buckets yet — :func:`publish_rows` re-quantizes
known rows in place AND records the fresh vector in an **exact tail**
(scored in f32 on the host, merged after the device stage), so a
just-folded key is findable at recall 1.0 the moment it publishes.

Continuation-retrain seam: :func:`update_index` re-quantizes and
re-assigns only the touched rows (O(delta)); a geometry change (reshard
/ capacity growth) rebuilds.

Catalogue-scale seams (ops/mips_daemon.py drives them):

- ``PIO_SERVE_MIPS_QUANT=pq`` materializes **product-quantized
  residual codes** instead of a dense per-row view: M subquantizers ×
  256 codewords (``PIO_SERVE_MIPS_PQ_M``) over residuals from the
  assigned centroid, scored asymmetrically via per-query LUTs computed
  once per dispatch — rank/M bytes per row (8–16× vs f32), with the
  exact f32 rerank stage unchanged in kind.
- :func:`rebuild_index` is the background-rebuild entry: re-clusters
  off the serving path, folds the virtual-id tail into a dense **ext
  block** (ids stay stable — the overlay's key→id map survives), and
  atomically swaps the registry entry with zero serving downtime.
- Cold buckets can be **tiered to host memory** at rebuild time from
  probe-hit statistics: demoted buckets leave the device arrays
  entirely and are served by an exact host-side scan when probed —
  never a serving-path blocking transfer.

Knobs (all read at call time): ``PIO_SERVE_MIPS`` (off|auto|on),
``PIO_SERVE_MIPS_NPROBE``, ``PIO_SERVE_MIPS_CANDIDATES``,
``PIO_SERVE_MIPS_MIN_ITEMS``, ``PIO_SERVE_MIPS_CENTROIDS``,
``PIO_SERVE_MIPS_QUANT`` (int8|bf16|pq), ``PIO_SERVE_MIPS_PQ_M``,
``PIO_SERVE_MIPS_PQ_CANDIDATES``, ``PIO_MIPS_TIER`` (off|auto|on).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

NEG_INF = jnp.float32(-3.4e38)

#: serving-stage scan accounting (docs/observability.md): rows touched
#: per stage — ``centroid`` (coarse centroid rows), ``coarse``
#: (quantized candidate slots in probed buckets, padding included: a
#: padded slot costs the same HBM read), ``rerank`` (exact f32 rows),
#: ``exhaustive`` (full-table rows on the fallback path). The bench's
#: candidates-scanned fraction is (coarse + rerank) / (exhaustive-
#: equivalent rows)
_CAND_SCANNED = obs_metrics.REGISTRY.counter(
    "pio_serve_candidates_scanned_total",
    "item rows scanned by serving top-k, by stage (see "
    "docs/observability.md)", labels=("stage",))
_SCAN_CENTROID = _CAND_SCANNED.labels(stage="centroid")
_SCAN_COARSE = _CAND_SCANNED.labels(stage="coarse")
_SCAN_RERANK = _CAND_SCANNED.labels(stage="rerank")
_SCAN_EXHAUSTIVE = _CAND_SCANNED.labels(stage="exhaustive")
_RECALL = obs_metrics.REGISTRY.gauge(
    "pio_serve_mips_recall",
    "last planted-probe recall@k of the two-stage path vs the "
    "exhaustive oracle (recall_probe; sag below the 0.95 gate -> raise "
    "PIO_SERVE_MIPS_NPROBE)")
_INDEX_AGE = obs_metrics.REGISTRY.gauge(
    "pio_mips_index_age_seconds",
    "age of the OLDEST live MIPS index since its last build/update/"
    "publish/daemon-swap — climbing without bound means retrain, "
    "fold-in AND the rebuild daemon are all failing to republish")
_TAIL_SIZE = obs_metrics.REGISTRY.gauge(
    "pio_mips_tail_size",
    "exact-tail entries awaiting fold-out, per serving engine — "
    "climbing past the rebuild-tail trigger means the rebuild daemon "
    "is dead or churn outruns its cadence (docs/observability.md "
    "runbook)", labels=("engine",))
_TIER_ROWS = obs_metrics.REGISTRY.gauge(
    "pio_mips_tier_rows",
    "catalogue rows by residence tier: device (quantized coarse "
    "views in HBM) vs host (cold buckets + exact tail served from "
    "host memory)", labels=("tier",))
_REBUILDS = obs_metrics.REGISTRY.counter(
    "pio_mips_rebuilds_total",
    "background index rebuild-and-swaps by trigger "
    "(tail|age|churn|promote|manual)", labels=("trigger",))


def _now() -> float:
    """THE clock for index freshness: every ``built_at`` stamp and the
    age collector read this seam, so a FakeClock patch sees exactly the
    ages production would (tests pin the adopt/swap reset through it)."""
    return time.time()


def _collect_index_age() -> None:
    ages = []
    tails: Dict[str, int] = {}
    dev_rows = host_rows = 0
    for e in list(_REGISTRY.values()):
        idx = e.index
        ages.append(_now() - idx.built_at)
        tail = idx.tail_size()
        tails[idx.engine] = tails.get(idx.engine, 0) + tail
        d, h = idx.tier_rows()
        dev_rows += d
        host_rows += h + tail
    if ages:
        _INDEX_AGE.set(max(ages))
    for engine, t in tails.items():
        _TAIL_SIZE.labels(engine=engine).set(t)
    _TIER_ROWS.labels(tier="device").set(dev_rows)
    _TIER_ROWS.labels(tier="host").set(host_rows)


obs_metrics.REGISTRY.register_collector("mips_index_age",
                                        _collect_index_age)


# ---------------------------------------------------------------------------
# knobs (call-time reads — serving routes can be flipped live)
# ---------------------------------------------------------------------------

def serving_mode() -> str:
    """off | auto | on (default auto: route when an index exists for
    the table — indexes are only built past the auto threshold)."""
    mode = os.environ.get("PIO_SERVE_MIPS", "auto").strip().lower()
    return mode if mode in ("off", "auto", "on") else "auto"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def min_items() -> int:
    """auto-mode catalogue floor: below it the exhaustive scan's fixed
    cost wins and the index is neither built nor routed (the measured
    crossover narrative of docs/performance.md)."""
    return _env_int("PIO_SERVE_MIPS_MIN_ITEMS", 65536)


def build_enabled(n_items: int) -> bool:
    mode = serving_mode()
    if mode == "off":
        return False
    if mode == "on":
        return n_items >= 2
    return n_items >= min_items()


def _next_pow2(n: int) -> int:
    from incubator_predictionio_tpu.ops.topk import next_pow2

    return next_pow2(n)


def default_centroids(n_items: int) -> int:
    """C ≈ sqrt(I) rounded to pow2, clamped [16, 4096] — the measured
    sweet spot of centroid-scan cost vs bucket granularity on the
    planted fixture (docs/performance.md)."""
    c = _env_int("PIO_SERVE_MIPS_CENTROIDS", 0)
    if c > 0:
        return max(_next_pow2(c), 1)
    return min(max(_next_pow2(int(np.sqrt(max(n_items, 1)))), 16), 4096)


def _nprobe_for(index: "MIPSIndex") -> int:
    """Buckets probed per query across the whole index (the sharded
    path splits it evenly, with a small per-shard floor). The default
    1/16 of the buckets — with the balanced bucket cap (≤ 2× the mean)
    — bounds the coarse gather at ~1/8 of the catalogue.

    Knob seam: ``PIO_SERVE_MIPS_NPROBE`` is a REGISTERED serving knob
    (obs/knobs.py) — read per call, so the knob controller's audited
    ``POST /knobs`` env rewrite takes effect on the very next query;
    the unaudited-knob-write lint rule pins who may write it."""
    n = _env_int("PIO_SERVE_MIPS_NPROBE", 0)
    if n <= 0:
        # 1/16 of the buckets, with a ~2048-coarse-slot floor: small
        # catalogues probe a deeper fraction (where the scan is cheap
        # anyway), the floor vanishes at scale
        n = max(index.c_total // 16, 2048 // max(index.cap, 1), 4)
    return min(max(n, 1), index.c_total)


def _candidates_for(index: "MIPSIndex", k: int) -> int:
    """Exact-rerank width (pow2): wide enough that the int8 coarse
    ranking essentially never drops a true top-k row, narrow enough
    that the rerank gather + the coarse top-k cut stay a small
    fraction of a full scan.

    Knob seam: ``PIO_SERVE_MIPS_CANDIDATES`` is a REGISTERED serving
    knob (obs/knobs.py), read per call like nprobe — the recall/latency
    trade the knob controller's hill-climb works against the live
    ``pio_serve_mips_recall`` probe. A PQ index reads its OWN width
    knob (``PIO_SERVE_MIPS_PQ_CANDIDATES``, default 2× the dense
    default): the lossier coarse ranking needs a wider exact rerank to
    hold the same recall gate, and tying the two modes to one knob
    would make the controller's hill-climb fight itself across a
    quant flip."""
    if index.quant == "pq":
        n = _env_int("PIO_SERVE_MIPS_PQ_CANDIDATES", 0)
        if n <= 0:
            n = 2048
    else:
        n = _env_int("PIO_SERVE_MIPS_CANDIDATES", 0)
        if n <= 0:
            n = 1024
    n = max(_next_pow2(n), _next_pow2(max(k, 1)))
    return min(n, _next_pow2(index.n_items))


def _quant_mode() -> str:
    q = os.environ.get("PIO_SERVE_MIPS_QUANT", "int8").strip().lower()
    return q if q in ("int8", "bf16", "pq") else "int8"


def _pq_m(rank: int) -> int:
    """Subquantizer count for PQ builds: ``PIO_SERVE_MIPS_PQ_M``
    (default 16, ~rank/16 bytes per row at rank 128) snapped DOWN to a
    divisor of the rank so every subspace gets the same width. A knob
    step lands at the next rebuild, like a quant flip."""
    m = _env_int("PIO_SERVE_MIPS_PQ_M", 16)
    m = max(1, min(m, rank))
    while rank % m:
        m -= 1
    return m


# ---------------------------------------------------------------------------
# index structure + registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ColdTier:
    """Host-memory residence for cold buckets (a rebuild-daemon
    decision — see :func:`rebuild_index`). Demoted rows leave the
    device arrays entirely; they are clustered into their OWN host
    mini-index and served by an exact f32 numpy scan of the probed
    buckets, merged after the device stage like the tail. ``hits`` is
    the promotion signal: probe pressure on a cold bucket sends its
    rows back to the device at the next rebuild."""

    centroids: np.ndarray       # [Cc, K] f32 unit centroids
    cmax: np.ndarray            # [Cc] f32 probe bound norms
    crad_cos: np.ndarray        # [Cc] f32 ball radius (cos)
    crad_sin: np.ndarray        # [Cc] f32 ball radius (sin)
    member_ids: List[np.ndarray]    # per-bucket global ids
    member_vecs: List[np.ndarray]   # per-bucket exact f32 rows
    rows: int                   # total demoted rows
    hits: np.ndarray            # [Cc] int64 probe-hit counters


@dataclasses.dataclass
class MIPSIndex:
    """Quantized views + coarse buckets over ONE item factor table.

    Device arrays share the table's sharding (row-sharded when placed;
    centroid arrays shard on the bucket axis with ``c_local`` buckets
    per shard, so every bucket's members are rows the same shard owns).
    Host mirrors (``assign``, ``members_np``, ``centroids_np``,
    ``counts``) exist for the O(delta) update path. The exact tail
    (``_tail``) holds published-but-not-yet-rebuilt vectors, merged in
    f32 after the device stage."""

    codes: jax.Array          # [I_pad, K] int8 symmetric per-row quant
    scales: jax.Array         # [I_pad] f32 per-row scale (max|v|/127)
    bf16: jax.Array           # [I_pad, K] bfloat16 view
    centroids: jax.Array      # [C, K] f32 unit centroids
    cmax: jax.Array           # [C] f32 max member row norm (probe bound)
    crad_cos: jax.Array       # [C] f32 cos of the bucket's max member
    crad_sin: jax.Array       # [C] f32 ...angle to its centroid (ball
    #                         # radius — the probe bound must stay an
    #                         # UPPER bound for off-centroid members)
    members: jax.Array        # [C, cap] int32 GLOBAL row ids, -1 pad
    assign: np.ndarray        # [n_items] int32 host bucket of each row
    members_np: np.ndarray    # [C, cap] host mirror of members
    centroids_np: np.ndarray  # [C, K] host mirror
    counts: np.ndarray        # [C] live members per bucket
    n_items: int              # true (servable) row count
    n_shards: int
    c_local: int              # buckets per shard (C = n_shards*c_local)
    cap: int                  # member slots per bucket (pow2)
    rank: int
    seed: int
    #: the quantized view this index materialized ("int8" | "bf16") —
    #: chosen from PIO_SERVE_MIPS_QUANT at BUILD time; the unselected
    #: view is a 1-row placeholder (at 1M×128 the spare view would pin
    #: hundreds of MB of HBM that nothing ever reads). A knob flip
    #: takes effect at the next rebuild.
    quant: str = "int8"
    built_at: float = 0.0     # wall ts of last build/update/publish
    rebuilds: int = 0         # full builds that produced this index
    delta_updates: int = 0    # O(delta) update_index applications
    #: PQ residual codes (quant == "pq"): bucket-major [C, cap, M]
    #: uint8 codes + [M, 256, rank/M] f32 codebooks, host mirrors for
    #: the O(delta) splice path. Placeholder-shaped under int8/bf16.
    pq_codes: Optional[jax.Array] = None
    pq_books: Optional[jax.Array] = None
    pq_codes_np: Optional[np.ndarray] = None
    pq_books_np: Optional[np.ndarray] = None
    pq_m: int = 0
    #: daemon-rebuild ext block: folded virtual-id rows [E_pad, K] f32
    #: at ids [capacity, capacity + n_ext) — the published id space
    #: stays stable across a swap, so the overlay's key→id map and any
    #: in-flight exclusion list survive unchanged
    ext: Optional[jax.Array] = None
    ext_np: Optional[np.ndarray] = None
    n_ext: int = 0
    #: true table capacity (padded row count). Under PQ every dense
    #: view is a placeholder, so the ``capacity`` property can no
    #: longer derive it from a view shape.
    capacity_rows: int = 0
    #: host cold tier (rebuild-daemon decision) — None when every
    #: bucket is device-resident
    cold: Optional[ColdTier] = None
    #: serving-engine label for the pio_mips_tail_size gauge
    engine: str = "default"
    #: host mirrors of the probe-bound arrays (the host-side probe
    #: used by cold-tier serving and the probe-hit sampler)
    cmax_np: Optional[np.ndarray] = None
    crad_cos_np: Optional[np.ndarray] = None
    crad_sin_np: Optional[np.ndarray] = None

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        #: exact tail: global/virtual id -> fresh f32 vector (host)
        self._tail: "Dict[int, np.ndarray]" = {}
        self._tail_pack: Optional[Tuple[np.ndarray, np.ndarray]] = None
        #: publish sequence numbers per tail id: the rebuild daemon
        #: snapshots a watermark, folds everything at-or-below it, and
        #: the swap carries newer entries into the successor's tail —
        #: a key published DURING a rebuild is never lost
        self._tail_seqs: Dict[int, int] = {}
        self._tail_seq = 0
        #: set under ``_lock`` at swap time: a publisher that raced the
        #: swap re-routes its entries to the successor index
        self._superseded: Optional["MIPSIndex"] = None
        self._next_virtual = self.capacity + self.n_ext
        self._table_ref: Optional[weakref.ref] = None
        #: per-bucket probe-hit counters (host, sampled) — the tiering
        #: daemon's demotion signal
        self.probe_hits = np.zeros(self.c_total, np.int64)
        self._probe_samples = 0
        self._dispatches = 0
        #: rows churned (published / delta-updated) since this index
        #: was built — a rebuild-daemon trigger input
        self.churn_rows = 0
        if not self.built_at:
            self.built_at = _now()

    @property
    def c_total(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def capacity(self) -> int:
        if self.capacity_rows:
            return self.capacity_rows
        # legacy derivation: the MATERIALIZED view carries the padded
        # table shape (the unselected view is a placeholder)
        view = self.bf16 if self.quant == "bf16" else self.codes
        return int(view.shape[0])

    def geometry(self) -> Tuple[int, int, int, int]:
        """What must match for an O(delta) update to splice in place —
        a change here is a reshard/regrow and means full rebuild."""
        return (self.capacity, self.rank, self.n_shards, self.cap)

    def tier_rows(self) -> Tuple[int, int]:
        """(device rows, host cold rows) — the pio_mips_tier_rows
        split (the exact tail is counted by the collector)."""
        host = self.cold.rows if self.cold is not None else 0
        return (self.n_items + self.n_ext - host, host)

    # -- exact tail ---------------------------------------------------------
    def tail_arrays(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(ids [T] int64, vecs [T, K] f32) or None when empty; packed
        lazily and cached until the next publish."""
        with self._lock:
            if not self._tail:
                return None
            if self._tail_pack is None:
                ids = np.fromiter(self._tail, np.int64,
                                  count=len(self._tail))
                vecs = np.stack([self._tail[int(i)] for i in ids])
                self._tail_pack = (ids, vecs.astype(np.float32))
            return self._tail_pack

    def tail_size(self) -> int:
        with self._lock:
            return len(self._tail)

    def tail_virtual_size(self) -> int:
        """Virtual-id tail entries (new keys not yet folded into the
        index) — the rebuild daemon's tail trigger input. Known-row
        overrides are excluded: they live in the tail until the next
        retrain by design and must not force rebuilds forever."""
        with self._lock:
            return sum(1 for g in self._tail if g >= self.capacity)

    def stats(self) -> Dict[str, Any]:
        dev, host = self.tier_rows()
        return {
            "items": self.n_items,
            "capacity": self.capacity,
            "centroids": self.c_total,
            "bucketCap": self.cap,
            "shards": self.n_shards,
            "tail": self.tail_size(),
            "tailVirtual": self.tail_virtual_size(),
            "ageSec": round(_now() - self.built_at, 1),
            "rebuilds": self.rebuilds,
            "deltaUpdates": self.delta_updates,
            "quant": self.quant,
            "pqM": self.pq_m,
            "ext": self.n_ext,
            "deviceRows": dev,
            "hostRows": host,
            "churnRows": self.churn_rows,
            "engine": self.engine,
        }


@dataclasses.dataclass
class _Entry:
    ref: weakref.ref
    index: MIPSIndex


#: id(table) -> entry; the weakref callback unregisters when the table
#: is collected, so a dropped model never pins its index
_REGISTRY: Dict[int, _Entry] = {}


def register_index(table: Any, index: MIPSIndex) -> MIPSIndex:
    key = id(table)

    def _drop(_ref: Any, key: int = key) -> None:
        _REGISTRY.pop(key, None)

    index._table_ref = weakref.ref(table, _drop)
    _REGISTRY[key] = _Entry(ref=index._table_ref, index=index)
    return index


def unregister_index(table: Any) -> None:
    _REGISTRY.pop(id(table), None)


def index_for(table: Any) -> Optional[MIPSIndex]:
    entry = _REGISTRY.get(id(table))
    if entry is None:
        return None
    # id() reuse guard: the key survives only while THIS table does
    if entry.ref() is not table:
        _REGISTRY.pop(id(table), None)
        return None
    return entry.index


def registered_index_count() -> int:
    return len(_REGISTRY)


def registered_tables() -> List[Tuple[Any, MIPSIndex]]:
    """Live (table, index) pairs — the rebuild daemon's scan set.
    Holding the returned table reference pins it for the rebuild."""
    out = []
    for entry in list(_REGISTRY.values()):
        table = entry.ref()
        if table is not None:
            out.append((table, entry.index))
    return out


def adopt_index(prev_table: Any, new_table: Any) -> Optional[MIPSIndex]:
    """Move a registered index onto a VALUE-IDENTICAL replacement table
    (the deploy-time ``prepare_model`` re-device_put of factors that
    were just trained in this process) — skipping the full rebuild the
    new object identity would otherwise force. The caller owns the
    equal-values contract; a shape mismatch refuses."""
    index = index_for(prev_table)
    if index is None or prev_table is new_table:
        return index
    if tuple(new_table.shape) != (index.capacity, index.rank):
        return None
    unregister_index(prev_table)
    register_index(new_table, index)
    # an adoption IS a swap: the index now serves a freshly deployed
    # table, so the age collector's baseline resets exactly like a
    # retrain build/update would reset it (pio_mips_index_age_seconds
    # must never report a hot-swapped index as stale)
    index.built_at = _now()
    return index


def status_snapshot() -> List[Dict[str, Any]]:
    """One ``stats()`` dict per live registered index — the ``mips``
    block of the prediction server's ``/status``."""
    out = []
    for e in list(_REGISTRY.values()):
        if e.ref() is None:
            continue
        try:
            out.append(e.index.stats())
        except Exception:     # a racing swap must never break /status
            logger.exception("mips status snapshot failed")
    return out


def route(table: Any, *, k: int,
          allowed_mask: Optional[Any] = None,
          exclude: Optional[Any] = None) -> Optional[MIPSIndex]:
    """THE auto-router predicate (ops/topk.py calls it on every serve
    entry): the registered index when the two-stage path should serve
    this query, else None → exhaustive. Filtered queries
    (``allowed_mask``) always fall back — an arbitrary mask can
    invalidate any candidate budget, and exhaustive honors it exactly.
    So does a query whose exclusion list rivals the candidate budget
    (a power user's seen set is exactly the rows that dominate the
    coarse cut — masking most of a fixed-width rerank would return far
    fewer than k real rows where exhaustive returns a full top-k)."""
    mode = serving_mode()
    if mode == "off" or allowed_mask is not None:
        return None
    index = index_for(table)
    if index is None or index.n_items < 2:
        return None
    if k >= index.n_items:
        return None  # top-"everything": the scan IS the answer
    if exclude is not None:
        width = int(getattr(exclude, "shape", (len(exclude),))[-1])
        if 2 * width >= _candidates_for(index, k):
            return None
    return index


def book_exhaustive(rows: int) -> None:
    """Scan accounting for the exhaustive fallback path (called by the
    ops/topk wrappers — never from inside a trace)."""
    _SCAN_EXHAUSTIVE.inc(rows)


# ---------------------------------------------------------------------------
# build / update / publish
# ---------------------------------------------------------------------------

#: members whose norm is at least this fraction of their bucket's max
#: participate in the probe-bound ball radius (see build_index): only
#: near-max rows can win a query through the bound, and letting every
#: moderate-norm member widen the ball degrades the probe ranking to
#: cmax alone (measured: recall 1.0 -> 0.93 on the planted fixture)
_RADIUS_NORM_FRAC = 0.8


def _quantize_int8(vf: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    scales = np.abs(vf).max(axis=1) / 127.0
    scales = np.maximum(scales, 1e-12).astype(np.float32)
    codes = np.rint(vf / scales[:, None]).astype(np.int8)
    return codes, scales


def _bf16(vf: np.ndarray) -> np.ndarray:
    import ml_dtypes

    return vf.astype(ml_dtypes.bfloat16)


#: PQ training budget: 256 codewords per subspace, Lloyd on a bounded
#: residual sample — build cost stays O(sample · 256 · K) however
#: large the catalogue is (the 10M-item build trains on the same 16k
#: rows a 100k build would)
_PQ_CODEBOOK = 256
_PQ_TRAIN_SAMPLE = 16384
_PQ_ITERS = 6


def _pq_train_books(res: np.ndarray, m: int, seed: int) -> np.ndarray:
    """[M, 256, rank/M] euclidean Lloyd codebooks over the residual
    subspaces. Residuals (row − assigned centroid) are what the codes
    must reconstruct — the centroid part of the score is exact (the
    probe stage already computed q·c for every bucket)."""
    n, rank = res.shape
    d = rank // m
    rng = np.random.default_rng(seed + 17)
    fit = (res if n <= _PQ_TRAIN_SAMPLE
           else res[rng.choice(n, _PQ_TRAIN_SAMPLE, replace=False)])
    books = np.zeros((m, _PQ_CODEBOOK, d), np.float32)
    if len(fit) == 0:
        return books
    for mi in range(m):
        sub = fit[:, mi * d:(mi + 1) * d].astype(np.float32)
        c = sub[rng.choice(len(sub), _PQ_CODEBOOK,
                           replace=len(sub) < _PQ_CODEBOOK)].copy()
        for _ in range(_PQ_ITERS):
            # nearest codeword by euclidean distance, via the
            # BLAS-shaped argmax(2·x·c − |c|²) expansion
            sc = 2.0 * sub @ c.T - (c * c).sum(axis=1)[None, :]
            a = np.argmax(sc, axis=1)
            sums = np.zeros((_PQ_CODEBOOK, d), np.float64)
            np.add.at(sums, a, sub)
            cnt = np.bincount(a, minlength=_PQ_CODEBOOK)
            nz = cnt > 0
            c[nz] = (sums[nz] / cnt[nz, None]).astype(np.float32)
        books[mi] = c
    return books


def _pq_encode(res: np.ndarray, books: np.ndarray,
               chunk: int = 65536) -> np.ndarray:
    """[n, M] uint8 nearest-codeword ids per subspace, chunked so the
    [chunk, 256] score block stays cache-sized."""
    n = len(res)
    m, _cb, d = books.shape
    codes = np.empty((n, m), np.uint8)
    for mi in range(m):
        sub = res[:, mi * d:(mi + 1) * d].astype(np.float32)
        bt = books[mi]
        pen = (bt * bt).sum(axis=1)[None, :]
        for s in range(0, n, chunk):
            sc = 2.0 * sub[s:s + chunk] @ bt.T - pen
            codes[s:s + chunk, mi] = np.argmax(sc, axis=1).astype(
                np.uint8)
    return codes


def _pq_pack(assign: np.ndarray, codes: np.ndarray, c: int,
             cap: int) -> np.ndarray:
    """Bucket-major [c, cap, M] uint8 code slots, laid out with the
    SAME stable-argsort slot order as :func:`_pack_members` — slot i of
    bucket b in ``members`` and in the PQ codes is the same row."""
    out = np.zeros((c, cap, codes.shape[1]), np.uint8)
    counts = np.bincount(assign, minlength=c)
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(order)) - starts[assign[order]]
    out[assign[order], pos] = codes[order]
    return out


def _spherical_kmeans(rows: np.ndarray, c: int, seed: int,
                      iters: int = 8,
                      sample_cap: int = 0) -> np.ndarray:
    """[c, K] unit centroids via seeded Lloyd on normalized rows; fitted
    on a bounded sample (64 rows per centroid) so build cost stays
    O(C²·K·iters) however large the shard is."""
    rng = np.random.default_rng(seed)
    unit = rows / np.maximum(
        np.linalg.norm(rows, axis=1, keepdims=True), 1e-9)
    cap = sample_cap or 64 * c
    fit = unit if len(unit) <= cap else unit[
        rng.choice(len(unit), cap, replace=False)]
    if len(fit) == 0:
        return np.zeros((c, rows.shape[1]), np.float32)
    cent = fit[rng.choice(len(fit), c, replace=len(fit) < c)].copy()
    for _ in range(iters):
        assign = np.argmax(fit @ cent.T, axis=1)
        for j in range(c):
            m = fit[assign == j]
            if len(m):
                mu = m.mean(axis=0)
                cent[j] = mu / max(float(np.linalg.norm(mu)), 1e-9)
    return cent.astype(np.float32)


def _assign_chunked(vf: np.ndarray, cent: np.ndarray,
                    chunk: int = 65536) -> np.ndarray:
    """argmax-cosine bucket of every row (norm cancels in the argmax),
    chunked so the [rows, C] score block never exceeds ~256 MB."""
    out = np.empty(len(vf), np.int32)
    for s in range(0, len(vf), chunk):
        out[s:s + chunk] = np.argmax(vf[s:s + chunk] @ cent.T, axis=1)
    return out


#: bucket preferences kept per row for the balanced spill (a row
#: overflowing its 8 best buckets goes to the emptiest open one)
_BALANCE_PREFS = 8


def _balanced_assign(vf: np.ndarray, cent: np.ndarray, cap: int,
                     chunk: int = 65536) -> np.ndarray:
    """Capacity-bounded bucket assignment: best-centroid first, spill
    to the next-best OPEN bucket when full.

    The bucket cap is the member-gather width the coarse stage pays
    for EVERY probed bucket (a padding slot reads like a real row), so
    bounding it near the mean — instead of letting k-means skew set it
    — is what holds the candidates-scanned fraction at the analytic
    nprobe/C × cap/mean figure. Fully vectorized: per-chunk top-8
    preference lists, then round-based greedy fill (rows contending
    for one bucket are admitted best-score-first, deterministically)."""
    n, c = len(vf), len(cent)
    p = min(_BALANCE_PREFS, c)
    pref = np.empty((n, p), np.int32)
    pscore = np.empty((n, p), np.float32)
    for s in range(0, n, chunk):
        scores = vf[s:s + chunk] @ cent.T
        top = np.argpartition(-scores, p - 1, axis=1)[:, :p]
        ts = np.take_along_axis(scores, top, axis=1)
        order = np.argsort(-ts, axis=1, kind="stable")
        pref[s:s + chunk] = np.take_along_axis(top, order, axis=1)
        pscore[s:s + chunk] = np.take_along_axis(ts, order, axis=1)
    assign = np.full(n, -1, np.int32)
    fill = np.zeros(c, np.int64)
    for _round in range(p):
        un = np.nonzero(assign < 0)[0]
        if not len(un):
            break
        open_ = fill < cap
        ok = open_[pref[un]]                        # [U, p]
        first = np.argmax(ok, axis=1)
        has = np.take_along_axis(ok, first[:, None], 1)[:, 0]
        un = un[has]
        if not len(un):
            break
        first = first[has]
        target = pref[un, first]
        score = pscore[un, first]
        # admit best-score-first within each contended bucket
        order = np.lexsort((-score, target))
        tsorted = target[order]
        counts = np.bincount(tsorted, minlength=c)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        pos = np.arange(len(order)) - starts[tsorted]
        accept = pos < (cap - fill)[tsorted]
        rows = un[order][accept]
        assign[rows] = tsorted[accept]
        fill += np.bincount(tsorted[accept], minlength=c)
    left = np.nonzero(assign < 0)[0]
    for row in left:  # bounded leftovers: total capacity > n by build
        b = int(np.argmin(fill))
        assign[row] = b
        fill[b] += 1
    return assign


def _pack_members(assign: np.ndarray, row_ids: np.ndarray, c: int,
                  cap: int) -> Tuple[np.ndarray, np.ndarray]:
    """Bucket member lists [c, cap] (global ids, -1 padded) + counts —
    one stable argsort, no Python loop over rows."""
    members = np.full((c, cap), -1, np.int32)
    counts = np.bincount(assign, minlength=c)
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(order)) - starts[assign[order]]
    members[assign[order], pos] = row_ids[order].astype(np.int32)
    return members, counts.astype(np.int64)


def _device_put_index(arr: np.ndarray, table: Any) -> jax.Array:
    """Place an index array alongside its table: same mesh, axis-0
    sharded when the table is distributed, else plain device_put."""
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    if is_distributed(table):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = table.sharding.mesh
        return jax.device_put(
            arr, NamedSharding(mesh, P(tuple(mesh.axis_names))))
    return jax.device_put(arr)


def _device_put_replicated(arr: np.ndarray, table: Any) -> jax.Array:
    """Replicated placement (PQ codebooks: [M, 256, d] is KB-scale and
    every shard needs the full set — axis-0 sharding would split the
    subquantizers)."""
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    if is_distributed(table):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            arr, NamedSharding(table.sharding.mesh, P()))
    return jax.device_put(arr)


def build_index(
    table: Any,                # [I_pad, K] f32 device table (maybe sharded)
    n_items: int,
    *,
    seed: int = 0,
    n_centroids: Optional[int] = None,
    host_factors: Optional[np.ndarray] = None,
    register: bool = True,
    probe_recall: bool = False,
    engine: str = "default",
) -> MIPSIndex:
    """Full build at train/retrain/publish time (host k-means + one
    assignment pass + quantization, then device placement). Per-shard
    when the table is distributed: shard ``s`` gets ``C/n`` buckets
    fitted and filled ONLY from the rows it owns."""
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    t0 = time.perf_counter()
    i_pad, rank = int(table.shape[0]), int(table.shape[1])
    n_items = min(int(n_items), i_pad)
    n_shards = 1
    if is_distributed(table):
        n_shards = int(table.sharding.mesh.devices.size)
    vf = (np.asarray(host_factors[:n_items], np.float32)
          if host_factors is not None
          else np.asarray(table[:n_items], np.float32))
    shard_rows = i_pad // n_shards
    # bucket granularity is sized from the PER-SHARD catalogue (each
    # shard keeps a full-resolution mini-index over the rows it owns);
    # splitting one global budget n ways would coarsen buckets with the
    # mesh and sink the sharded recall gate
    if n_centroids:
        c_local = max(_next_pow2(n_centroids) // n_shards, 1)
    else:
        c_local = default_centroids(-(-n_items // n_shards))
    c_total = c_local * n_shards

    # balanced-bucket cap ≈ 1.25× the mean bucket size (8-aligned):
    # every probed bucket's cap slots are gathered whether occupied or
    # not, so padding headroom is pure wasted HBM read — the spill
    # assignment keeps recall while the cap pins the scanned fraction
    # at the analytic nprobe/C figure
    biggest_shard = max(
        (min((s + 1) * shard_rows, n_items) - s * shard_rows
         for s in range(n_shards)
         if min((s + 1) * shard_rows, n_items) > s * shard_rows),
        default=1)
    mean_bucket = -(-biggest_shard // c_local)
    cap = max(-(-int(mean_bucket * 1.25) // 8) * 8, 8)
    assign = np.zeros(n_items, np.int32)
    cent = np.zeros((c_total, rank), np.float32)
    for s in range(n_shards):
        lo = s * shard_rows
        hi = min(lo + shard_rows, n_items)
        if hi <= lo:
            # an empty shard keeps zero centroids; its buckets stay
            # empty and its coarse scan scores NEG_INF everywhere
            continue
        local = vf[lo:hi]
        cent_s = _spherical_kmeans(local, c_local, seed + s)
        assign[lo:hi] = (s * c_local
                         + _balanced_assign(local, cent_s, cap))
        cent[s * c_local:(s + 1) * c_local] = cent_s
    members_np, counts = _pack_members(
        assign, np.arange(n_items, dtype=np.int64), c_total, cap)
    norms = np.linalg.norm(vf, axis=1).astype(np.float32)
    cmax = np.zeros(c_total, np.float32)
    np.maximum.at(cmax, assign, norms)
    # bucket ball radius over the HIGH-NORM members (≥ ½·cmax): the
    # probe ranks buckets by cmax·|q|·cos(θ_qc − r). Plain cmax·cosθ_qc
    # under-ranks a bucket whose best match sits off-centroid (the
    # retrain-moved-outlier case); the FULL worst-member radius swings
    # the other way — one spilled ordinary row balloons every bucket's
    # ball and the ranking degenerates to cmax alone. Only rows with
    # norm comparable to the bucket max can actually win a query, so
    # only they widen the ball.
    unit = vf / np.maximum(norms[:, None], 1e-9)
    row_cos = np.einsum("ik,ik->i", unit, cent[assign])
    crad_cos = np.ones(c_total, np.float32)
    high = norms >= _RADIUS_NORM_FRAC * cmax[assign]
    np.minimum.at(crad_cos, assign[high],
                  row_cos[high].astype(np.float32))
    crad_cos = np.clip(crad_cos, -1.0, 1.0)
    crad_sin = np.sqrt(1.0 - crad_cos * crad_cos).astype(np.float32)

    # materialize ONLY the selected quantized view (the others would
    # pin table-scale HBM nothing reads); placeholders keep the jit
    # signatures uniform — the static `quant` branch never touches them
    quant = _quant_mode()
    pq_m = 0
    pq_codes_np = np.zeros((n_shards, 1, 1), np.uint8)
    pq_books_np = np.zeros((1, _PQ_CODEBOOK, 1), np.float32)
    if quant == "pq":
        # residuals vs the ASSIGNED centroid: the probe stage computes
        # q·c exactly for every bucket, so the codes only need to
        # carry the residual part of the inner product
        pq_m = _pq_m(rank)
        res = vf - cent[assign]
        pq_books_np = _pq_train_books(res, pq_m, int(seed))
        pq_codes_np = _pq_pack(assign, _pq_encode(res, pq_books_np),
                               c_total, cap)
        codes = np.zeros((n_shards, rank), np.int8)
        scales = np.zeros(n_shards, np.float32)
        bf16_view = _bf16(np.zeros((n_shards, rank), np.float32))
    elif quant == "bf16":
        vf_pad = (np.concatenate(
            [vf, np.zeros((i_pad - n_items, rank), np.float32)])
            if i_pad > n_items else vf)
        # placeholder rows = n_shards so the uniform axis-0 sharding
        # still divides
        codes = np.zeros((n_shards, rank), np.int8)
        scales = np.zeros(n_shards, np.float32)
        bf16_view = _bf16(vf_pad)
    else:
        codes, scales = _quantize_int8(vf)
        if i_pad > n_items:
            pad = i_pad - n_items
            codes = np.concatenate(
                [codes, np.zeros((pad, rank), np.int8)])
            scales = np.concatenate([scales, np.zeros(pad, np.float32)])
        bf16_view = _bf16(np.zeros((n_shards, rank), np.float32))

    index = MIPSIndex(
        codes=_device_put_index(codes, table),
        scales=_device_put_index(scales, table),
        bf16=_device_put_index(bf16_view, table),
        centroids=_device_put_index(cent, table),
        cmax=_device_put_index(cmax, table),
        crad_cos=_device_put_index(crad_cos, table),
        crad_sin=_device_put_index(crad_sin, table),
        members=_device_put_index(members_np, table),
        assign=assign, members_np=members_np, centroids_np=cent,
        counts=counts, n_items=n_items, n_shards=n_shards,
        c_local=c_local, cap=cap, rank=rank, seed=int(seed),
        quant=quant, rebuilds=1,
        pq_codes=_device_put_index(pq_codes_np, table),
        pq_books=_device_put_replicated(pq_books_np, table),
        pq_codes_np=pq_codes_np, pq_books_np=pq_books_np, pq_m=pq_m,
        capacity_rows=i_pad, engine=engine,
        cmax_np=cmax.copy(), crad_cos_np=crad_cos.copy(),
        crad_sin_np=crad_sin.copy(),
    )
    if register:
        register_index(table, index)
    if probe_recall and register:
        try:
            recall_probe(table, index, host_factors=vf)
        except Exception:
            logger.exception("mips recall probe failed at build")
    logger.info(
        "mips index built: %d items, %d centroids (cap %d, %d shard%s) "
        "in %.2fs", n_items, c_total, cap, n_shards,
        "s" if n_shards != 1 else "", time.perf_counter() - t0)
    return index


def update_index(
    prev_table: Any,
    new_table: Any,
    n_items: int,
    touched_rows: Optional[np.ndarray],
) -> Optional[MIPSIndex]:
    """O(delta) continuation-retrain splice: re-quantize + re-assign
    ONLY the touched/new rows of the index registered for
    ``prev_table`` and re-register it under ``new_table``. Returns None
    (caller rebuilds) when no index is registered, the shard geometry
    or capacity changed (reshard → full rebuild is the contract), or
    the new ids outgrew the padded capacity."""
    index = index_for(prev_table)
    if index is None or touched_rows is None:
        return None
    i_pad, rank = int(new_table.shape[0]), int(new_table.shape[1])
    n_shards = 1
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    if is_distributed(new_table):
        n_shards = int(new_table.sharding.mesh.devices.size)
    if (i_pad, rank, n_shards) != (index.capacity, index.rank,
                                   index.n_shards):
        return None
    if index.n_ext or index.cold is not None:
        # a daemon-rebuilt index carries folded virtual rows (ext) or
        # a host cold tier keyed to a probe-stats window the retrain
        # invalidates — the splice contract doesn't cover either, so
        # the caller full-rebuilds (which also re-homes the ext rows)
        return None
    n_items = int(n_items)
    if n_items > index.capacity:
        return None
    touched = np.unique(np.concatenate([
        np.asarray(touched_rows, np.int64).ravel(),
        np.arange(index.n_items, n_items, dtype=np.int64),
    ]))
    touched = touched[(touched >= 0) & (touched < n_items)]
    if len(touched):
        tj = jnp.asarray(touched.astype(np.int32))
        vt = np.asarray(new_table[tj], np.float32)
        if index.quant == "pq":
            # PQ codes live bucket-major and encode residuals vs the
            # ASSIGNED centroid — re-home first, then encode against
            # the final (bucket, slot) home
            _reassign_rows(index, touched, vt)
            _requantize_rows(index, tj, vt)
        else:
            _requantize_rows(index, tj, vt)
            _reassign_rows(index, touched, vt)
    index.n_items = n_items
    index.delta_updates += 1
    index.churn_rows += len(touched)
    index.built_at = _now()
    with index._lock:
        # republished rows supersede their tail overrides; genuinely
        # new virtual entries (ids past capacity) survive the splice
        for row in touched:
            index._tail.pop(int(row), None)
        index._tail_pack = None
    unregister_index(prev_table)
    register_index(new_table, index)
    return index


def _requantize_rows(index: MIPSIndex, rows_j: jax.Array,
                     vecs: np.ndarray) -> None:
    """Splice fresh vectors into the MATERIALIZED quantized view (the
    other views are placeholders — see ``MIPSIndex.quant``). Under PQ
    the codes are bucket-major: each row re-encodes against its
    CURRENT bucket's centroid into its member slot (call after any
    re-assignment); rows not in any device bucket (cold/tail-only) are
    skipped — their exact tail entry serves them."""
    if index.quant == "pq":
        rows_np = np.asarray(rows_j, np.int64)
        changed: set = set()
        for pos, row in enumerate(rows_np):
            b = (int(index.assign[row])
                 if row < len(index.assign) else -1)
            if b < 0:
                continue
            slots = np.nonzero(index.members_np[b] == row)[0]
            if not len(slots):
                continue
            res = (vecs[pos].astype(np.float32)
                   - index.centroids_np[b])
            index.pq_codes_np[b, slots[0]] = _pq_encode(
                res[None, :], index.pq_books_np)[0]
            changed.add(b)
        if changed:
            bids = np.asarray(sorted(changed), np.int32)
            index.pq_codes = index.pq_codes.at[jnp.asarray(bids)].set(
                jnp.asarray(index.pq_codes_np[bids]))
        return
    if index.quant == "bf16":
        index.bf16 = index.bf16.at[rows_j].set(
            jnp.asarray(vecs).astype(jnp.bfloat16))
        return
    codes_t, scales_t = _quantize_int8(vecs)
    index.codes = index.codes.at[rows_j].set(jnp.asarray(codes_t))
    index.scales = index.scales.at[rows_j].set(jnp.asarray(scales_t))


def _reassign_rows(index: MIPSIndex, rows: np.ndarray,
                   vecs: np.ndarray) -> None:
    """Move ``rows`` to their nearest same-shard bucket on the host
    mirrors, then splice ONLY the changed buckets to the device —
    O(delta · cap), never a full repack."""
    shard_rows = index.capacity // index.n_shards
    grown = np.setdiff1d(rows, np.arange(len(index.assign)),
                         assume_unique=False)
    if len(grown):
        index.assign = np.concatenate([
            index.assign,
            np.full(int(rows.max()) + 1 - len(index.assign), -1,
                    np.int32)])
    changed_buckets = set()
    changed_cmax: Dict[int, float] = {}
    changed_crad: Dict[int, float] = {}
    norms = np.linalg.norm(vecs, axis=1)
    cmax_np = np.array(index.cmax)  # np.asarray of a jax array is RO

    def note_radius(bucket: int, pos: int) -> None:
        # widen the bucket's ball to cover the (re-solved / re-homed)
        # row's direction — but only for rows heavy enough to win a
        # query (the same _RADIUS_NORM_FRAC rule as the build)
        if norms[pos] < _RADIUS_NORM_FRAC * cmax_np[bucket]:
            return
        cos = float(vecs[pos] @ index.centroids_np[bucket]
                    / max(norms[pos], 1e-9))
        changed_crad[bucket] = min(changed_crad.get(bucket, 1.0), cos)

    for pos, row in enumerate(np.asarray(rows, np.int64)):
        shard = int(row) // shard_rows
        base = shard * index.c_local
        cent_s = index.centroids_np[base:base + index.c_local]
        new_b = base + int(np.argmax(cent_s @ vecs[pos]))
        old_b = int(index.assign[row]) if row < len(index.assign) else -1
        if norms[pos] > cmax_np[new_b]:
            cmax_np[new_b] = norms[pos]
            changed_cmax[new_b] = float(norms[pos])
        if old_b == new_b:
            note_radius(old_b, pos)
            continue
        if index.counts[new_b] >= index.cap:
            if old_b >= 0:
                # full target: keep the old membership (the fresh codes
                # still score there; widen the old ball accordingly) —
                # the next full rebuild repacks
                note_radius(old_b, pos)
                if norms[pos] > cmax_np[old_b]:
                    cmax_np[old_b] = norms[pos]
                    changed_cmax[old_b] = float(norms[pos])
                continue
            # a NEW row with a full best bucket must live SOMEWHERE:
            # spill to the emptiest bucket of its shard, else (shard
            # totally full) serve it exactly from the tail until the
            # next rebuild
            new_b = base + int(np.argmin(
                index.counts[base:base + index.c_local]))
            if index.counts[new_b] >= index.cap:
                with index._lock:
                    index._tail[int(row)] = np.asarray(
                        vecs[pos], np.float32)
                    index._tail_seq += 1
                    index._tail_seqs[int(row)] = index._tail_seq
                    index._tail_pack = None
                continue
            if norms[pos] > cmax_np[new_b]:
                cmax_np[new_b] = norms[pos]
                changed_cmax[new_b] = float(norms[pos])
        if old_b >= 0:
            slots = index.members_np[old_b]
            hit = np.nonzero(slots == row)[0]
            if len(hit):
                last = int(index.counts[old_b]) - 1
                slots[hit[0]] = slots[last]
                slots[last] = -1
                if index.quant == "pq":
                    # the compaction moved the LAST member into the
                    # vacated slot — its PQ code moves with it (the
                    # slot layouts of members and pq_codes are one)
                    index.pq_codes_np[old_b, hit[0]] = (
                        index.pq_codes_np[old_b, last])
                index.counts[old_b] = last
                changed_buckets.add(old_b)
        index.members_np[new_b, int(index.counts[new_b])] = row
        index.counts[new_b] += 1
        index.assign[row] = new_b
        changed_buckets.add(new_b)
        note_radius(new_b, pos)
    if changed_buckets:
        buckets = np.asarray(sorted(changed_buckets), np.int32)
        index.members = index.members.at[jnp.asarray(buckets)].set(
            jnp.asarray(index.members_np[buckets]))
        if index.quant == "pq":
            index.pq_codes = index.pq_codes.at[
                jnp.asarray(buckets)].set(
                jnp.asarray(index.pq_codes_np[buckets]))
    if changed_cmax:
        # per-bucket .at[] splice (never a fresh jnp.asarray) so a
        # sharded cmax keeps its placement through the update
        bids = np.asarray(sorted(changed_cmax), np.int32)
        vals = np.asarray([changed_cmax[int(b)] for b in bids],
                          np.float32)
        index.cmax = index.cmax.at[jnp.asarray(bids)].set(
            jnp.asarray(vals))
        if index.cmax_np is not None:
            index.cmax_np[bids] = vals
    if changed_crad:
        bids = jnp.asarray(np.asarray(sorted(changed_crad), np.int32))
        vals = jnp.asarray(np.asarray(
            [changed_crad[int(b)] for b in np.asarray(bids)],
            np.float32))
        index.crad_cos = index.crad_cos.at[bids].min(vals)
        cos_b = index.crad_cos[bids]
        index.crad_sin = index.crad_sin.at[bids].set(
            jnp.sqrt(jnp.maximum(1.0 - cos_b * cos_b, 0.0)))
        if index.crad_cos_np is not None:
            bnp = np.asarray(bids)
            index.crad_cos_np[bnp] = np.minimum(
                index.crad_cos_np[bnp], np.asarray(vals))
            index.crad_sin_np[bnp] = np.sqrt(np.maximum(
                1.0 - index.crad_cos_np[bnp] ** 2, 0.0))


def publish_rows(
    table: Any,
    vecs: np.ndarray,               # [T, K] fresh f32 vectors
    rows: Optional[Sequence[int]] = None,   # per-vec base row, -1 = new
) -> Optional[np.ndarray]:
    """Speed-overlay publish seam: fold-in vectors enter serving NOW.

    Known rows (``rows[i] >= 0``) are re-quantized in place (the coarse
    stage sees the fresh vector) AND recorded in the exact tail — the
    published solve, not the stale base row, is what the merged result
    scores. New keys (``rows[i] < 0`` or ``rows=None``) get virtual ids
    (``>= capacity``) in the tail only; the next build/update folds
    them out. Returns the assigned global/virtual ids, or None when no
    index is registered for ``table`` (publishing is always safe to
    call)."""
    index = index_for(table)
    if index is None:
        return None
    vecs = np.asarray(vecs, np.float32)
    if vecs.ndim == 1:
        vecs = vecs[None, :]
    if rows is None:
        rows_arr = np.full(len(vecs), -1, np.int64)
    else:
        rows_arr = np.asarray(rows, np.int64).ravel()
    known = np.nonzero((rows_arr >= 0)
                       & (rows_arr < index.n_items))[0]
    if len(known):
        rj = jnp.asarray(rows_arr[known].astype(np.int32))
        _requantize_rows(index, rj, vecs[known])
    out_ids = np.empty(len(vecs), np.int64)
    known_set = set(known.tolist())
    while True:
        with index._lock:
            successor = index._superseded
            if successor is None:
                for pos in range(len(vecs)):
                    if pos in known_set:
                        gid = int(rows_arr[pos])
                    else:
                        gid = index._next_virtual
                        index._next_virtual += 1
                    index._tail[gid] = vecs[pos]
                    index._tail_seq += 1
                    index._tail_seqs[gid] = index._tail_seq
                    out_ids[pos] = gid
                index._tail_pack = None
                index.churn_rows += len(vecs)
        if successor is None:
            break
        # a daemon swap raced this publish: the successor is already
        # registered, so record the entries there (the swap's tail
        # carry-over only covers entries that existed under the OLD
        # lock — re-routing here closes the window)
        index = successor
    index.built_at = _now()
    return out_ids


# ---------------------------------------------------------------------------
# background rebuild (ops/mips_daemon.py drives this off-path)
# ---------------------------------------------------------------------------

def _tier_mode() -> str:
    """off | auto | on: host-tiering of cold buckets at rebuild time.
    ``auto`` (default) demotes only with enough probe-hit samples;
    ``on`` trusts whatever counters exist (tests plant them)."""
    m = os.environ.get("PIO_MIPS_TIER", "auto").strip().lower()
    return m if m in ("off", "auto", "on") else "auto"


def _tier_min_samples() -> int:
    return _env_int("PIO_MIPS_TIER_MIN_SAMPLES", 32)


def _tier_max_frac() -> float:
    try:
        return min(max(float(os.environ.get(
            "PIO_MIPS_TIER_MAX_FRAC", "") or 0.5), 0.0), 0.9)
    except ValueError:
        return 0.5


def _build_cold(vecs: np.ndarray, ids: np.ndarray,
                seed: int) -> ColdTier:
    """Cluster the demoted rows into their own host mini-index (same
    probe-bound geometry as the device index, numpy arrays only)."""
    n = len(ids)
    cc = min(max(_next_pow2(int(np.sqrt(max(n, 1)))), 16), 1024)
    cent = _spherical_kmeans(vecs, cc, seed + 31)
    assign = _assign_chunked(vecs, cent)
    norms = np.linalg.norm(vecs, axis=1).astype(np.float32)
    cmax = np.zeros(cc, np.float32)
    np.maximum.at(cmax, assign, norms)
    unit = vecs / np.maximum(norms[:, None], 1e-9)
    row_cos = np.einsum("ik,ik->i", unit, cent[assign])
    crad_cos = np.ones(cc, np.float32)
    high = norms >= _RADIUS_NORM_FRAC * cmax[assign]
    np.minimum.at(crad_cos, assign[high],
                  row_cos[high].astype(np.float32))
    crad_cos = np.clip(crad_cos, -1.0, 1.0)
    member_ids = []
    member_vecs = []
    for b in range(cc):
        sel = assign == b
        member_ids.append(ids[sel].astype(np.int64))
        member_vecs.append(vecs[sel].astype(np.float32))
    return ColdTier(
        centroids=cent, cmax=cmax, crad_cos=crad_cos,
        crad_sin=np.sqrt(1.0 - crad_cos * crad_cos).astype(np.float32),
        member_ids=member_ids, member_vecs=member_vecs, rows=n,
        hits=np.zeros(cc, np.int64))


def rebuild_index(table: Any, *, trigger: str = "manual",
                  probe_recall: bool = False) -> Optional[MIPSIndex]:
    """Background rebuild-and-swap for a SINGLE-DEVICE table (the
    rebuild daemon's workhorse — ops/mips_daemon.py books the trigger,
    trace span and metrics around this call).

    Off the serving path it: (1) snapshots the exact tail under a
    sequence watermark, (2) re-clusters the catalogue WITH the
    virtual-id tail folded into a dense **ext block** at its existing
    ids (the overlay's key→id map survives the swap untouched — this
    is the ``adopt_keys`` choreography applied to the index), (3)
    decides bucket tiering from the probe-hit window, then (4)
    atomically replaces the registry entry. Entries published after
    the watermark are carried into the successor's tail under the OLD
    index's lock, and a publisher that raced the swap re-routes via
    ``_superseded`` — a published key is findable at recall 1.0
    before, during and after the swap. The old index object keeps
    serving in-flight queries until their references drop."""
    old = index_for(table)
    if old is None or _maybe_sharded(table):
        return None
    t0 = time.perf_counter()
    i_pad, rank = int(table.shape[0]), int(table.shape[1])
    n_items = old.n_items
    cap_rows = old.capacity
    with old._lock:
        watermark = old._tail_seq
        tail_snap = {g: np.asarray(v, np.float32)
                     for g, v in old._tail.items()}
        next_virtual = old._next_virtual

    # -- assemble the full servable row set -------------------------------
    vf = np.asarray(table[:n_items], np.float32).copy()
    for gid, vec in tail_snap.items():
        if gid < n_items:
            # known-row override: cluster/encode the PUBLISHED solve
            # (the tail entry stays live for the exact final score)
            vf[gid] = vec
    n_ext = max(int(next_virtual) - cap_rows, 0)
    ext_np = np.zeros((n_ext, rank), np.float32)
    have = np.zeros(n_ext, bool)
    if old.ext_np is not None and old.n_ext:
        ext_np[:old.n_ext] = old.ext_np[:old.n_ext]
        have[:old.n_ext] = True
    for gid, vec in tail_snap.items():
        j = gid - cap_rows
        if 0 <= j < n_ext:
            ext_np[j] = vec
            have[j] = True
    ext_ids = cap_rows + np.nonzero(have)[0].astype(np.int64)
    ids_all = np.concatenate(
        [np.arange(n_items, dtype=np.int64), ext_ids])
    rows_all = (np.concatenate([vf, ext_np[have]])
                if len(ext_ids) else vf)

    # -- tier decision from the probe-hit window --------------------------
    cold_mask = np.zeros(len(ids_all), bool)
    tier = _tier_mode()
    enough = (tier == "on"
              or old._probe_samples >= _tier_min_samples())
    if tier != "off" and enough and n_items > 2:
        bucket_cold = old.probe_hits <= 0
        # demotion: rows whose device bucket drew no probes over the
        # window; promotion: rows whose COLD bucket drew probes come
        # back (pressure), quiet cold buckets stay demoted
        row_cold = np.zeros(len(ids_all), bool)
        real = ids_all < n_items
        a_old = np.full(len(ids_all), -1, np.int64)
        in_assign = ids_all[real] < len(old.assign)
        a_idx = ids_all[real][in_assign]
        a_old_real = np.full(int(real.sum()), -1, np.int64)
        a_old_real[in_assign] = old.assign[a_idx]
        a_old[real] = a_old_real
        valid = a_old >= 0
        row_cold[valid] = bucket_cold[a_old[valid]]
        if old.cold is not None:
            still_cold = set()
            for cb in np.nonzero(old.cold.hits <= 0)[0]:
                still_cold.update(
                    int(g) for g in old.cold.member_ids[int(cb)])
            if still_cold:
                row_cold |= np.isin(
                    ids_all, np.fromiter(still_cold, np.int64,
                                         len(still_cold)))
        # published overrides and ext rows are fresh by definition
        fresh = np.fromiter(tail_snap, np.int64, len(tail_snap))
        if len(fresh):
            row_cold &= ~np.isin(ids_all, fresh)
        max_cold = int(_tier_max_frac() * len(ids_all))
        if row_cold.sum() > max_cold:
            keep_hot = np.nonzero(row_cold)[0][max_cold:]
            row_cold[keep_hot] = False
        if row_cold.sum() >= 8:        # below that tiering is noise
            cold_mask = row_cold

    hot_ids = ids_all[~cold_mask]
    hot_vecs = np.ascontiguousarray(rows_all[~cold_mask])
    n_hot = len(hot_ids)

    # -- re-cluster the hot set (single shard) ----------------------------
    seed = old.seed + old.rebuilds
    c_local = default_centroids(max(n_hot, 1))
    cent = _spherical_kmeans(hot_vecs, c_local, seed)
    mean_bucket = -(-max(n_hot, 1) // c_local)
    cap = max(-(-int(mean_bucket * 1.25) // 8) * 8, 8)
    a_hot = _balanced_assign(hot_vecs, cent, cap)
    members_np, counts = _pack_members(a_hot, hot_ids, c_local, cap)
    norms = np.linalg.norm(hot_vecs, axis=1).astype(np.float32)
    cmax = np.zeros(c_local, np.float32)
    np.maximum.at(cmax, a_hot, norms)
    unit = hot_vecs / np.maximum(norms[:, None], 1e-9)
    row_cos = np.einsum("ik,ik->i", unit, cent[a_hot])
    crad_cos = np.ones(c_local, np.float32)
    high = norms >= _RADIUS_NORM_FRAC * cmax[a_hot]
    np.minimum.at(crad_cos, a_hot[high],
                  row_cos[high].astype(np.float32))
    crad_cos = np.clip(crad_cos, -1.0, 1.0)
    crad_sin = np.sqrt(1.0 - crad_cos * crad_cos).astype(np.float32)

    # assign is indexed by GLOBAL id (update/publish splices): size it
    # over the whole id space, -1 for pad/cold/tail-only rows
    e_pad = _next_pow2(max(n_ext, 8))
    assign = np.full(cap_rows + e_pad, -1, np.int32)
    assign[hot_ids] = a_hot

    # -- quantized views over the extended id space -----------------------
    quant = _quant_mode()
    pq_m = 0
    pq_codes_np = np.zeros((1, 1, 1), np.uint8)
    pq_books_np = np.zeros((1, _PQ_CODEBOOK, 1), np.float32)
    codes = np.zeros((1, rank), np.int8)
    scales = np.zeros(1, np.float32)
    bf16_view = _bf16(np.zeros((1, rank), np.float32))
    if quant == "pq":
        pq_m = _pq_m(rank)
        res = hot_vecs - cent[a_hot]
        pq_books_np = _pq_train_books(res, pq_m, seed)
        pq_codes_np = _pq_pack(a_hot, _pq_encode(res, pq_books_np),
                               c_local, cap)
    elif quant == "bf16":
        full = np.zeros((cap_rows + e_pad, rank), np.float32)
        full[hot_ids] = hot_vecs
        bf16_view = _bf16(full)
    else:
        c_h, s_h = _quantize_int8(hot_vecs)
        codes = np.zeros((cap_rows + e_pad, rank), np.int8)
        scales = np.zeros(cap_rows + e_pad, np.float32)
        codes[hot_ids] = c_h
        scales[hot_ids] = s_h

    cold_tier = None
    if cold_mask.any():
        cold_tier = _build_cold(
            np.ascontiguousarray(rows_all[cold_mask]),
            ids_all[cold_mask], seed)

    ext_dev = None
    ext_full = None
    if n_ext:
        ext_full = np.zeros((e_pad, rank), np.float32)
        ext_full[:n_ext] = ext_np
        ext_dev = jax.device_put(ext_full)

    new = MIPSIndex(
        codes=jax.device_put(codes),
        scales=jax.device_put(scales),
        bf16=jax.device_put(bf16_view),
        centroids=jax.device_put(cent),
        cmax=jax.device_put(cmax),
        crad_cos=jax.device_put(crad_cos),
        crad_sin=jax.device_put(crad_sin),
        members=jax.device_put(members_np),
        assign=assign, members_np=members_np, centroids_np=cent,
        counts=counts, n_items=n_items, n_shards=1, c_local=c_local,
        cap=cap, rank=rank, seed=old.seed, quant=quant,
        rebuilds=old.rebuilds + 1, delta_updates=old.delta_updates,
        pq_codes=jax.device_put(pq_codes_np),
        pq_books=jax.device_put(pq_books_np),
        pq_codes_np=pq_codes_np, pq_books_np=pq_books_np, pq_m=pq_m,
        ext=ext_dev, ext_np=ext_full, n_ext=n_ext,
        capacity_rows=cap_rows, cold=cold_tier, engine=old.engine,
        cmax_np=cmax.copy(), crad_cos_np=crad_cos.copy(),
        crad_sin_np=crad_sin.copy(),
    )

    # warm the serving compile BEFORE the swap (ext-block shapes are
    # pow2-rung stable, so consecutive rebuilds usually reuse it): the
    # first post-swap query must not eat a compile
    try:
        if n_items > 1:
            mips_score_and_top_k(vf[0], table, new,
                                 min(10, n_items - 1))
    except Exception:
        logger.exception("mips rebuild warmup failed (serving anyway)")

    # -- the atomic swap --------------------------------------------------
    with old._lock:
        new._next_virtual = old._next_virtual
        for gid, vec in old._tail.items():
            if gid < n_items or old._tail_seqs.get(gid, 0) > watermark:
                # known-row overrides stay (the exact final score);
                # virtual entries published after the watermark carry
                # over — nothing published is ever lost to a swap
                new._tail[gid] = np.asarray(vec, np.float32)
                new._tail_seq += 1
                new._tail_seqs[gid] = new._tail_seq
        new._tail_pack = None
        old._superseded = new
        register_index(table, new)
    _REBUILDS.labels(trigger=trigger).inc()
    if probe_recall:
        try:
            recall_probe(table, new, host_factors=vf)
        except Exception:
            logger.exception("mips recall probe failed at rebuild")
    logger.info(
        "mips index rebuilt (%s): %d items + %d ext, %d centroids "
        "(cap %d), %d cold rows, folded %d tail entries in %.2fs",
        trigger, n_items, n_ext, c_local, cap,
        cold_tier.rows if cold_tier else 0,
        sum(1 for g in tail_snap if g >= cap_rows),
        time.perf_counter() - t0)
    return new


# ---------------------------------------------------------------------------
# the two-stage device kernel
# ---------------------------------------------------------------------------

def _coarse_cut(coarse, cand, n_cand):
    """Top-``n_cand`` coarse survivors. ``lax.top_k``, not argsort: the
    full variadic sort measured 12× slower on CPU XLA at this width,
    and top_k has a native TPU lowering."""
    n_cand = min(n_cand, cand.shape[1])
    _, pos = jax.lax.top_k(coarse, n_cand)
    return jnp.take_along_axis(cand, pos, axis=1)


def _exact_rerank(uv, rows_g, table, exclude, offset, k, ext=None,
                  ext_base=0):
    """Exact f32 rerank of the candidate slice → ([B, kk] scores,
    [B, kk] GLOBAL ids). ``ext`` (daemon-rebuilt indexes) holds the
    folded virtual-id rows at ids ``>= ext_base`` — those never exist
    in ``table``, so the rerank gathers them from the ext block."""
    rows_l = jnp.maximum(rows_g - offset, 0)
    if ext is not None:
        in_ext = rows_g >= ext_base
        tab_v = table[jnp.where(in_ext, 0, rows_l)].astype(jnp.float32)
        ext_v = ext[jnp.clip(rows_g - ext_base, 0, ext.shape[0] - 1)]
        vecs = jnp.where(in_ext[:, :, None], ext_v, tab_v)
    else:
        vecs = table[rows_l].astype(jnp.float32)
    exact = jnp.einsum(
        "bnk,bk->bn", vecs, uv,
        preferred_element_type=jnp.float32)
    exact = jnp.where(rows_g >= 0, exact, NEG_INF)
    if exclude is not None:
        hit = (rows_g[:, :, None] == exclude[None, None, :]).any(-1)
        exact = jnp.where(hit, NEG_INF, exact)
    kk = min(k, rows_g.shape[1])
    top_s, pos2 = jax.lax.top_k(exact, kk)
    top_i = jnp.take_along_axis(rows_g, pos2, axis=1)
    return top_s, top_i


def _probe_bound(uv, centroids, cmax, crad_cos, crad_sin):
    """([B, C] upper bound, [B, C] raw centroid scores). The bound is
    cmax·|q|·cos(θ_qc − r) with r the bucket's ball radius — valid for
    every member, including spilled/off-centroid rows. The raw q·c
    scores ride along because the PQ path reuses them as the exact
    centroid part of its residual decomposition."""
    s = jnp.einsum("bk,ck->bc", uv, centroids,
                   preferred_element_type=jnp.float32)
    qn2 = jnp.sum(uv * uv, axis=1, keepdims=True)
    ortho = jnp.sqrt(jnp.maximum(qn2 - s * s, 0.0))
    return (cmax[None, :] * (s * crad_cos[None, :]
                             + ortho * crad_sin[None, :]), s)


def _pq_coarse(uv, s, probe, pq_codes, pq_books):
    """[B, P, cap] asymmetric PQ scores for the probed buckets' member
    slots: q·v ≈ q·c_b (exact, from the probe stage's raw centroid
    scores) + Σ_m LUT[m, code_m] with LUT = q_sub·codebook — one
    [B, M, 256] einsum per dispatch, then pure integer gathers."""
    B = uv.shape[0]
    m, _cb, d = pq_books.shape
    base = jnp.take_along_axis(s, probe, axis=1)          # [B, P]
    lut = jnp.einsum(
        "bmd,mjd->bmj", uv.reshape(B, m, d), pq_books,
        preferred_element_type=jnp.float32)               # [B, M, 256]
    codes_g = pq_codes[probe].astype(jnp.int32)           # [B,P,cap,M]

    def gather_res(lut_b, codes_b):   # [M, 256], [P, cap, M]
        return lut_b[jnp.arange(m)[None, None, :], codes_b]

    res = jax.vmap(gather_res)(lut, codes_g).sum(-1)      # [B, P, cap]
    return base[:, :, None] + res


def _two_stage(uv, codes, scales, bf16, pq_codes, pq_books, centroids,
               cmax, crad_cos, crad_sin, members, table, exclude,
               offset, *, k, nprobe, n_cand, quant):
    """Fused traced core over (possibly shard-local) slices: [B, K]
    queries → ([B, kk] scores, [B, kk] GLOBAL ids). ``offset`` maps the
    global ids in ``members`` onto this slice's row space. Used by the
    shard_map path, where the whole two-stage must be one program; the
    single-device wrappers run the STAGED pair below instead."""
    B = uv.shape[0]
    cs, s = _probe_bound(uv, centroids, cmax, crad_cos, crad_sin)
    nprobe = min(nprobe, centroids.shape[0])
    _, probe = jax.lax.top_k(cs, nprobe)             # [B, P]
    if quant == "pq":
        # bucket-major codes: gathered by LOCAL probe index, no row
        # offset involved (the slot layout mirrors ``members``)
        cand = members[probe]                        # [B, P, cap]
        coarse = _pq_coarse(uv, s, probe, pq_codes,
                            pq_books).reshape(B, -1)
        cand = cand.reshape(B, -1)
    else:
        cand = members[probe].reshape(B, -1)         # [B, P*cap] global
        safe = jnp.maximum(cand - offset, 0)
        if quant == "bf16":
            coarse = jnp.einsum(
                "bnk,bk->bn", bf16[safe].astype(jnp.float32), uv,
                preferred_element_type=jnp.float32)
        else:
            coarse = jnp.einsum(
                "bnk,bk->bn", codes[safe].astype(jnp.float32), uv,
                preferred_element_type=jnp.float32) * scales[safe]
    coarse = jnp.where(cand >= 0, coarse, NEG_INF)
    rows_g = _coarse_cut(coarse, cand, n_cand)
    return _exact_rerank(uv, rows_g, table, exclude, offset, k)


# -- staged single-device pair ----------------------------------------------
# XLA CPU fuses an int8→f32 convert INTO a downstream dot and emits a
# scalar loop ~8× slower than the BLAS matvec on the same data (measured:
# fused 2.0 ms vs gather+convert 0.55 ms + matvec 0.37 ms at 32k×64);
# a jit boundary after the gather+convert is the only reliable
# materialization point, so the unsharded path runs as TWO dispatches —
# still ONE device→host fetch per query, which is what tunneled-latency
# serving actually counts.

@functools.partial(jax.jit, static_argnames=("nprobe", "quant"))
def _mips_probe_jit(uv, centroids, cmax, crad_cos, crad_sin, members,
                    codes, scales, bf16, *, nprobe, quant):
    """Stage 1: centroid scan → probed buckets → candidate ids + the
    MATERIALIZED f32 view of their quantized rows (gather + convert
    only — nothing downstream may fuse into it)."""
    B = uv.shape[0]
    cs, _s = _probe_bound(uv, centroids, cmax, crad_cos, crad_sin)
    _, probe = jax.lax.top_k(cs, min(nprobe, centroids.shape[0]))
    cand = members[probe].reshape(B, -1)
    safe = jnp.maximum(cand, 0).reshape(-1)
    # g is emitted 2-D [B·n, K]: the rank stage feeds it to a plain
    # matmul without slicing (a [0]-slice of a 3-D output forces an
    # 8 MB copy before XLA's BLAS path engages)
    if quant == "bf16":
        g = bf16[safe].astype(jnp.float32)
        sg = jnp.ones((B, cand.shape[1]), jnp.float32)
    else:
        g = codes[safe].astype(jnp.float32)
        sg = scales[safe].reshape(B, -1)
    return cand, g, sg


@functools.partial(jax.jit, static_argnames=("nprobe", "quant"))
def _mips_probe_rows_jit(user_factors, rows, centroids, cmax, crad_cos,
                         crad_sin, members, codes, scales, bf16, *,
                         nprobe, quant):
    """Stage 1 with the user-row gather inside the dispatch (the
    score_user / batched shapes)."""
    uv = user_factors[rows]
    cand, g, sg = _mips_probe_jit(
        uv, centroids, cmax, crad_cos, crad_sin, members, codes,
        scales, bf16, nprobe=nprobe, quant=quant)
    return uv, cand, g, sg


@functools.partial(jax.jit, static_argnames=("k", "n_cand", "quant",
                                             "ext_base"))
def _mips_rank_jit(uv, cand, g, sg, table, ext, exclude, *, k, n_cand,
                   quant, ext_base=0):
    """Stage 2: coarse score over the materialized quantized rows
    (BLAS-shaped), top-k cut, exact f32 rerank, final top-k."""
    B, n = cand.shape
    if B == 1:
        # 2-D matvec on the materialized [n, K] — the BLAS fast path
        coarse = (g @ uv[0])[None, :]
    else:
        coarse = jnp.einsum(
            "bnk,bk->bn", g.reshape(B, n, -1), uv,
            preferred_element_type=jnp.float32)
    if quant != "bf16":
        coarse = coarse * sg
    coarse = jnp.where(cand >= 0, coarse, NEG_INF)
    rows_g = _coarse_cut(coarse, cand, n_cand)
    top_s, top_i = _exact_rerank(uv, rows_g, table, exclude, 0, k,
                                 ext=ext, ext_base=ext_base)
    return jnp.stack([top_s, top_i.astype(jnp.float32)])


# -- staged PQ pair (single-device) ------------------------------------------
# The PQ coarse stage is integer gathers + a LUT einsum — no int8→f32
# convert for XLA CPU to mis-fuse — but the staged split is kept so
# both quant families dispatch identically (two programs, one
# device→host fetch) and share the rank-stage compile ladder shape.

@functools.partial(jax.jit, static_argnames=("nprobe",))
def _mips_pq_probe_jit(uv, centroids, cmax, crad_cos, crad_sin,
                       members, pq_codes, pq_books, *, nprobe):
    """PQ stage 1: centroid scan → probed buckets → candidate ids +
    asymmetric coarse scores (base q·c + residual LUT sums)."""
    B = uv.shape[0]
    cs, s = _probe_bound(uv, centroids, cmax, crad_cos, crad_sin)
    _, probe = jax.lax.top_k(cs, min(nprobe, centroids.shape[0]))
    cand = members[probe]                             # [B, P, cap]
    coarse = _pq_coarse(uv, s, probe, pq_codes,
                        pq_books).reshape(B, -1)
    cand = cand.reshape(B, -1)
    return cand, jnp.where(cand >= 0, coarse, NEG_INF)


@functools.partial(jax.jit, static_argnames=("nprobe",))
def _mips_pq_probe_rows_jit(user_factors, rows, centroids, cmax,
                            crad_cos, crad_sin, members, pq_codes,
                            pq_books, *, nprobe):
    """PQ stage 1 with the user-row gather inside the dispatch."""
    uv = user_factors[rows]
    cand, coarse = _mips_pq_probe_jit(
        uv, centroids, cmax, crad_cos, crad_sin, members, pq_codes,
        pq_books, nprobe=nprobe)
    return uv, cand, coarse


@functools.partial(jax.jit, static_argnames=("k", "n_cand",
                                             "ext_base"))
def _mips_pq_rank_jit(uv, cand, coarse, table, ext, exclude, *, k,
                      n_cand, ext_base=0):
    """PQ stage 2: coarse top-k cut, exact f32 rerank (table + ext
    block), final top-k."""
    rows_g = _coarse_cut(coarse, cand, n_cand)
    top_s, top_i = _exact_rerank(uv, rows_g, table, exclude, 0, k,
                                 ext=ext, ext_base=ext_base)
    return jnp.stack([top_s, top_i.astype(jnp.float32)])


@functools.partial(jax.jit, static_argnames=(
    "k", "nprobe", "n_cand", "quant", "mesh", "gather_user"))
def _mips_sharded_jit(user_vector, codes, scales, bf16, pq_codes,
                      pq_books, centroids, cmax, crad_cos, crad_sin,
                      members, table, exclude, *, k, nprobe, n_cand,
                      quant, mesh, gather_user):
    """Placed tables: per-shard coarse scan + candidate gather + exact
    rerank over the rows the shard owns (everything stays shard-local),
    then the same [n, k_local] all-gather merge as the exhaustive
    ``sharded_top_k``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_predictionio_tpu.parallel.collectives import (
        all_gather,
        axis_index,
        shard_map,
    )

    axes = tuple(mesh.axis_names)
    n = int(mesh.devices.size)
    local_rows = table.shape[0] // n
    # nprobe/n_cand arrive PRE-SPLIT per shard (one quota rule,
    # ops/mips._quotas, shared with the scan accounting)
    nprobe_l, n_cand_l = nprobe, n_cand
    k_l = min(k, n_cand_l)
    if gather_user:
        uf, rows = user_vector
        uv = uf[rows]
    else:
        uv = user_vector
    uv = jax.lax.with_sharding_constraint(uv, NamedSharding(mesh, P()))
    spec = P(axes)
    args = [uv, codes, scales, bf16, pq_codes, pq_books, centroids,
            cmax, crad_cos, crad_sin, members, table]
    # pq_books is replicated (every shard scores with the full
    # codebook set); everything else row/bucket-shards on axis 0
    specs = [P(), spec, spec, spec, spec, P(), spec, spec, spec, spec,
             spec, spec]
    has_ex = exclude is not None
    if has_ex:
        args.append(exclude)
        specs.append(P())

    def shard(uv_l, codes_l, scales_l, bf_l, pqc_l, pqb_l, cent_l,
              cmax_l, ccos_l, csin_l, mem_l, tab_l, *rest):
        ex_l = rest[0] if has_ex else None
        offset = axis_index(axes) * local_rows
        top_s, top_i = _two_stage(
            uv_l, codes_l, scales_l, bf_l, pqc_l, pqb_l, cent_l,
            cmax_l, ccos_l, csin_l, mem_l, tab_l, ex_l, offset,
            k=k_l, nprobe=nprobe_l, n_cand=n_cand_l, quant=quant)
        merged_s = all_gather(top_s, axes, axis=1, tiled=True)
        merged_i = all_gather(top_i.astype(jnp.int32), axes, axis=1,
                              tiled=True)
        kk = min(k, merged_s.shape[1])
        out_s, pos = jax.lax.top_k(merged_s, kk)
        out_i = jnp.take_along_axis(merged_i, pos, axis=1)
        return jnp.stack([out_s, out_i.astype(jnp.float32)])

    return shard_map(
        shard, mesh=mesh, in_specs=tuple(specs), out_specs=P(),
        check_rep=False,
    )(*args)


def mips_compile_cache_size() -> int:
    """Compiled two-stage variants resident — summed into
    ``ops.topk.serve_compile_cache_size`` so the scheduler's
    zero-steady-state-recompile contract covers the MIPS path too."""
    return sum(
        int(fn._cache_size())
        for fn in (_mips_probe_jit, _mips_probe_rows_jit,
                   _mips_rank_jit, _mips_pq_probe_jit,
                   _mips_pq_probe_rows_jit, _mips_pq_rank_jit,
                   _mips_sharded_jit)
    )


# ---------------------------------------------------------------------------
# serving wrappers (the ops/topk auto-routers land here)
# ---------------------------------------------------------------------------

def _quotas(index: MIPSIndex, k: int) -> Tuple[int, int, int, int]:
    """THE quota rule: (per-shard nprobe, per-shard candidate count,
    total coarse slots, total rerank rows) for one query at the current
    knobs. The sharded path splits the global budgets evenly with a
    small per-shard probe floor (a tiny per-shard index must still
    probe enough buckets to cover a mixed-interest query; the floor is
    cheap precisely because such shards hold few rows). The wrappers
    pass these to the jits as statics AND book them as scan
    accounting, so the measured fraction can never drift from the
    dispatched shapes."""
    n = index.n_shards
    nprobe = _nprobe_for(index)
    n_cand = _candidates_for(index, k)
    if n > 1:
        nprobe_l = min(max(-(-nprobe // n), min(16, index.c_local)),
                       index.c_local)
        n_cand_l = max(-(-n_cand // n), 1)
    else:
        nprobe_l = min(nprobe, index.c_local)
        n_cand_l = n_cand
    return nprobe_l, n_cand_l, nprobe_l * index.cap * n, n_cand_l * n


def _book_scan(index: MIPSIndex, b: int, coarse: int,
               rerank: int) -> None:
    _SCAN_CENTROID.inc(b * index.c_total)
    _SCAN_COARSE.inc(b * coarse)
    _SCAN_RERANK.inc(b * rerank)


def scan_budget(index: MIPSIndex, k: int) -> Tuple[int, int, int]:
    """(global nprobe, coarse slots scanned, rerank rows) per query at
    the current knobs — the bench's analytic candidates-scanned
    figure, from the same quota rule the dispatch uses."""
    nprobe_l, _n_cand_l, coarse, rerank = _quotas(index, k)
    return nprobe_l * index.n_shards, coarse, rerank


def _pad_k(packed: np.ndarray, k: int) -> np.ndarray:
    """[2, ..., kk] → [2, ..., k] (NEG_INF/-1 filled) so the two-stage
    result is shape-compatible with the exhaustive contract even when
    the candidate budget is under k."""
    kk = packed.shape[-1]
    if kk >= k:
        return packed
    pad = np.zeros(packed.shape[:-1] + (k - kk,), np.float32)
    pad[0] = float(NEG_INF)
    pad[1] = -1.0
    return np.concatenate([np.asarray(packed), pad], axis=-1)


def _merge_tail(index: MIPSIndex, packed, uv_host: np.ndarray, k: int,
                exclude) -> np.ndarray:
    """Exact f32 merge of the published tail into a device [2, k] (or
    [2, B, k]) result. Tail entries OVERRIDE device rows with the same
    id (the published solve is fresher than the base row)."""
    tail = index.tail_arrays()
    packed = np.asarray(packed)
    if tail is None:
        return packed
    tids, tvecs = tail
    ex = None
    if exclude is not None:
        ex = np.asarray(exclude).astype(np.int64)
    single = packed.ndim == 2
    if single:
        packed = packed[:, None, :]
        uv_host = np.asarray(uv_host, np.float32)[None, :]
    tscores = uv_host @ tvecs.T                      # [B, T]
    out = np.empty((2, packed.shape[1], k), np.float32)
    for b in range(packed.shape[1]):
        dev_s = packed[0, b]
        dev_i = packed[1, b].astype(np.int64)
        keep = ~np.isin(dev_i, tids)
        ts, ti = tscores[b], tids
        if ex is not None:
            tkeep = ~np.isin(ti, ex)
            ts, ti = ts[tkeep], ti[tkeep]
        all_s = np.concatenate([dev_s[keep], ts])
        all_i = np.concatenate([dev_i[keep], ti])
        order = np.argsort(-all_s, kind="stable")[:k]
        ns = len(order)
        out[0, b, :ns] = all_s[order]
        out[1, b, :ns] = all_i[order].astype(np.float32)
        if ns < k:
            out[0, b, ns:] = float(NEG_INF)
            out[1, b, ns:] = -1.0
    return out[:, 0, :] if single else out


def merge_published_fallback(table: Any, packed: Any, uv_host_fn,
                             k: int,
                             exclude: Optional[Any] = None) -> Any:
    """Exhaustive-fallback parity seam (ops/topk.py): a query routed
    AROUND the two-stage path — oversized exclusion list, top-
    everything k, serving mode off — must still see overlay-published
    rows, which live only in the index's exact tail (virtual ids are
    not table rows, and a known-row override is fresher than the table
    row the exhaustive scan just scored). Cold-tiered rows need no
    help: demotion shrinks the INDEX views, never the table. No-op
    without a registered index or with an empty tail; ``uv_host_fn``
    is only called when there is something to merge."""
    index = index_for(table)
    if index is None or index.tail_size() == 0:
        return packed
    return _merge_tail(index, np.asarray(packed, np.float32),
                       np.asarray(uv_host_fn(), np.float32), k,
                       exclude)


def _maybe_sharded(table: Any) -> bool:
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    return is_distributed(table)


#: probe-hit sampling period: every Nth dispatch recomputes the probe
#: bound on the host to credit the probed buckets' hit counters (the
#: tiering daemon's demotion signal). 1/8 keeps the [B, C] numpy
#: matmul amortized to noise; when a cold tier is live the bound is
#: computed every dispatch anyway (the cold merge needs it).
_PROBE_SAMPLE_EVERY = 8


def _host_probe_bound(uv: np.ndarray, centroids: np.ndarray,
                      cmax: np.ndarray, crad_cos: np.ndarray,
                      crad_sin: np.ndarray) -> np.ndarray:
    """numpy mirror of :func:`_probe_bound` → [B, C] bound."""
    s = uv @ centroids.T
    qn2 = np.sum(uv * uv, axis=1, keepdims=True)
    ortho = np.sqrt(np.maximum(qn2 - s * s, 0.0))
    return cmax[None, :] * (s * crad_cos[None, :]
                            + ortho * crad_sin[None, :])


def _top_buckets(bound: np.ndarray, nprobe: int) -> np.ndarray:
    """[B, P] host top-nprobe bucket ids per query."""
    nprobe = min(nprobe, bound.shape[1])
    if nprobe >= bound.shape[1]:
        return np.tile(np.arange(bound.shape[1]), (len(bound), 1))
    return np.argpartition(-bound, nprobe - 1, axis=1)[:, :nprobe]


def _merge_cold(index: MIPSIndex, packed: np.ndarray,
                uv_host: np.ndarray, k: int, exclude,
                nprobe: int) -> np.ndarray:
    """Exact host-side serve of the probed COLD buckets, merged into
    the device result like the tail. Cold rows are exact f32 — recall
    for a demoted row is oracle-grade, the trade is host CPU on the
    (by construction rare) queries that probe a cold bucket."""
    cold = index.cold
    single = packed.ndim == 2
    if single:
        packed = packed[:, None, :]
        uv_host = np.asarray(uv_host, np.float32)[None, :]
    ex = (np.asarray(exclude).astype(np.int64)
          if exclude is not None else None)
    bound = _host_probe_bound(uv_host, cold.centroids, cold.cmax,
                              cold.crad_cos, cold.crad_sin)
    top = _top_buckets(bound, min(nprobe, len(cold.cmax)))
    np.add.at(cold.hits, top.ravel(), 1)
    out = np.empty((2, packed.shape[1], k), np.float32)
    for b in range(packed.shape[1]):
        ids_l: List[np.ndarray] = [packed[1, b].astype(np.int64)]
        sc_l: List[np.ndarray] = [packed[0, b].astype(np.float32)]
        for cb in top[b]:
            cids = cold.member_ids[int(cb)]
            if not len(cids):
                continue
            sc = cold.member_vecs[int(cb)] @ uv_host[b]
            if ex is not None:
                keep = ~np.isin(cids, ex)
                cids, sc = cids[keep], sc[keep]
            ids_l.append(cids)
            sc_l.append(sc.astype(np.float32))
        all_i = np.concatenate(ids_l)
        all_s = np.concatenate(sc_l)
        order = np.argsort(-all_s, kind="stable")[:k]
        ns = len(order)
        out[0, b, :ns] = all_s[order]
        out[1, b, :ns] = all_i[order].astype(np.float32)
        if ns < k:
            out[0, b, ns:] = float(NEG_INF)
            out[1, b, ns:] = -1.0
    return out[:, 0, :] if single else out


def _host_stage(index: MIPSIndex, packed, uv_host_fn, k: int, exclude,
                nprobe: int) -> np.ndarray:
    """Post-device host work shared by the serving wrappers: probe-hit
    sampling (demotion signal), the cold-tier exact merge, then the
    exact-tail merge (override semantics — tail last, so a republished
    id always serves its freshest vector). ``uv_host_fn`` defers the
    query fetch: the common no-tail/no-cold steady state pays nothing."""
    packed = _pad_k(np.asarray(packed), k)
    index._dispatches += 1
    cold = index.cold
    sample = (index._dispatches % _PROBE_SAMPLE_EVERY == 0
              and index.cmax_np is not None)
    uv_host = None
    if cold is not None or sample or index.tail_size():
        uv_host = np.asarray(uv_host_fn(), np.float32)
    if sample and uv_host is not None:
        uv2 = uv_host if uv_host.ndim == 2 else uv_host[None, :]
        bound = _host_probe_bound(uv2, index.centroids_np,
                                  index.cmax_np, index.crad_cos_np,
                                  index.crad_sin_np)
        np.add.at(index.probe_hits,
                  _top_buckets(bound, nprobe).ravel(), 1)
        index._probe_samples += 1
    if cold is not None:
        packed = _merge_cold(index, packed, uv_host, k, exclude,
                             nprobe)
    if index.tail_size():
        packed = _merge_tail(index, _pad_k(np.asarray(packed), k),
                             uv_host, k, exclude)
    return _pad_k(np.asarray(packed), k)


def mips_score_and_top_k(
    user_vector: Any,           # [K]
    table: Any,                 # [I_pad, K] (maybe sharded)
    index: MIPSIndex,
    k: int,
    exclude: Optional[Any] = None,
) -> np.ndarray:
    """Two-stage twin of ``ops.topk.score_and_top_k`` → packed [2, k]."""
    from incubator_predictionio_tpu.obs import profile as _profile

    nprobe_l, n_cand_l, coarse, rerank = _quotas(index, k)
    _pt0 = _profile.t0()
    uv = jnp.asarray(user_vector, jnp.float32).reshape(1, -1)
    if _maybe_sharded(table):
        packed = _mips_sharded_jit(
            uv, index.codes, index.scales, index.bf16, index.pq_codes,
            index.pq_books, index.centroids, index.cmax,
            index.crad_cos, index.crad_sin, index.members, table,
            exclude, k=k, nprobe=nprobe_l, n_cand=n_cand_l,
            quant=index.quant, mesh=table.sharding.mesh,
            gather_user=False)[:, 0, :]
    elif index.quant == "pq":
        cand, coarse_s = _mips_pq_probe_jit(
            uv, index.centroids, index.cmax, index.crad_cos,
            index.crad_sin, index.members, index.pq_codes,
            index.pq_books, nprobe=nprobe_l)
        packed = _mips_pq_rank_jit(
            uv, cand, coarse_s, table, index.ext, exclude, k=k,
            n_cand=n_cand_l, ext_base=index.capacity)[:, 0, :]
    else:
        q = index.quant
        cand, g, sg = _mips_probe_jit(
            uv, index.centroids, index.cmax, index.crad_cos,
            index.crad_sin, index.members, index.codes, index.scales,
            index.bf16, nprobe=nprobe_l, quant=q)
        packed = _mips_rank_jit(
            uv, cand, g, sg, table, index.ext, exclude, k=k,
            n_cand=n_cand_l, quant=q,
            ext_base=index.capacity)[:, 0, :]
    _profile.record(_pt0, "serve", "serve_topk_mips",
                    2.0 * (index.c_total + coarse + rerank)
                    * index.rank, packed)
    _book_scan(index, 1, coarse, rerank)
    return _host_stage(
        index, packed,
        lambda: np.asarray(user_vector, np.float32), k, exclude,
        nprobe_l * index.n_shards)


def mips_score_user_and_top_k(
    user_factors: Any,
    table: Any,
    index: MIPSIndex,
    user_idx: int,
    k: int,
    exclude: Optional[Any] = None,
) -> np.ndarray:
    """Two-stage twin of ``ops.topk.score_user_and_top_k`` (user-row
    gather stays inside the single dispatch) → packed [2, k]."""
    from incubator_predictionio_tpu.obs import profile as _profile

    nprobe_l, n_cand_l, coarse, rerank = _quotas(index, k)
    _pt0 = _profile.t0()
    rows = jnp.asarray([int(user_idx)], jnp.int32)
    if _maybe_sharded(table):
        packed = _mips_sharded_jit(
            (user_factors, rows), index.codes, index.scales, index.bf16,
            index.pq_codes, index.pq_books, index.centroids, index.cmax,
            index.crad_cos, index.crad_sin, index.members, table,
            exclude, k=k, nprobe=nprobe_l, n_cand=n_cand_l,
            quant=index.quant, mesh=table.sharding.mesh,
            gather_user=True)[:, 0, :]
    elif index.quant == "pq":
        uv, cand, coarse_s = _mips_pq_probe_rows_jit(
            user_factors, rows, index.centroids, index.cmax,
            index.crad_cos, index.crad_sin, index.members,
            index.pq_codes, index.pq_books, nprobe=nprobe_l)
        packed = _mips_pq_rank_jit(
            uv, cand, coarse_s, table, index.ext, exclude, k=k,
            n_cand=n_cand_l, ext_base=index.capacity)[:, 0, :]
    else:
        q = index.quant
        uv, cand, g, sg = _mips_probe_rows_jit(
            user_factors, rows, index.centroids, index.cmax,
            index.crad_cos, index.crad_sin, index.members, index.codes,
            index.scales, index.bf16, nprobe=nprobe_l, quant=q)
        packed = _mips_rank_jit(
            uv, cand, g, sg, table, index.ext, exclude, k=k,
            n_cand=n_cand_l, quant=q,
            ext_base=index.capacity)[:, 0, :]
    _profile.record(_pt0, "serve", "serve_topk_mips",
                    2.0 * (index.c_total + coarse + rerank)
                    * index.rank, packed)
    _book_scan(index, 1, coarse, rerank)
    return _host_stage(
        index, packed,
        lambda: np.asarray(user_factors[user_idx], np.float32), k,
        exclude, nprobe_l * index.n_shards)


#: batched two-stage dispatch width cap: the [B, nprobe·cap, K]
#: candidate gather is the peak transient; 128 rows keeps it ~100 MB at
#: the default budgets. Larger scheduler batches split into ladder-
#: stable 128-row chunks (one dispatch each — still pow2 shapes).
MIPS_BATCH_CHUNK = 128


def mips_batch_score_top_k(
    user_factors: Any,
    table: Any,
    index: MIPSIndex,
    rows: Any,                  # [B] int array (already pow2-padded)
    k: int,
) -> np.ndarray:
    """Two-stage twin of ``ops.topk.batch_score_top_k`` → [2, B, k]."""
    from incubator_predictionio_tpu.obs import profile as _profile

    nprobe_l, n_cand_l, coarse, rerank = _quotas(index, k)
    rows_np = np.asarray(rows, np.int32).ravel()
    B = len(rows_np)
    _pt0 = _profile.t0()
    chunks = []
    for s in range(0, B, MIPS_BATCH_CHUNK):
        rj = jnp.asarray(rows_np[s:s + MIPS_BATCH_CHUNK])
        if _maybe_sharded(table):
            part = _mips_sharded_jit(
                (user_factors, rj), index.codes, index.scales,
                index.bf16, index.pq_codes, index.pq_books,
                index.centroids, index.cmax, index.crad_cos,
                index.crad_sin, index.members, table, None, k=k,
                nprobe=nprobe_l, n_cand=n_cand_l, quant=index.quant,
                mesh=table.sharding.mesh, gather_user=True)
        elif index.quant == "pq":
            uv, cand, coarse_s = _mips_pq_probe_rows_jit(
                user_factors, rj, index.centroids, index.cmax,
                index.crad_cos, index.crad_sin, index.members,
                index.pq_codes, index.pq_books, nprobe=nprobe_l)
            part = _mips_pq_rank_jit(
                uv, cand, coarse_s, table, index.ext, None, k=k,
                n_cand=n_cand_l, ext_base=index.capacity)
        else:
            q = index.quant
            uv, cand, g, sg = _mips_probe_rows_jit(
                user_factors, rj, index.centroids, index.cmax,
                index.crad_cos, index.crad_sin, index.members,
                index.codes, index.scales, index.bf16,
                nprobe=nprobe_l, quant=q)
            part = _mips_rank_jit(
                uv, cand, g, sg, table, index.ext, None, k=k,
                n_cand=n_cand_l, quant=q, ext_base=index.capacity)
        chunks.append(_pad_k(np.asarray(part), k))
    packed = (chunks[0] if len(chunks) == 1
              else np.concatenate(chunks, axis=1))
    _profile.record(_pt0, "serve", "serve_topk_mips_batch",
                    2.0 * B * (index.c_total + coarse + rerank)
                    * index.rank, packed)
    _book_scan(index, B, coarse, rerank)
    return _host_stage(
        index, packed,
        lambda: np.asarray(user_factors[jnp.asarray(rows_np)],
                           np.float32), k, None,
        nprobe_l * index.n_shards)


# ---------------------------------------------------------------------------
# the planted recall probe (the pio_serve_mips_recall gauge's source)
# ---------------------------------------------------------------------------

def recall_probe(
    table: Any,
    index: Optional[MIPSIndex] = None,
    *,
    host_factors: Optional[np.ndarray] = None,
    k: int = 20,
    n_queries: int = 8,
    seed: int = 0,
) -> Optional[float]:
    """Measure recall@k of the two-stage path against the exhaustive
    host oracle on mixture queries sampled from the catalogue itself,
    and publish it as ``pio_serve_mips_recall``. Cheap enough to run at
    every build/publish (it also warms the serving compile)."""
    from incubator_predictionio_tpu.utils.planted import (
        exhaustive_top_k,
        planted_queries,
        recall_against_oracle,
    )

    index = index if index is not None else index_for(table)
    if index is None:
        return None
    k = min(k, max(index.n_items - 1, 1))
    vf = (np.asarray(host_factors[:index.n_items], np.float32)
          if host_factors is not None
          else np.asarray(table[:index.n_items], np.float32))
    queries = planted_queries(vf, n_queries, seed=seed + 1)
    oracle = exhaustive_top_k(vf, queries, k)
    got = np.stack([
        mips_score_and_top_k(q, table, index, k)[1].astype(np.int64)
        for q in queries
    ])
    recall, _worst = recall_against_oracle(got, oracle, k)
    _RECALL.set(recall)
    return recall
