"""Host-side sparse → static-shape padded structures.

XLA wants static shapes; ratings matrices are ragged. The bridge is
degree-bucketed padded neighbor lists: rows (users or items) are grouped into
buckets by degree ceiling (powers of two), each bucket padded to its ceiling.
This bounds padding waste at <2× while keeping the number of distinct
compiled shapes at O(log max_degree) — the ALX paper's sharded-batch layout
reduced to its single-host form (PAPERS.md: ALX §4).

Construction is host-side numpy (it runs once per training read, off the
device hot path).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class PaddedRows:
    """One degree bucket of padded neighbor lists.

    ``row_ids[i]`` is the original row index of padded row ``i``;
    ``cols[i, :]`` / ``vals[i, :]`` are its neighbor column indices and
    values, valid where ``mask[i, :] > 0``. Padding columns point at index 0
    with mask 0 so gathers stay in-bounds.
    """

    row_ids: np.ndarray  # [B] int32
    cols: np.ndarray     # [B, D] int32
    vals: np.ndarray     # [B, D] float32
    mask: np.ndarray     # [B, D] float32

    @property
    def width(self) -> int:
        return int(self.cols.shape[1])

    def pad_rows_to(self, multiple: int) -> "PaddedRows":
        """Pad the batch dimension to a multiple (device-count divisibility).

        Padding rows carry ``row_id = -1`` with zero mask; the ALS scatter
        remaps negatives out of bounds and drops them (ops/als.py
        ``_scatter_rows``)."""
        b = self.row_ids.shape[0]
        target = ((b + multiple - 1) // multiple) * multiple
        if target == b:
            return self
        pad = target - b
        return PaddedRows(
            row_ids=np.concatenate([self.row_ids, np.full(pad, -1, np.int32)]),
            cols=np.concatenate(
                [self.cols, np.zeros((pad, self.width), np.int32)]
            ),
            vals=np.concatenate(
                [self.vals, np.zeros((pad, self.width), np.float32)]
            ),
            mask=np.concatenate(
                [self.mask, np.zeros((pad, self.width), np.float32)]
            ),
        )


#: triplet count above which the C++ builder is worth its call overhead
NATIVE_MIN_NNZ = 100_000


@dataclasses.dataclass
class HeavySegments:
    """Split-row segments extracted from :class:`PaddedRows` buckets.

    Rows whose degree exceeds ``max_width`` are split across several padded
    rows; the solver cannot treat those independently (one scatter-set per
    padded row would keep only one segment's solution). This structure
    groups every split row's segments for the partial-Gram combining solve
    in ops/als.py: per-segment Grams/rhs are computed exactly like a normal
    bucket, then segment-summed by ``seg_ids`` before the single solve per
    heavy row — the ALX sharded-batch reduction in single-host form
    (PAPERS.md: ALX §4).
    """

    seg_ids: np.ndarray  # [S] int32 → index into row_ids (compact)
    row_ids: np.ndarray  # [H] int32 original row indices
    cols: np.ndarray     # [S, W] int32
    vals: np.ndarray     # [S, W] float32
    mask: np.ndarray     # [S, W] float32


def split_heavy(
    buckets: Sequence[PaddedRows],
    row_multiple: int = 8,
) -> Tuple[List[PaddedRows], "HeavySegments | None"]:
    """Separate split rows (duplicated row ids) from the light buckets.

    Returns rebuilt light buckets (split rows removed, re-padded to
    ``row_multiple``) and a :class:`HeavySegments` holding every split
    row's segments, or None when no row was split.
    """
    all_ids = np.concatenate(
        [np.asarray(b.row_ids) for b in buckets]
    ) if buckets else np.empty(0, np.int32)
    live = all_ids[all_ids >= 0]
    uniq, counts = np.unique(live, return_counts=True)
    heavy_ids = set(int(i) for i in uniq[counts > 1])
    if not heavy_ids:
        return list(buckets), None

    light: List[PaddedRows] = []
    seg_rows: List[Tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
    for b in buckets:
        ids = np.asarray(b.row_ids)
        is_heavy = np.isin(ids, list(heavy_ids)) & (ids >= 0)
        for i in np.nonzero(is_heavy)[0]:
            seg_rows.append((int(ids[i]), b.cols[i], b.vals[i], b.mask[i]))
        keep = ~is_heavy & (ids >= 0)
        if keep.any():
            light.append(
                PaddedRows(
                    row_ids=ids[keep], cols=b.cols[keep],
                    vals=b.vals[keep], mask=b.mask[keep],
                ).pad_rows_to(row_multiple)
            )

    width = max(seg[1].shape[0] for seg in seg_rows)
    s = len(seg_rows)
    cols = np.zeros((s, width), np.int32)
    vals = np.zeros((s, width), np.float32)
    mask = np.zeros((s, width), np.float32)
    row_ids = np.asarray(sorted(heavy_ids), np.int32)
    index = {int(r): i for i, r in enumerate(row_ids)}
    seg_ids = np.empty(s, np.int32)
    for i, (rid, c, v, m) in enumerate(seg_rows):
        w = c.shape[0]
        cols[i, :w], vals[i, :w], mask[i, :w] = c, v, m
        seg_ids[i] = index[rid]
    return light, HeavySegments(
        seg_ids=seg_ids, row_ids=row_ids, cols=cols, vals=vals, mask=mask)


def build_padded_rows(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    min_width: int = 8,
    max_width: int = 4096,
    row_multiple: int = 8,
    impl: str = "auto",
    degrees: "np.ndarray | None" = None,
) -> List[PaddedRows]:
    """COO triplets → degree-bucketed :class:`PaddedRows`.

    Rows with degree > ``max_width`` are *split* across multiple padded rows
    of width ``max_width``, so no data is dropped for power users/items.
    NOTE: the current ALS solver writes one solution per padded row
    (scatter-set) and therefore cannot combine split rows — it validates and
    raises on them (ops/als.py ``assert_no_split``). The split layout exists
    for the future partial-Gram combining solver (the ALX multi-chip path);
    until then keep ``max_width`` above the data's max degree.

    ``impl``: "auto" uses the native C++ builder (native/src/csr_builder.cc)
    for large inputs, "native"/"numpy" force a path. Both produce identical
    buckets.

    ``degrees``: optional precomputed per-row nnz histogram
    (int64[n_rows], sum == nnz) replacing the native plan pass — the
    pipelined ingest path accumulates it per scan shard while the scan is
    still running (see :class:`StreamingPrep`). A wrong histogram is
    detected natively and falls back to the exact plan.
    """
    if impl not in ("auto", "native", "numpy"):
        raise ValueError(f"unknown impl {impl!r}")
    if impl == "native" or (impl == "auto" and len(rows) >= NATIVE_MIN_NNZ):
        from incubator_predictionio_tpu.native.csr import build_buckets_native
        buckets = build_buckets_native(
            np.asarray(rows), np.asarray(cols), np.asarray(vals), n_rows,
            min_width, max_width, degrees=degrees)
        if buckets is not None:
            return [
                PaddedRows(row_ids=r, cols=c, vals=v, mask=m)
                .pad_rows_to(row_multiple)
                for (_w, r, c, v, m) in buckets
            ]
        if impl == "native":
            raise RuntimeError("native csr builder unavailable")
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int32)
    vals = np.asarray(vals, np.float32)
    order = np.argsort(rows, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]

    row_ids_present, starts, counts = np.unique(
        rows, return_index=True, return_counts=True
    )

    # assemble (row_id, start, length) segments, splitting heavy rows
    segments: List[Tuple[int, int, int]] = []
    for rid, start, count in zip(row_ids_present, starts, counts):
        off = 0
        while count - off > 0:
            seg = min(count - off, max_width)
            segments.append((int(rid), int(start + off), int(seg)))
            off += seg

    # bucket segments by power-of-two ceiling
    buckets: dict[int, List[Tuple[int, int, int]]] = {}
    for rid, start, seg in segments:
        width = min_width
        while width < seg:
            width *= 2
        buckets.setdefault(width, []).append((rid, start, seg))

    out: List[PaddedRows] = []
    for width in sorted(buckets):
        segs = buckets[width]
        b = len(segs)
        r_ids = np.empty(b, np.int32)
        c = np.zeros((b, width), np.int32)
        v = np.zeros((b, width), np.float32)
        m = np.zeros((b, width), np.float32)
        for i, (rid, start, seg) in enumerate(segs):
            r_ids[i] = rid
            c[i, :seg] = cols[start:start + seg]
            v[i, :seg] = vals[start:start + seg]
            m[i, :seg] = 1.0
        out.append(
            PaddedRows(row_ids=r_ids, cols=c, vals=v, mask=m).pad_rows_to(
                row_multiple
            )
        )
    return out


def build_both_sides(
    users: np.ndarray,
    items: np.ndarray,
    vals: np.ndarray,
    n_users: int,
    n_items: int,
    max_width: int = 4096,
    row_multiple: int = 8,
    split_row_multiple: int = 8,
    user_degrees: "np.ndarray | None" = None,
    item_degrees: "np.ndarray | None" = None,
    on_side=None,
):
    """Both training orientations (user-major and item-major) built
    concurrently → ((user_light, user_heavy), (item_light, item_heavy)).

    The two sides are independent and the native builder's ctypes calls
    release the GIL, so a two-thread pool halves the prep wall on hosts
    with ≥2 usable cores (pinned single-core containers degrade to the
    sequential cost — thread spawn is noise at this scale).

    ``user_degrees``/``item_degrees``: optional precomputed per-row
    histograms (see :func:`build_padded_rows`). ``on_side(side, light,
    heavy)`` — side in {"user", "item"} — fires from the worker thread
    the moment that side finishes, so a consumer can start the H2D
    transfer of one side's buckets while the other side is still
    padding (bench.py's pipelined prep→device path)."""
    from concurrent.futures import ThreadPoolExecutor

    def side(name, rows, cols, n_rows, degrees):
        out = split_heavy(
            build_padded_rows(rows, cols, vals, n_rows, max_width=max_width,
                              row_multiple=row_multiple, degrees=degrees),
            row_multiple=split_row_multiple)
        if on_side is not None:
            on_side(name, out[0], out[1])
        return out

    with ThreadPoolExecutor(max_workers=2) as pool:
        fu = pool.submit(side, "user", users, items, n_users, user_degrees)
        fi = pool.submit(side, "item", items, users, n_items, item_degrees)
        return fu.result(), fi.result()


class StreamingPrep:
    """Scan→prep pipeline sink: consume scan shards as they land.

    The sharded event-log scan (data/storage/cpplog.py ``shard_sink``)
    hands over each completed shard — indices already remapped into the
    global id tables — while later shards are still scanning with the GIL
    released. This sink does the prep work that is per-shard computable
    up front: the per-side degree histograms that replace the native csr
    *plan* pass (:func:`build_padded_rows` ``degrees``). ``overlap_s``
    records how much prep wall was absorbed into the scan.

    ``finish(inter)`` then runs :func:`build_both_sides` on the final
    arrays. Histograms are only used when the scan did NOT have to
    reorder rows (``scan_reordered`` in the scan stats): a reorder
    re-interns ids, so the accumulated histograms index a permuted table
    and are discarded (degrees are recomputed natively — correctness
    never depends on the pipeline)."""

    def __init__(self) -> None:
        self.user_degrees = np.zeros(0, np.int64)
        self.item_degrees = np.zeros(0, np.int64)
        self.overlap_s = 0.0
        self.shards = 0

    def _accumulate(self, hist: np.ndarray, idx: np.ndarray) -> np.ndarray:
        add = np.bincount(idx, minlength=len(hist)).astype(np.int64)
        if len(add) > len(hist):
            add[:len(hist)] += hist
            return add
        hist += add
        return hist

    def add_shard(self, k: int, uidx, iidx, vals, times=None) -> None:
        import time

        t0 = time.perf_counter()
        self.user_degrees = self._accumulate(self.user_degrees, uidx)
        self.item_degrees = self._accumulate(self.item_degrees, iidx)
        self.shards += 1
        self.overlap_s += time.perf_counter() - t0

    def finish(
        self,
        inter,
        max_width: int = 4096,
        row_multiple: int = 8,
        split_row_multiple: int = 8,
        reordered: bool = False,
        on_side=None,
    ):
        """→ same ((user_light, user_heavy), (item_light, item_heavy))
        tuple as :func:`build_both_sides`, fed the pre-accumulated degree
        histograms when they are still valid for ``inter``."""
        n_users, n_items = len(inter.user_ids), len(inter.item_ids)
        ud = id_ = None
        if not reordered and self.shards:
            mu = min(n_users, len(self.user_degrees))
            ud = np.zeros(n_users, np.int64)
            ud[:mu] = self.user_degrees[:mu]
            mi = min(n_items, len(self.item_degrees))
            id_ = np.zeros(n_items, np.int64)
            id_[:mi] = self.item_degrees[:mi]
        return build_both_sides(
            inter.user_idx, inter.item_idx, inter.values, n_users, n_items,
            max_width=max_width, row_multiple=row_multiple,
            split_row_multiple=split_row_multiple,
            user_degrees=ud, item_degrees=id_, on_side=on_side)
