"""TPU compute kernels — the replacement for Spark MLlib.

Where the reference's engine templates call MLlib (ALS at
examples/scala-parallel-recommendation/custom-query/src/main/scala/
ALSAlgorithm.scala:25-31, NaiveBayes/RandomForest in
examples/scala-parallel-classification/), this package provides JAX/XLA
implementations designed for the MXU: batched normal-equation solves,
one-big-matmul scoring, device top-k, segment-sum sufficient statistics.
"""

from incubator_predictionio_tpu.ops.sparse import PaddedRows, build_padded_rows
from incubator_predictionio_tpu.ops.als import (
    ALSState,
    als_init,
    als_sweep,
    als_train,
    continue_state,
    rmse,
)
from incubator_predictionio_tpu.ops.retrain import als_retrain
from incubator_predictionio_tpu.ops.topk import top_k_with_exclusions

__all__ = [
    "PaddedRows", "build_padded_rows", "ALSState", "als_init", "als_sweep",
    "als_train", "als_retrain", "continue_state", "rmse",
    "top_k_with_exclusions",
]
