"""Device-side top-K scoring with exclusions.

Serving a recommendation query in the reference is a driver-side loop over
an in-memory factor map (examples/.../ALSModel.scala recommendProducts). On
TPU the whole catalog is scored in one [1, K] × [K, I] matmul and ranked
with ``lax.top_k`` without leaving the device; seen/blocked items are masked
to -inf before ranking (business-rule filtering at serve time, parity with
the ecommerce template's filtering serve step).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.obs import profile as _profile
from incubator_predictionio_tpu.ops import mips as _mips

NEG_INF = jnp.float32(-3.4e38)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_with_exclusions(
    scores: jax.Array,              # [I] f32
    k: int,
    exclude: Optional[jax.Array] = None,   # [E] int32 item ids, -1 = no-op
    allowed_mask: Optional[jax.Array] = None,  # [I] bool — serve-time filter
) -> Tuple[jax.Array, jax.Array]:
    """Returns (top_scores[k], top_indices[k])."""
    if allowed_mask is not None:
        scores = jnp.where(allowed_mask, scores, NEG_INF)
    if exclude is not None:
        # negative ids would wrap numpy-style; remap to n so "drop" drops them
        safe = jnp.where(exclude < 0, scores.shape[-1], exclude)
        scores = scores.at[safe].set(NEG_INF, mode="drop")
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _score_and_top_k_xla(
    user_vector: jax.Array,
    item_factors: jax.Array,
    k: int,
    exclude: Optional[jax.Array] = None,
    allowed_mask: Optional[jax.Array] = None,
) -> jax.Array:
    scores = item_factors @ user_vector
    top_s, top_i = top_k_with_exclusions(scores, k, exclude, allowed_mask)
    return jnp.stack([top_s, top_i.astype(jnp.float32)])


#: catalogs below this use the fused XLA matvec+top_k (lower fixed cost);
#: above it the Pallas blocked kernel's HBM-write savings win (measured
#: crossover on v5e: XLA ahead at 131k items, Pallas ahead at 1M)
PALLAS_MIN_ITEMS = 500_000


# ---------------------------------------------------------------------------
# Sharded serving: per-shard partial top-k + all-gather merge.
#
# With the item table row-sharded over the mesh (FactorPlacement), each
# device scores ONLY its slice and ranks a local top-k; one [n, k]
# all-gather then merges — the collective moves k rows per shard instead
# of the full score vector, and the full [I] score vector never exists
# anywhere. Serving routes here automatically when the table is actually
# distributed (parallel/placement.py is_distributed).
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit, static_argnames=("k", "valid_items", "mesh", "gather_user"))
def _sharded_topk_jit(
    user_vector,                # [K] or (user_factors, user_idx)
    item_factors: jax.Array,    # [I_pad, K] row-sharded over mesh
    exclude,                    # [E] int32 global ids or None
    allowed_mask,               # [I_pad] bool or None
    *,
    k: int,
    valid_items: int,
    mesh,
    gather_user: bool,
):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_predictionio_tpu.parallel.collectives import (
        all_gather,
        axis_index,
        shard_map,
    )

    axes = tuple(mesh.axis_names)
    n = int(mesh.devices.size)
    i_pad = item_factors.shape[0]
    local_rows = i_pad // n
    k_local = min(k, local_rows)
    if gather_user:
        uf, user_idx = user_vector
        # one GSPMD gather from the sharded user table — the owning
        # shard serves the row; no host crossing
        uv = uf[user_idx]
    else:
        uv = user_vector
    uv = jax.lax.with_sharding_constraint(
        uv, NamedSharding(mesh, P()))

    spec = P(axes)
    args = [uv, item_factors]
    specs = [P(), spec]
    has_ex = exclude is not None
    has_mask = allowed_mask is not None
    if has_ex:
        args.append(exclude)
        specs.append(P())
    if has_mask:
        args.append(allowed_mask)
        specs.append(spec)

    def shard(uv_l, items_l, *rest):
        rest = list(rest)
        ex_l = rest.pop(0) if has_ex else None
        mask_l = rest.pop(0) if has_mask else None
        offset = axis_index(axes) * local_rows
        scores = items_l @ uv_l                      # [local_rows]
        rows_g = offset + jnp.arange(local_rows)
        scores = jnp.where(rows_g < valid_items, scores, NEG_INF)
        if mask_l is not None:
            scores = jnp.where(mask_l, scores, NEG_INF)
        if ex_l is not None:
            loc = ex_l - offset
            safe = jnp.where(
                (loc >= 0) & (loc < local_rows), loc, local_rows)
            scores = scores.at[safe].set(NEG_INF, mode="drop")
        s_l, i_l = jax.lax.top_k(scores, k_local)    # partial top-k
        merged_s = all_gather(s_l, axes, axis=0, tiled=True)
        merged_i = all_gather(
            (i_l + offset).astype(jnp.int32), axes, axis=0, tiled=True)
        top_s, pos = jax.lax.top_k(merged_s, k)      # merge n·k → k
        top_i = merged_i[pos]
        return jnp.stack([top_s, top_i.astype(jnp.float32)])

    return shard_map(
        shard, mesh=mesh, in_specs=tuple(specs),
        out_specs=P(), check_rep=False,
    )(*args)


def _fold_valid_mask(
    allowed_mask: Optional[jax.Array],
    item_factors: jax.Array,
    valid_items: Optional[int],
) -> Optional[jax.Array]:
    """Fold a ``valid_items`` bound into the allowed mask for the
    single-device paths (the sharded path masks by row offset instead,
    without materializing an [I] array)."""
    if valid_items is None or valid_items >= item_factors.shape[0]:
        return allowed_mask
    vm = jnp.arange(item_factors.shape[0]) < valid_items
    if allowed_mask is None:
        return vm
    return jnp.asarray(allowed_mask, bool) & vm


def sharded_top_k(
    user_vector,                 # [K] vector OR (user_factors, user_idx)
    item_factors: jax.Array,     # row-sharded [I_pad, K]
    k: int,
    exclude: Optional[jax.Array] = None,
    allowed_mask: Optional[jax.Array] = None,
    valid_items: Optional[int] = None,
) -> jax.Array:
    """Top-k over a mesh-sharded item table → packed [2, k] (replicated).

    ``valid_items`` masks the placement's padding rows (zero factors
    would otherwise outrank negative real scores); default = the full
    padded table. ``allowed_mask`` shorter than the padded table is
    padded False (padding is never servable)."""
    _pt0 = _profile.t0()  # None on the PIO_PROFILE=0 default hot path
    mesh = item_factors.sharding.mesh
    i_pad = int(item_factors.shape[0])
    valid = int(valid_items) if valid_items is not None else i_pad
    gather_user = isinstance(user_vector, tuple)
    if allowed_mask is not None and allowed_mask.shape[0] < i_pad:
        allowed_mask = jnp.pad(
            jnp.asarray(allowed_mask, bool),
            (0, i_pad - allowed_mask.shape[0]))
    kk = min(int(k), i_pad)
    out = _sharded_topk_jit(
        user_vector, item_factors, exclude, allowed_mask,
        k=kk, valid_items=valid, mesh=mesh, gather_user=gather_user)
    _profile.record(_pt0, "serve", "serve_topk_sharded",
                    2.0 * i_pad * item_factors.shape[1], out)
    return out


@functools.partial(jax.jit, static_argnames=("k",))
def _score_user_top_k_xla(
    user_factors: jax.Array,        # [U, K]
    item_factors: jax.Array,        # [I, K]
    user_idx,                       # scalar int
    k: int,
    exclude: Optional[jax.Array] = None,
    allowed_mask: Optional[jax.Array] = None,
) -> jax.Array:
    scores = item_factors @ user_factors[user_idx]
    top_s, top_i = top_k_with_exclusions(scores, k, exclude, allowed_mask)
    return jnp.stack([top_s, top_i.astype(jnp.float32)])


def score_user_and_top_k(
    user_factors: jax.Array,        # [U, K] (device-resident)
    item_factors: jax.Array,        # [I, K] (device-resident)
    user_idx: int,
    k: int,
    exclude: Optional[jax.Array] = None,
    allowed_mask: Optional[jax.Array] = None,
    valid_items: Optional[int] = None,
) -> jax.Array:
    """Serving fast path: user-row gather + full-catalog scoring + top-k in
    ONE device call, packed [2, k].

    On a tunneled/remote TPU every separate op is a host round trip;
    indexing ``user_factors[user_idx]`` outside the jit would double the
    per-query latency. Callers fetch the packed result with one
    ``np.asarray``. ``valid_items`` masks trailing padding rows — a
    PLACED table's pow2 capacity tail has zero factors, and score 0
    would outrank genuinely negative real items — so any caller serving
    a padded table directly must pass the true item count."""
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    # auto-route: a registered MIPS index serves the query two-stage
    # (coarse bucket scan + exact rerank, ops/mips.py) unless the mode,
    # a filter mask, or the catalogue size says exhaustive; exhaustive
    # stays the fallback AND the recall oracle (valid_items is moot on
    # the MIPS path — buckets only ever hold true rows)
    mips_index = _mips.route(item_factors, k=k,
                             allowed_mask=allowed_mask, exclude=exclude)
    if mips_index is not None:
        return _mips.mips_score_user_and_top_k(
            user_factors, item_factors, mips_index, user_idx, k,
            exclude=exclude)
    _mips.book_exhaustive(int(item_factors.shape[0]))
    # fallback parity with the published tail — see score_and_top_k
    masked = allowed_mask is not None

    def _fold(out):
        if masked:
            return out
        return _mips.merge_published_fallback(
            item_factors, out,
            lambda: np.asarray(user_factors[user_idx], np.float32), k,
            exclude)

    if is_distributed(item_factors):
        return _fold(sharded_top_k((user_factors, user_idx),
                                   item_factors, k, exclude=exclude,
                                   allowed_mask=allowed_mask,
                                   valid_items=valid_items))
    allowed_mask = _fold_valid_mask(allowed_mask, item_factors,
                                    valid_items)
    _pt0 = _profile.t0()
    if item_factors.shape[0] >= PALLAS_MIN_ITEMS and k <= 128:
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            score_and_top_k_pallas, topk_kernel_available)
        if topk_kernel_available():
            # huge catalogs: compute dominates, the extra gather dispatch
            # is noise next to the blocked kernel's win
            out = score_and_top_k_pallas(
                user_factors[user_idx], item_factors, k,
                exclude=exclude, allowed_mask=allowed_mask,
                block_items=8192,
            )
            _profile.record(
                _pt0, "serve", "serve_topk",
                2.0 * item_factors.shape[0] * item_factors.shape[1], out)
            return _fold(out)
    out = _score_user_top_k_xla(user_factors, item_factors, user_idx, k,
                                exclude, allowed_mask)
    _profile.record(_pt0, "serve", "serve_topk",
                    2.0 * item_factors.shape[0] * item_factors.shape[1],
                    out)
    return _fold(out)


@functools.partial(jax.jit, static_argnames=("k", "valid_items"))
def _batch_score_top_k_xla(
    user_factors: jax.Array,        # [U, K]
    item_factors: jax.Array,        # [I, K]
    rows: jax.Array,                # [B] int32 user indices
    k: int,
    valid_items: Optional[int] = None,
) -> jax.Array:
    scores = user_factors[rows] @ item_factors.T          # [B, I] — MXU
    if valid_items is not None and valid_items < item_factors.shape[0]:
        # placed tables carry zero-factor padding rows; mask them out
        # (score 0 would outrank genuinely negative real items). Under
        # sharded operands GSPMD partitions the matmul + mask + top_k.
        cols = jnp.arange(item_factors.shape[0])
        scores = jnp.where(cols[None, :] < valid_items, scores, NEG_INF)
    top_s, top_i = jax.lax.top_k(scores, k)
    return jnp.stack([top_s, top_i.astype(jnp.float32)])  # [2, B, k]


def next_pow2(n: int) -> int:
    """Smallest power of two ≥ n (≥1) — THE padding policy of the batched
    serving dispatch. Warmup hooks compile per-shape against this exact
    function, so any change here automatically changes what they warm."""
    return 1 << max(int(n) - 1, 0).bit_length()


def pad_exclude(ids) -> Optional[jax.Array]:
    """Exclusion ids → pow2-padded int32 device array (-1 = no-op
    slots), or None for an empty list — THE serve-time exclusion
    shape. One copy of the padding policy: it bounds the jitted serve
    variants to O(log max-seen) compiles, so every call site must pad
    by the same rule."""
    ids = list(ids)
    if not ids:
        return None
    width = next_pow2(len(ids))
    out = np.full(width, -1, np.int32)
    out[:len(ids)] = ids
    return jnp.asarray(out)


def ladder_rungs(cap: int) -> Tuple[int, ...]:
    """The pow2 batch-width ladder up to ``cap`` — exactly the shapes
    :func:`batch_score_top_k` can dispatch (its ``B`` pads to the next
    power of two) and therefore exactly what the continuous-batching
    scheduler (serving/scheduler.py) may pick. Deploy-time warmup
    (``ALSAlgorithm.warmup``) and the zero-recompile test walk THIS
    ladder, so warmed shapes track dispatchable shapes through one
    rule."""
    cap = next_pow2(max(int(cap), 1))
    return tuple(1 << i for i in range(cap.bit_length()))


def serve_compile_cache_size() -> int:
    """Compiled serving-dispatch variants resident in this process —
    the scheduler's zero-steady-state-recompile contract counter (the
    serving twin of ``speed.foldin.foldin_compile_cache_size``).
    Bounded by the pow2 ladder × the distinct (k, catalog) shapes
    served; tests pin that a warm ladder stops growing it."""
    return sum(
        int(fn._cache_size())
        for fn in (top_k_with_exclusions, _score_and_top_k_xla,
                   _score_user_top_k_xla, _batch_score_top_k_xla,
                   _sharded_topk_jit)
    ) + _mips.mips_compile_cache_size()


def batch_score_top_k(
    user_factors: jax.Array,
    item_factors: jax.Array,
    rows,                           # [B] int array of user indices
    k: int,
    valid_items: Optional[int] = None,
) -> jax.Array:
    """Score B users against the whole catalog and rank, in ONE dispatch.

    The serving micro-batcher's compute path (the reference leaves this as
    "TODO: Parallelize", CreateServer.scala:523): one [B, K] × [K, I] matmul
    amortizes the device round trip over the whole batch. BOTH static shape
    inputs are padded to the next power of two — ``rows`` with row 0
    repeated, ``k`` capped at the catalog — so live traffic with varying
    batch sizes AND varying ``num`` compiles O(log max-batch · log catalog)
    variants total instead of one per distinct (B, num) pair. Callers slice
    row b of the packed [2, B_pad, k_pad] result to their own ``num``."""
    import numpy as np

    B = len(rows)
    n_items = item_factors.shape[0]
    k_pad = min(next_pow2(int(k)), n_items)
    if B == 0:
        # an empty batch would otherwise index rows[0] below (and
        # next_pow2(0) still pads to 1) — hand back an empty packed
        # result without touching the device
        return jnp.zeros((2, 0, k_pad), jnp.float32)
    pad = next_pow2(B)
    # vectorized pad (row 0 repeated), not a per-call Python list — this
    # runs on the serving hot path for every fused micro-batch
    rows_np = np.asarray(rows, np.int32).reshape(B)
    if pad > B:
        rows_np = np.concatenate(
            [rows_np, np.full(pad - B, rows_np[0], np.int32)])
    # the scheduler's fused dispatch rides the same MIPS auto-route as
    # the per-query paths (padded rows keep the pow2 ladder; the
    # two-stage stage widths are static, so steady state still never
    # recompiles)
    mips_index = _mips.route(item_factors, k=k_pad)
    if mips_index is not None:
        return _mips.mips_batch_score_top_k(
            user_factors, item_factors, mips_index, rows_np, k_pad)
    _mips.book_exhaustive(int(pad) * int(item_factors.shape[0]))
    _pt0 = _profile.t0()  # None on the PIO_PROFILE=0 default hot path
    out = _batch_score_top_k_xla(user_factors, item_factors,
                                 jnp.asarray(rows_np), k_pad,
                                 valid_items=valid_items)
    _profile.record(
        _pt0, "serve", "serve_topk_batch",
        2.0 * B * user_factors.shape[1] * item_factors.shape[0], out)
    # fallback parity with the published tail — see score_and_top_k
    return _mips.merge_published_fallback(
        item_factors, out,
        lambda: np.asarray(user_factors[jnp.asarray(rows_np)],
                           np.float32), k_pad, None)


def score_and_top_k(
    user_vector: jax.Array,         # [K]
    item_factors: jax.Array,        # [I, K]
    k: int,
    exclude: Optional[jax.Array] = None,
    allowed_mask: Optional[jax.Array] = None,
    valid_items: Optional[int] = None,
) -> jax.Array:
    """Full-catalog scoring + ranking in one fused device call.

    Returns a single packed [2, k] f32 array (row 0 = scores, row 1 =
    indices): serving pays exactly ONE device→host fetch per query — on a
    tunneled/remote TPU each fetch is a full round trip, so fetch count, not
    FLOPs, dominates query latency. Large catalogs on real TPU route to the
    Pallas blocked-candidate kernel (ops/pallas_kernels.py), which never
    writes the full score vector to HBM. ``valid_items`` masks a placed
    table's zero-factor padding tail (see :func:`score_user_and_top_k`).
    """
    from incubator_predictionio_tpu.parallel.placement import (
        is_distributed,
    )

    # auto-route to the two-stage MIPS path (ops/mips.py) when an index
    # is registered for this table; filters/off/small catalogues keep
    # the exhaustive path below, which is also the recall oracle
    mips_index = _mips.route(item_factors, k=k,
                             allowed_mask=allowed_mask, exclude=exclude)
    if mips_index is not None:
        return _mips.mips_score_and_top_k(
            user_vector, item_factors, mips_index, k, exclude=exclude)
    _mips.book_exhaustive(int(item_factors.shape[0]))
    # fallback parity: overlay-published rows live only in the index's
    # exact tail (virtual ids are NOT table rows), so a query routed
    # around the two-stage path — oversized exclusion list, mode off —
    # must merge them or published keys silently vanish. Filtered
    # queries skip the merge (a virtual id cannot honor an item mask);
    # no-op without a registered index or with an empty tail.
    masked = allowed_mask is not None

    def _fold(out):
        if masked:
            return out
        return _mips.merge_published_fallback(
            item_factors, out,
            lambda: np.asarray(user_vector, np.float32), k, exclude)

    if is_distributed(item_factors):
        # placed serving: per-shard partial top-k + all-gather merge
        return _fold(sharded_top_k(user_vector, item_factors, k,
                                   exclude=exclude,
                                   allowed_mask=allowed_mask,
                                   valid_items=valid_items))
    allowed_mask = _fold_valid_mask(allowed_mask, item_factors,
                                    valid_items)
    _pt0 = _profile.t0()  # None on the PIO_PROFILE=0 default hot path
    if item_factors.shape[0] >= PALLAS_MIN_ITEMS and k <= 128:
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            score_and_top_k_pallas, topk_kernel_available)
        if topk_kernel_available():
            out = score_and_top_k_pallas(
                user_vector, item_factors, k,
                exclude=exclude, allowed_mask=allowed_mask,
                block_items=8192,
            )
            _profile.record(
                _pt0, "serve", "serve_topk",
                2.0 * item_factors.shape[0] * item_factors.shape[1], out)
            return _fold(out)
    out = _score_and_top_k_xla(user_vector, item_factors, k,
                               exclude, allowed_mask)
    _profile.record(_pt0, "serve", "serve_topk",
                    2.0 * item_factors.shape[0] * item_factors.shape[1],
                    out)
    return _fold(out)
