"""Tenant registry — access key → tenant id → deploy (ROADMAP item 4).

The reference PredictionIO is a multi-app server: app ids + access keys
multiplex event ingest AND engine deployments through one address. Our
ingest side already speaks that grammar (``servers/event_server.py``
authenticates ``accessKey`` query param / HTTP Basic against the
``AccessKey`` DAO); this module brings the SERVING side to parity and
is the single source of truth every tenant-aware plane reads:

- the prediction server's per-tenant deploys and tenant-scoped
  ``/reload`` (servers/prediction_server.py),
- the front door's query-path auth + tenant routing
  (serving/frontdoor.py — placement/circuits stay transport-scoped),
- the scheduler's per-(tenant, engine) queues, weights and admission
  quotas (serving/scheduler.py),
- the ``tenant`` label on ``pio_query_latency_seconds`` /
  ``pio_serve_shed_total`` / ``pio_serve_queue_depth`` — label values
  come ONLY from this registry (the bounded-cardinality contract the
  ``unscoped-tenant-metric`` lint rule enforces),
- per-tenant SLO specs (obs/slo.py ``tenant_specs``) and the tenant
  block incident capture freezes into bundles (obs/recorder.py).

Registry grammar (``PIO_TENANTS``, documented in docs/production.md
"Multi-tenant platform"): ``;``-separated entries, each

    <tenant_id>:<access_key>[:opt=val[,opt=val...]]

with options ``weight`` (weighted-fair dispatch share, default 1),
``quota`` (max queued admissions across the tenant's queues; absent =
unlimited), ``engine`` / ``variant`` (the deploy this tenant's queries
route to; absent = the worker's default deploy), and ``disabled``
(key rejected with 401 while the entry keeps its registry slot).

The registry is BOUNDED (``MAX_TENANTS``) and tenant ids are validated
against a closed grammar — both are what make ``tenant`` a legal
metric label. An empty registry (no ``PIO_TENANTS``) is the
single-tenant compatibility mode: ``/queries.json`` stays
unauthenticated and everything books under the ``default`` tenant.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import re
import threading
from typing import Any, Dict, Optional, Tuple

from incubator_predictionio_tpu.utils.http import HttpError

#: the single-tenant compatibility label — every unconfigured process
#: books its traffic here, so dashboards read identically before and
#: after a fleet turns tenancy on
DEFAULT_TENANT = "default"

#: registry bound: the tenant label's worst-case cardinality (and the
#: per-worker deploy count ceiling — co-resident deploys share one
#: device)
MAX_TENANTS = 64

_TENANT_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_-]{0,63}$")


class TenantAuthError(HttpError):
    """401 on the query path: unknown, disabled, or missing access key
    while tenancy is configured — the serving twin of the event
    server's ``AuthError``."""

    def __init__(self, message: str = "Invalid accessKey.") -> None:
        super().__init__(401, message)


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One registry entry: the key→tenant→deploy mapping plus the
    isolation policy the scheduler enforces."""

    tenant_id: str
    access_key: str
    weight: int = 1
    quota: Optional[int] = None
    engine_id: Optional[str] = None
    engine_variant: Optional[str] = None
    enabled: bool = True


class TenantRegistry:
    """Bounded, immutable-after-construction tenant table."""

    def __init__(self, tenants: Tuple[Tenant, ...] = ()) -> None:
        if len(tenants) > MAX_TENANTS:
            raise ValueError(
                f"tenant registry bounded at {MAX_TENANTS} entries "
                f"(got {len(tenants)})")
        by_id: Dict[str, Tenant] = {}
        by_key: Dict[str, Tenant] = {}
        for t in tenants:
            if not _TENANT_ID_RE.match(t.tenant_id):
                raise ValueError(
                    f"invalid tenant id {t.tenant_id!r}: must match "
                    f"{_TENANT_ID_RE.pattern}")
            if t.tenant_id in by_id:
                raise ValueError(f"duplicate tenant id {t.tenant_id!r}")
            if not t.access_key:
                raise ValueError(
                    f"tenant {t.tenant_id!r} needs an access key")
            if t.access_key in by_key:
                raise ValueError(
                    f"duplicate access key for tenant {t.tenant_id!r}")
            if t.weight < 1:
                raise ValueError(
                    f"tenant {t.tenant_id!r}: weight must be >= 1")
            by_id[t.tenant_id] = t
            by_key[t.access_key] = t
        self._by_id = by_id
        self._by_key = by_key

    # -- parsing ------------------------------------------------------------
    @classmethod
    def from_env(cls, value: Optional[str] = None) -> "TenantRegistry":
        """Parse the ``PIO_TENANTS`` grammar (see module docstring).
        An unset/empty value is the empty registry — single-tenant
        compatibility mode."""
        raw = os.environ.get("PIO_TENANTS", "") if value is None else value
        tenants = []
        for entry in raw.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":", 2)
            if len(parts) < 2:
                raise ValueError(
                    f"PIO_TENANTS entry {entry!r}: expected "
                    "<tenant_id>:<access_key>[:opt=val,...]")
            tenant_id, access_key = parts[0].strip(), parts[1].strip()
            kwargs: Dict[str, Any] = {}
            if len(parts) == 3:
                for opt in parts[2].split(","):
                    opt = opt.strip()
                    if not opt:
                        continue
                    name, _, val = opt.partition("=")
                    name = name.strip()
                    val = val.strip()
                    if name == "weight":
                        kwargs["weight"] = int(val)
                    elif name == "quota":
                        kwargs["quota"] = int(val)
                    elif name == "engine":
                        kwargs["engine_id"] = val
                    elif name == "variant":
                        kwargs["engine_variant"] = val
                    elif name == "disabled":
                        kwargs["enabled"] = val.lower() in (
                            "", "0", "off", "false")
                    else:
                        raise ValueError(
                            f"PIO_TENANTS entry {entry!r}: unknown "
                            f"option {name!r}")
            tenants.append(Tenant(tenant_id, access_key, **kwargs))
        return cls(tuple(tenants))

    # -- lookups ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._by_id)

    def __bool__(self) -> bool:
        return bool(self._by_id)

    def tenants(self) -> Tuple[Tenant, ...]:
        return tuple(self._by_id.values())

    def tenant_ids(self) -> Tuple[str, ...]:
        return tuple(self._by_id)

    def get(self, tenant_id: str) -> Optional[Tenant]:
        return self._by_id.get(tenant_id)

    def by_key(self, access_key: str) -> Optional[Tenant]:
        return self._by_key.get(access_key)

    def label(self, tenant_id: Optional[str]) -> str:
        """A METRIC-SAFE tenant label: the id when registered, the
        default label otherwise — so a label value can never come from
        the wire unvalidated."""
        if tenant_id is not None and tenant_id in self._by_id:
            return tenant_id
        return DEFAULT_TENANT

    def weights(self) -> Dict[str, int]:
        return {t.tenant_id: t.weight for t in self._by_id.values()}

    def quotas(self) -> Dict[str, Optional[int]]:
        return {t.tenant_id: t.quota for t in self._by_id.values()}

    # -- auth (EventServer.scala:93-131 grammar, serving edition) -----------
    def authenticate(self, request: Any) -> str:
        """Map a query-path request to its tenant id.

        Empty registry → :data:`DEFAULT_TENANT`, no auth (the
        single-deploy compatibility mode). Configured registry → the
        ``accessKey`` query param or HTTP Basic username (the event
        server's exact grammar) must name an enabled tenant; missing,
        unknown, or disabled keys raise :class:`TenantAuthError`
        (401)."""
        if not self._by_id:
            return DEFAULT_TENANT
        key = extract_access_key(request)
        if not key:
            raise TenantAuthError("Missing accessKey.")
        tenant = self._by_key.get(key)
        if tenant is None:
            raise TenantAuthError("Invalid accessKey.")
        if not tenant.enabled:
            raise TenantAuthError("Access key disabled.")
        return tenant.tenant_id

    def describe(self) -> Dict[str, Dict[str, Any]]:
        """The registry table for /status blocks and incident bundles
        (keys redacted — bundles and status pages are shareable)."""
        return {
            t.tenant_id: {
                "weight": t.weight,
                "quota": t.quota,
                "engine": t.engine_id,
                "variant": t.engine_variant,
                "enabled": t.enabled,
            }
            for t in self._by_id.values()
        }


def extract_access_key(request: Any) -> Optional[str]:
    """The event server's auth grammar (EventServer.scala:93-131):
    ``accessKey`` query param, else HTTP Basic where the username is
    the key."""
    key = request.query.get("accessKey")
    if key:
        return key
    auth = request.headers.get("authorization", "")
    if auth.startswith("Basic "):
        try:
            decoded = base64.b64decode(auth[6:]).decode("utf-8")
            return decoded.strip().split(":")[0]
        except Exception:  # noqa: BLE001 — malformed header = no key
            return None
    return None


# ---------------------------------------------------------------------------
# process-wide singleton (parsed once per PIO_TENANTS value — workers,
# the front door and the admin all read the same table)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_registry: Optional[TenantRegistry] = None
_registry_env: Optional[str] = None


def get_registry() -> TenantRegistry:
    """The process registry, re-parsed whenever ``PIO_TENANTS``
    changes (tests monkeypatch the env; servers read it at request
    time through this seam)."""
    global _registry, _registry_env
    raw = os.environ.get("PIO_TENANTS", "")
    with _lock:
        if _registry is None or raw != _registry_env:
            _registry = TenantRegistry.from_env(raw)
            _registry_env = raw
        return _registry


def set_registry(registry: Optional[TenantRegistry]) -> None:
    """Inject a registry (tests); ``None`` reverts to env parsing."""
    global _registry, _registry_env
    with _lock:
        _registry = registry
        _registry_env = (os.environ.get("PIO_TENANTS", "")
                         if registry is not None else None)


def reset_registry() -> None:
    set_registry(None)


def export_tenants_fn() -> Any:
    """The incident-capture seam (obs/recorder.py ``tenants_fn``,
    wired in servers/admin.py and the prediction server): a callable
    freezing the tenant block into bundles — the registry table plus
    every per-tenant SLO entry (spec names ``<slo>@<tenant>``), so a
    bundle answers "which tenant breached, and was the fleet healthy"
    without the live process."""

    def tenants_block() -> Optional[Dict[str, Any]]:
        registry = get_registry()
        if not registry:
            return None
        from incubator_predictionio_tpu.obs import slo as obs_slo

        per_tenant: Dict[str, Any] = {
            tid: {"policy": desc, "slo": []}
            for tid, desc in registry.describe().items()
        }
        try:
            for entry in obs_slo.get_engine().evaluate():
                _, _, tid = entry["name"].partition("@")
                if tid in per_tenant:
                    per_tenant[tid]["slo"].append(entry)
        except Exception:  # noqa: BLE001 — the table alone still lands
            pass
        return per_tenant

    return tenants_block


__all__ = [
    "DEFAULT_TENANT", "MAX_TENANTS", "Tenant", "TenantAuthError",
    "TenantRegistry", "export_tenants_fn", "extract_access_key",
    "get_registry", "reset_registry", "set_registry",
]
