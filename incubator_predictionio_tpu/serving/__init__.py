"""Request plane between the HTTP servers and the device kernels.

``serving.scheduler`` is THE sanctioned seam for query-path device
dispatch: server request handlers enqueue, the scheduler coalesces
(queue-depth-adaptive pow2 batching onto the compile-cached kernel
ladders) and sheds (SLO-projected 503 + Retry-After) — the pio-lint
rule ``unbatched-dispatch`` flags handlers that bypass it.

``serving.frontdoor`` is the layer above: ONE address fanned across N
worker processes with queue-depth-aware placement, circuit-breaker
health, budgeted retry, and rolling drain-reload choreography
(docs/production.md "Fleet front door").
"""

from incubator_predictionio_tpu.serving.scheduler import (  # noqa: F401
    BatchScheduler,
    ShedError,
    ladder_cap,
    max_wait_s,
    plan_dispatch,
)


def __getattr__(name: str):
    """Lazy ``FrontDoor``/``FrontDoorConfig`` re-export: importing the
    frontdoor module registers the pio_frontdoor_* metric families, and
    a plain prediction WORKER (which imports serving.scheduler) must
    not grow empty front-door series on its /metrics."""
    if name in ("FrontDoor", "FrontDoorConfig"):
        from incubator_predictionio_tpu.serving import frontdoor

        return getattr(frontdoor, name)
    raise AttributeError(name)
