"""Request plane between the HTTP servers and the device kernels.

``serving.scheduler`` is THE sanctioned seam for query-path device
dispatch: server request handlers enqueue, the scheduler coalesces
(queue-depth-adaptive pow2 batching onto the compile-cached kernel
ladders) and sheds (SLO-projected 503 + Retry-After) — the pio-lint
rule ``unbatched-dispatch`` flags handlers that bypass it.
"""

from incubator_predictionio_tpu.serving.scheduler import (  # noqa: F401
    BatchScheduler,
    ShedError,
    ladder_cap,
    max_wait_s,
    plan_dispatch,
)
