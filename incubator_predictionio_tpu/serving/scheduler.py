"""Continuous-batching serving scheduler — the socket→kernel request plane.

The fixed micro-batcher this replaces fused at most ``max_batch=64``
queries per device dispatch regardless of queue pressure, so concurrent
serving topped out when the per-dispatch overhead stopped amortizing
(BENCH_r04/r05: ~1.8–2.5k QPS/process) and overload had no exit but
rising latency. This module is the queue-aware plane ROADMAP item 2
names:

- **Admission queues, per engine.** Every in-flight query lands in its
  engine's FIFO queue (recommendation / ecommerce / similarproduct
  traffic fuses independently — one engine's burst never pads another's
  batches), and dispatcher threads drain whole batches into ONE
  ``handle_batch`` call — which routes to the existing padded device
  kernels (``ops/topk.batch_score_top_k``, the ``speed/foldin`` bucket
  ladder, ``sharded_top_k`` under a placed table: all pad to the same
  pow2 ladder, so every batch width this scheduler can choose is
  already compile-cached after warmup — zero steady-state recompiles,
  pinned by ``tests/test_scheduler.py``).

- **Queue-depth-adaptive batch width.** Each queue carries a pow2
  *rung*: the batch width the next dispatch drains. Deeper queue than
  the rung → grow to the next ladder rung (up to :func:`ladder_cap`);
  queue at half the rung or less → collapse one rung. Idle traffic
  serves at rung 1 with zero added latency; a burst walks up the ladder
  in log2 steps and walks back down when it passes
  (:func:`plan_dispatch` is the pure decision rule the tests drive).

- **Age bound** (``PIO_SERVE_MAX_WAIT_MS``): a query must never wait
  past the bound just because the rung is small — when the oldest
  queued request's age crosses it, the dispatch takes the whole backlog
  (up to the cap) regardless of the rung. This is the starvation fix
  for the old batcher, where a request arriving behind a full batch
  could wait multiple full dispatch cycles.

- **Load shedding** against the declared ``serve_p99`` objective
  (obs/slo.py): at admission, the projected completion time — queue
  depth over the rung, times the EWMA dispatch wall, plus the live p99
  estimate from ``pio_query_latency_seconds`` — is compared to the SLO
  threshold. A request that cannot make it sheds with 503 +
  ``Retry-After`` (:class:`ShedError`) instead of poisoning the p99 for
  everyone admitted behind it; a higher-priority arrival evicts the
  lowest-priority queued request rather than shedding itself. Sheds
  book ``pio_serve_shed_total{tenant,reason}``.

- **Tenant isolation** (ROADMAP item 4, serving/tenancy.py). Queues
  are keyed ``(tenant, engine)``; dispatch is WEIGHTED-FAIR across
  tenants (lowest virtual service — dispatched queries over weight —
  goes next, FIFO within a tenant), replacing oldest-head-across-
  queues, which a flooding tenant would monopolize. Per-tenant
  admission QUOTAS bound a tenant's total backlog (shed reason
  ``quota``); the shed projection reads the TENANT's own queue and the
  TENANT's own live p99, so a noisy neighbor's backlog never sheds a
  victim's traffic; and priority eviction is cross-tenant but
  restricted to tenants AT OR OVER their weighted fair share of the
  backlog — an under-share (victim) tenant's queued requests are never
  evicted on an aggressor's behalf.

Exported series: ``pio_serve_batch_size`` (pow2 buckets — the fused
width distribution, the fleet bench's ``fleet_batch_p50`` source),
``pio_serve_queue_wait_seconds``,
``pio_serve_shed_total{tenant,reason}`` (tenant values come from the
bounded registry — the ``unscoped-tenant-metric`` lint contract).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import inspect
import math
import os
import threading
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import recorder as obs_recorder
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.serving import tenancy
from incubator_predictionio_tpu.utils import times
from incubator_predictionio_tpu.utils.http import HttpError

#: fused batch width per dispatch, on pow2 buckets matching the ladder
#: the padded kernels compile (1..8192 covers any sane cap)
_BATCH_SIZE = obs_metrics.REGISTRY.histogram(
    "pio_serve_batch_size",
    "queries fused into one scheduler dispatch (pow2 ladder buckets)",
    buckets=tuple(float(1 << i) for i in range(14)))
_QUEUE_WAIT = obs_metrics.REGISTRY.histogram(
    "pio_serve_queue_wait_seconds",
    "admission-queue wait before a query's batch dispatched")
_SHED = obs_metrics.REGISTRY.counter(
    "pio_serve_shed_total",
    "requests shed by the scheduler, by tenant and reason (overload = "
    "projected past the serve_p99 objective; quota = the tenant's "
    "admission quota was full; evicted = displaced by a higher-"
    "priority arrival; shutdown = scheduler stopping)",
    labels=("tenant", "reason"))
_COMPILE_CACHE = obs_metrics.REGISTRY.gauge(
    "pio_serve_compile_cache_size",
    "compiled serving-dispatch variants resident (ops/topk ladder) — "
    "flat in steady state, the zero-recompile contract's counter")


def _collect_compile_cache() -> None:
    # scrape-time: only report when the serving kernels were actually
    # imported — never drag jax into a process that scrapes but does
    # not serve (storage/event servers share this registry module)
    import sys as _sys

    mod = _sys.modules.get("incubator_predictionio_tpu.ops.topk")
    if mod is not None:
        _COMPILE_CACHE.set(float(mod.serve_compile_cache_size()))


obs_metrics.REGISTRY.register_collector("serve_compile_cache",
                                        _collect_compile_cache)


def next_pow2(n: int) -> int:
    """Smallest power of two ≥ n (≥1) — the ladder's rung spacing, the
    same policy ``ops/topk.next_pow2`` pads dispatch shapes with."""
    return 1 << max(int(n) - 1, 0).bit_length()


def ladder_cap() -> int:
    """Largest batch width the scheduler may fuse (pow2-rounded).

    ``PIO_SERVE_MAX_BATCH`` is the LADDER CAP, not a fixed batch size:
    dispatches use the adaptive rung and only reach the cap under
    sustained queue pressure (docs/production.md "Serving fleet")."""
    try:
        n = int(os.environ.get("PIO_SERVE_MAX_BATCH", "512"))
    except ValueError:
        n = 512
    return next_pow2(max(n, 1))


def max_wait_s() -> float:
    """Age bound: no admitted query waits longer than this for its
    dispatch just because the rung is small (``PIO_SERVE_MAX_WAIT_MS``,
    default 250 ms; ≤0 disables the bound)."""
    try:
        ms = float(os.environ.get("PIO_SERVE_MAX_WAIT_MS", "250"))
    except ValueError:
        ms = 250.0
    return ms / 1000.0


def serve_objective_s() -> float:
    """The serve_p99 SLO threshold the shed projection tests against —
    read from the SAME declared objective the burn-rate engine
    evaluates (obs/slo.py, ``PIO_SLO_SERVE_P99_S``), so shedding and
    the SLO can never disagree about the promise."""
    from incubator_predictionio_tpu.obs import slo as obs_slo

    for spec in obs_slo.default_specs():
        if spec.name == "serve_p99":
            return float(spec.threshold)
    return 0.25


def shed_enabled() -> bool:
    return os.environ.get("PIO_SERVE_SHED", "1").lower() not in (
        "0", "off", "false")


class ShedError(HttpError):
    """503 with a Retry-After contract: the scheduler projected this
    request past the serve_p99 objective. Clients back off for
    ``retry_after_s`` and retry; the header rides the error response
    (utils/http.py forwards ``HttpError.headers``)."""

    def __init__(self, retry_after_s: float, reason: str = "overload"):
        retry = max(int(math.ceil(retry_after_s)), 1)
        super().__init__(
            503,
            "Serving overloaded: request projected past the latency "
            f"objective; retry after {retry}s.")
        self.headers = {"Retry-After": str(retry)}
        self.reason = reason
        self.retry_after_s = retry


def plan_dispatch(depth: int, rung: int, oldest_age_s: float,
                  cap: int, wait_bound_s: float) -> Tuple[int, int]:
    """The pure dispatch decision: ``(take, next_rung)``.

    - take ``min(depth, rung)`` normally; the WHOLE backlog (up to
      ``cap``) when the oldest waiter's age crossed the bound — the
      scheduler never holds a query past ``PIO_SERVE_MAX_WAIT_MS``.
    - grow the rung one ladder step when the queue outran it, collapse
      one step when the queue sits at half the rung or less; steady
      traffic keeps its rung (hysteresis band (rung/2, rung]).
    """
    depth = max(int(depth), 0)
    rung = min(max(int(rung), 1), cap)
    if depth == 0:
        return 0, rung
    if wait_bound_s > 0 and oldest_age_s >= wait_bound_s:
        take = min(depth, cap)
    else:
        take = min(depth, rung)
    if depth > rung:
        rung = min(rung * 2, cap)
    elif 2 * depth <= rung:
        rung = max(rung // 2, 1)
    return take, rung


@dataclasses.dataclass
class _Pending:
    body: Any
    fut: "concurrent.futures.Future"
    t_enq: float
    priority: int
    #: the submitting request's ambient trace ID (None outside a
    #: request) — the dispatch loop re-installs ONE member's trace
    #: around handle_batch so the latency histogram's exemplar
    #: reservoir (obs/metrics.py) can name a concrete query for the
    #: batch's shared wall
    trace_id: Optional[str] = None


class _EngineQueue:
    """One engine's admission queue + its ladder/latency state."""

    __slots__ = ("items", "rung", "ewma_wall", "in_flight")

    def __init__(self) -> None:
        self.items: Deque[_Pending] = deque()
        self.rung = 1
        #: EWMA of one dispatch's wall — the shed projection's cycle
        #: cost. 0.0 until the first dispatch lands (never shed on a
        #: cold queue: there is no evidence of overload yet).
        self.ewma_wall = 0.0
        self.in_flight = 0

    def note_wall(self, wall: float) -> None:
        self.ewma_wall = (wall if self.ewma_wall == 0.0
                          else 0.7 * self.ewma_wall + 0.3 * wall)

    def projected_wait_s(self, cap: int) -> float:
        """Queue wait a NEW arrival would see: full dispatch cycles
        ahead of it plus the in-flight dispatch, each at the EWMA wall.

        The cycle width is the rung THIS depth will drive the ladder
        to — not the current rung: a burst against a cold (rung-1)
        queue is exactly what adaptive batching absorbs, and
        projecting it as depth-many singleton dispatches would shed
        the load the ladder was about to fuse (a metastable shed
        spiral: shedding holds the queue short, the rung never grows,
        the projection never recovers)."""
        if self.ewma_wall <= 0.0:
            return 0.0
        depth = len(self.items) + 1
        width = min(max(self.rung, next_pow2(depth)), cap)
        cycles = math.ceil(depth / width)
        return (cycles + (1 if self.in_flight else 0)) * self.ewma_wall


class BatchScheduler:
    """Continuous-batching scheduler over one ``handle_batch`` callable.

    ``handle_batch(bodies) -> results`` serves a whole batch in one
    device dispatch (results list aligned with bodies; an Exception
    entry fails just that member). A two-parameter handler —
    ``handle_batch(bodies, engine)`` — additionally receives the queue
    key, for multi-engine hosts; a three-parameter handler —
    ``handle_batch(bodies, engine, tenant)`` — also receives the
    tenant, for multi-deploy hosts (servers/prediction_server.py routes
    each tenant's batch to its own deploy). Construction-time signature
    stays compatible with the old ``_MicroBatcher(handle, max_batch,
    workers=…)``; ``max_batch`` is now the LADDER CAP the adaptive rung
    grows toward, not the fixed fuse width.
    """

    def __init__(
        self,
        handle_batch: Callable[..., List[Any]],
        max_batch: Optional[int] = None,
        workers: int = 1,
        *,
        clock: Optional[Callable[[], float]] = None,
        wait_bound_s: Optional[float] = None,
        slo_s: Optional[float] = None,
        p99_fn: Optional[Callable[..., Optional[float]]] = None,
        shed: Optional[bool] = None,
        tenant_weights: Optional[Dict[str, int]] = None,
        tenant_quotas: Optional[Dict[str, Optional[int]]] = None,
    ) -> None:
        self._handle_batch = handle_batch
        try:
            params = [
                p for p in inspect.signature(handle_batch).parameters
                .values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty  # a defaulted slot is NOT an
                # engine parameter (closure-style wrappers default-bind)
            ]
            self._pass_engine = len(params) >= 2
            self._pass_tenant = len(params) >= 3
        except (TypeError, ValueError):
            self._pass_engine = False
            self._pass_tenant = False
        self.cap = (ladder_cap() if max_batch is None
                    else next_pow2(max(int(max_batch), 1)))
        #: compat: old callers read ``max_batch`` as the fuse bound
        self.max_batch = self.cap
        self._clock = clock if clock is not None else times.monotonic
        self.wait_bound_s = (max_wait_s() if wait_bound_s is None
                             else float(wait_bound_s))
        self.slo_s = serve_objective_s() if slo_s is None else float(slo_s)
        self._p99_fn = p99_fn
        # a one-parameter p99 feed is per-tenant (the live latency
        # estimate must slice the tenant's own child — a flooding
        # neighbor's fat tail must not shed a healthy tenant's traffic)
        self._p99_per_tenant = False
        if p99_fn is not None:
            try:
                p99_params = [
                    p for p in inspect.signature(p99_fn).parameters
                    .values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)
                    and p.default is p.empty
                ]
                self._p99_per_tenant = len(p99_params) >= 1
            except (TypeError, ValueError):
                self._p99_per_tenant = False
        self._shed = shed_enabled() if shed is None else bool(shed)
        self._cv = threading.Condition()
        #: queues keyed (tenant, engine) — one tenant's engines fuse
        #: independently AND one tenant's flood stays its own problem
        self._queues: "OrderedDict[Tuple[str, str], _EngineQueue]" = \
            OrderedDict()
        #: weighted-fair dispatch state: per-tenant NORMALIZED virtual
        #: service (queries dispatched / weight) — the non-empty tenant
        #: with the lowest value goes next
        self._service: Dict[str, float] = {}
        self._tenant_weights: Dict[str, int] = dict(tenant_weights or {})
        self._tenant_quotas: Dict[str, Optional[int]] = dict(
            tenant_quotas or {})
        #: per-tenant last-admission clock — a tenant that submitted
        #: within CONTEND_WINDOW_S is "contending" and the weighted
        #: dispatch-slot caps bind (see _slot_caps_locked)
        self._t_last_submit: Dict[str, float] = {}
        self._stopped = False
        self.shed_count = 0
        self.shed_by_tenant: Dict[str, int] = {}
        self._n_workers = max(int(workers), 1)
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"pio-serve-sched-{i}")
            for i in range(self._n_workers)
        ]
        for t in self._threads:
            t.start()
        # the flight recorder's state-snapshot seam: incident bundles
        # freeze this scheduler's queue/rung/shed state alongside the
        # metric window. Weakref-bound with named replace semantics so
        # a hot-swapped server's new scheduler takes over the slot and
        # the old one can be collected (the registry-collector idiom).
        ref = weakref.ref(self)

        def _snapshot_provider():
            sched = ref()
            return sched.snapshot() if sched is not None else None

        obs_recorder.register_state_provider("scheduler",
                                             _snapshot_provider)

    # -- tenant helpers (call under self._cv) -------------------------------
    def _weight(self, tenant: str) -> int:
        return max(int(self._tenant_weights.get(tenant, 1)), 1)

    def _tenant_depth_locked(self, tenant: str) -> int:
        return sum(len(q.items) for (t, _e), q in self._queues.items()
                   if t == tenant)

    def _fair_share_tenants_locked(self) -> "set":
        """Tenants AT OR OVER their weighted fair share of the queued
        backlog — the only legal eviction victims. With one active
        tenant the share test is an equality, so single-tenant priority
        eviction behaves exactly as before tenancy existed."""
        queued: Dict[str, int] = {}
        for (t, _e), q in self._queues.items():
            if q.items:
                queued[t] = queued.get(t, 0) + len(q.items)
        total = sum(queued.values())
        total_weight = sum(self._weight(t) for t in queued)
        return {
            t for t, n in queued.items()
            if n * total_weight >= self._weight(t) * total
        }

    def _tenant_inflight_locked(self, tenant: str) -> int:
        return sum(q.in_flight for (t, _e), q in self._queues.items()
                   if t == tenant)

    #: a tenant that admitted a query this recently still counts as
    #: contending for dispatch slots even if its queue is momentarily
    #: empty — the whole point of the slot reservation is the NEXT
    #: arrival of a light tenant, which by definition is not queued yet
    CONTEND_WINDOW_S = 5.0

    def _slot_caps_locked(self, now: float) -> Optional[Dict[str, int]]:
        """Per-tenant caps on CONCURRENT dispatch slots, or None (no
        caps). When ≥2 tenants are contending (submitted within
        CONTEND_WINDOW_S, or still backlogged) and the scheduler runs
        ≥2 dispatcher threads, each tenant's slots are bounded by its
        weighted share ``ceil(workers * w / total_w)`` of the thread
        pool: a low-weight flooder that would otherwise keep EVERY
        thread inside its own floor-length dispatches is pinned below
        the wall, so a light tenant's arrival never waits a full
        in-flight dispatch. The caps are deliberately NOT
        work-conserving while contention lasts — the reserved slot is
        the isolation — but a tenant alone on the scheduler (no recent
        traffic from anyone else) is never capped, so single-tenant
        throughput is untouched."""
        if self._n_workers < 2:
            return None
        contending = {t for t, ts in self._t_last_submit.items()
                      if now - ts <= self.CONTEND_WINDOW_S}
        contending |= {t for (t, _e), q in self._queues.items()
                      if q.items}
        if len(contending) < 2:
            return None
        total_w = sum(self._weight(t) for t in contending)
        return {
            t: max(1, math.ceil(
                self._n_workers * self._weight(t) / total_w))
            for t in contending
        }

    def set_tenant_policy(
            self, weights: Optional[Dict[str, int]] = None,
            quotas: Optional[Dict[str, Optional[int]]] = None) -> None:
        """Adopt a tenant registry's isolation policy live (the server
        calls this after a registry (re)parse — weights steer the
        weighted-fair pick, quotas bound admissions)."""
        with self._cv:
            if weights is not None:
                self._tenant_weights = dict(weights)
            if quotas is not None:
                self._tenant_quotas = dict(quotas)

    # -- admission ----------------------------------------------------------
    def submit(self, body: Any, priority: int = 0,
               engine: str = "default",
               tenant: str = tenancy.DEFAULT_TENANT,
               ) -> "concurrent.futures.Future":
        """Enqueue one query body → Future of its result. ``priority``
        orders only the SHED decision (higher survives longer), never
        dispatch order — admitted requests stay FIFO so no admitted
        query starves behind a later high-priority one. The shed
        projection reads only THIS tenant's queue and p99, and eviction
        victims come only from at-or-over-fair-share tenants: a noisy
        neighbor sheds its own traffic, never a victim's."""
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        now = self._clock()
        shed_exc: Optional[ShedError] = None
        victim: Optional[_Pending] = None
        victim_tenant = tenant
        with self._cv:
            if self._stopped:
                fut.set_exception(
                    HttpError(503, "Server is shutting down."))
                return fut
            key = (tenant, engine)
            self._t_last_submit[tenant] = now
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _EngineQueue()
            tenant_depth = self._tenant_depth_locked(tenant)
            quota = self._tenant_quotas.get(tenant)
            if quota is not None and tenant_depth >= int(quota):
                # the tenant's OWN admission bound — enforced even with
                # SLO shedding off, and never answered by eviction: a
                # quota is the tenant displacing itself, not others
                shed_exc = ShedError(
                    max(q.projected_wait_s(self.cap), 1.0),
                    reason="quota")
            elif self._shed and q.items:
                projected = q.projected_wait_s(self.cap)
                if self._p99_fn is None:
                    p99 = None
                elif self._p99_per_tenant:
                    p99 = self._p99_fn(tenant)
                else:
                    p99 = self._p99_fn()
                if projected > 0 and \
                        projected + float(p99 or 0.0) > self.slo_s:
                    eligible = self._fair_share_tenants_locked()
                    lowest: Optional[_Pending] = None
                    lowest_key: Optional[Tuple[str, str]] = None
                    for (t, e), cand in self._queues.items():
                        if t not in eligible or not cand.items:
                            continue
                        head = min(cand.items, key=lambda p: p.priority)
                        if lowest is None or \
                                (head.priority, head.t_enq) < \
                                (lowest.priority, lowest.t_enq):
                            lowest, lowest_key = head, (t, e)
                    if lowest is not None and lowest.priority < priority:
                        # evict the lowest-priority waiter in favor of
                        # this higher-priority arrival — fleet QoS: paid
                        # traffic rides through an overload
                        self._queues[lowest_key].items.remove(lowest)
                        victim = lowest
                        victim_tenant = lowest_key[0]
                    else:
                        shed_exc = ShedError(projected, reason="overload")
            if shed_exc is None:
                if tenant_depth == 0:
                    # empty→non-empty catch-up: an idle tenant must not
                    # bank service credit and then burst ahead of
                    # steadily-queued tenants
                    active = [self._service.get(t, 0.0)
                              for (t, _e), aq in self._queues.items()
                              if aq.items and t != tenant]
                    floor = min(active) if active else 0.0
                    self._service[tenant] = max(
                        self._service.get(tenant, 0.0), floor)
                q.items.append(_Pending(body, fut, now, int(priority),
                                        obs_trace.current_trace_id()))
                self._cv.notify()
            retry_hint = q.projected_wait_s(self.cap)
            # counted under the lock: submit runs on the HTTP thread
            # pool, and a bare += from two shedding threads can lose
            # an increment (the /status figure must track the counter)
            if victim is not None or shed_exc is not None:
                self.shed_count += 1
                shed_t = victim_tenant if victim is not None else tenant
                self.shed_by_tenant[shed_t] = \
                    self.shed_by_tenant.get(shed_t, 0) + 1
        if victim is not None:
            _SHED.labels(tenant=tenancy.get_registry().label(victim_tenant),
                         reason="evicted").inc()
            victim.fut.set_exception(
                ShedError(retry_hint, reason="evicted"))
        if shed_exc is not None:
            _SHED.labels(tenant=tenancy.get_registry().label(tenant),
                         reason=shed_exc.reason).inc()
            fut.set_exception(shed_exc)
        return fut

    # -- introspection ------------------------------------------------------
    @staticmethod
    def _engine_key(tenant: str, engine: str) -> str:
        """Status/snapshot queue name: bare ``engine`` for the default
        tenant (pre-tenancy readers keep their key), ``tenant/engine``
        otherwise."""
        return (engine if tenant == tenancy.DEFAULT_TENANT
                else f"{tenant}/{engine}")

    def depth(self, engine: Optional[str] = None,
              tenant: Optional[str] = None) -> int:
        with self._cv:
            return sum(
                len(q.items) for (t, e), q in self._queues.items()
                if (engine is None or e == engine)
                and (tenant is None or t == tenant))

    def depths_by_tenant(self) -> Dict[str, int]:
        """Queued admissions per tenant — the tenant-labeled
        ``pio_serve_queue_depth`` collector's feed.

        Deliberately lock-free: the flight recorder runs registry
        collectors at sampling Hz off its own thread, and taking the
        dispatch cv for an advisory depth snapshot contends with the
        serving hot path (it measurably moved the recorder-overhead
        p99 pin). ``len(deque)`` is GIL-atomic, a racy read only
        mis-states a depth by the in-flight delta, and the walk
        retries if an admission resizes the queue registry mid-walk.
        """
        while True:
            out: Dict[str, int] = {}
            try:
                # advisory scrape-time snapshot, racy by contract
                # (see docstring for why no lock)
                # pio-lint: disable=unguarded-shared-state
                for (t, _e), q in list(self._queues.items()):
                    out[t] = out.get(t, 0) + len(q.items)
                return out
            except RuntimeError:
                continue

    def rung(self, engine: str = "default",
             tenant: str = tenancy.DEFAULT_TENANT) -> int:
        with self._cv:
            q = self._queues.get((tenant, engine))
            return q.rung if q is not None else 1

    def stats(self) -> Dict[str, Any]:
        """Per-engine scheduler state for /status and the tests. The
        ``knobs`` block is the worker's announcement that it honors
        ``POST /knobs`` live refreshes (obs/knobs.py): the knob
        controller's front-door fan-out reads it to confirm support,
        and it carries the values currently in force. The ``tenants``
        block answers "which tenant is hurting" in one read."""
        with self._cv:
            return {
                "cap": self.cap,
                "shed": self.shed_count,
                "knobs": {
                    "supported": True,
                    "waitBoundS": self.wait_bound_s,
                    "sloS": self.slo_s,
                    "shedEnabled": self._shed,
                },
                "engines": {
                    self._engine_key(t, e): {
                        "depth": len(q.items), "rung": q.rung,
                        "ewmaWallS": round(q.ewma_wall, 6)}
                    for (t, e), q in self._queues.items()
                },
                "tenants": self._tenants_block_locked(),
            }

    def _tenants_block_locked(self) -> Dict[str, Any]:
        tenants = set(self._tenant_weights) | set(self._tenant_quotas) \
            | {t for (t, _e) in self._queues} | set(self.shed_by_tenant)
        block: Dict[str, Any] = {}
        for t in sorted(tenants):
            block[t] = {
                "depth": self._tenant_depth_locked(t),
                "shed": self.shed_by_tenant.get(t, 0),
                "weight": self._weight(t),
                "quota": self._tenant_quotas.get(t),
            }
        return block

    def snapshot(self) -> Dict[str, Any]:
        """The incident-capture state block: :meth:`stats` plus the
        admission policy and each queue's oldest-waiter age — what an
        operator needs to read a frozen bundle without the process."""
        now = self._clock()
        with self._cv:
            out: Dict[str, Any] = {
                "cap": self.cap,
                "shed": self.shed_count,
                "waitBoundS": self.wait_bound_s,
                "sloS": self.slo_s,
                "shedEnabled": self._shed,
                "stopped": self._stopped,
                "engines": {},
                "tenants": self._tenants_block_locked(),
            }
            for (t, e), q in self._queues.items():
                out["engines"][self._engine_key(t, e)] = {
                    "depth": len(q.items),
                    "rung": q.rung,
                    "ewmaWallS": round(q.ewma_wall, 6),
                    "inFlight": q.in_flight,
                    "oldestAgeS": (round(now - q.items[0].t_enq, 4)
                                   if q.items else None),
                }
            return out

    def apply_knobs(self) -> Dict[str, Any]:
        """Re-read the env-declared knobs captured at construction —
        the ladder cap, the wait bound, the serve objective, the shed
        toggle — and adopt them live. This is the worker-side half of
        the audited knob seam: only the ``POST /knobs`` route
        (servers/prediction_server.py) calls it, right after the knob
        controller's fan-out rewrites the env, so a running scheduler
        takes a new vector without restart. Rungs are clamped into the
        new cap; a shrunken cap therefore takes effect on the very next
        dispatch plan."""
        with self._cv:
            self.cap = ladder_cap()
            self.max_batch = self.cap
            self.wait_bound_s = max_wait_s()
            self.slo_s = serve_objective_s()
            self._shed = shed_enabled()
            for q in self._queues.values():
                q.rung = min(max(q.rung, 1), self.cap)
            return {
                "cap": self.cap,
                "waitBoundS": self.wait_bound_s,
                "sloS": self.slo_s,
                "shedEnabled": self._shed,
            }

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # -- dispatch loop ------------------------------------------------------
    def _pick_locked(self) -> Optional[Tuple[Tuple[str, str],
                                             _EngineQueue]]:
        """Weighted-fair across tenants, FIFO within one.

        Pick the non-empty tenant with the LOWEST virtual FINISH time
        for its head (normalized service — queries dispatched over
        weight — plus one head's worth of service, 1/weight), then that
        tenant's oldest head across its engines — so a flooding tenant
        advances its own service counter and yields the device back at
        its weight share, instead of monopolizing oldest-head order.
        The finish-time term breaks the post-catch-up tie in favor of
        the heavier tenant: a light high-weight tenant whose service
        was just floored to a flooder's pays one in-flight dispatch,
        not a full extra turn behind the flood. AGE BOUND OVERRIDE: a
        head that has waited past the wait bound is served first
        regardless of fairness — the no-query-waits-past-the-bound
        promise outranks the share schedule. SLOT CAPS: while ≥2
        tenants are contending, a tenant already holding its weighted
        share of dispatch slots is skipped entirely (even from the
        overdue override) so one thread stays free for the others —
        see _slot_caps_locked."""
        best: Optional[Tuple[Tuple[str, str], _EngineQueue]] = None
        overdue: Optional[Tuple[Tuple[str, str], _EngineQueue]] = None
        best_finish = 0.0
        now = self._clock()
        caps = self._slot_caps_locked(now)
        for key, q in self._queues.items():
            if not q.items:
                continue
            if caps is not None:
                cap = caps.get(key[0])
                if cap is not None and \
                        self._tenant_inflight_locked(key[0]) >= cap:
                    continue
            head_t = q.items[0].t_enq
            if self.wait_bound_s > 0 and now - head_t >= self.wait_bound_s:
                if overdue is None or head_t < overdue[1].items[0].t_enq:
                    overdue = (key, q)
            finish = (self._service.get(key[0], 0.0)
                      + 1.0 / self._weight(key[0]))
            if best is None or (finish, head_t) < \
                    (best_finish, best[1].items[0].t_enq):
                best = (key, q)
                best_finish = finish
        return overdue if overdue is not None else best

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and self._pick_locked() is None:
                    self._cv.wait(0.5)
                picked = self._pick_locked()
                if picked is None:
                    if self._stopped:
                        return
                    continue
                (tenant, engine), q = picked
                now = self._clock()
                oldest_age = now - q.items[0].t_enq
                take, q.rung = plan_dispatch(
                    len(q.items), q.rung, oldest_age, self.cap,
                    self.wait_bound_s)
                batch = [q.items.popleft() for _ in range(take)]
                q.in_flight += 1
                self._service[tenant] = self._service.get(tenant, 0.0) \
                    + take / self._weight(tenant)
            t0 = self._clock()
            for p in batch:
                _QUEUE_WAIT.observe(max(t0 - p.t_enq, 0.0))
            _BATCH_SIZE.observe(float(len(batch)))
            # exemplar seam: the dispatcher thread has no request
            # context, so re-install the OLDEST traced member's trace
            # ID for the duration of the dispatch — every histogram
            # observation the batch handler books (the per-query
            # latency histogram above all) can then carry a concrete
            # trace exemplar naming one real query of this batch
            ex_trace = next((p.trace_id for p in batch
                             if p.trace_id is not None), None)
            token = (obs_trace.set_current(ex_trace)
                     if ex_trace is not None else None)
            try:
                if self._pass_tenant:
                    results = self._handle_batch(
                        [p.body for p in batch], engine, tenant)
                elif self._pass_engine:
                    results = self._handle_batch(
                        [p.body for p in batch], engine)
                else:
                    results = self._handle_batch([p.body for p in batch])
            except Exception as exc:  # catastrophic: fail the whole batch
                results = [exc] * len(batch)
            finally:
                if token is not None:
                    obs_trace.reset_current(token)
            wall = self._clock() - t0
            with self._cv:
                q.note_wall(wall)
                q.in_flight -= 1
                # a slot-capped tenant just freed a slot: wake the idle
                # dispatcher the cap reserved, or it stalls a cv.wait
                self._cv.notify()
            for p, res in zip(batch, results):
                if isinstance(res, Exception):
                    p.fut.set_exception(res)
                else:
                    p.fut.set_result(res)
