"""Fleet front door — one address, health-checked routing, zero-downtime
rolling reload.

`bench_fleet`'s load generator used to spray worker processes directly:
no single address, per-process `/reload`, and a worker joining the fleet
paid the full XLA compile wall before it could serve (ROADMAP item 1).
This module is the serving control plane in front of N prediction
workers:

- **Queue-depth-aware placement.** Each worker's score is the front
  door's own in-flight count plus the worker's last reported scheduler
  backlog — piggybacked on every ``/queries.json`` response as
  ``X-PIO-Queue-Depth`` (servers/prediction_server.py) and refreshed by
  the probe loop from ``GET /`` between requests. Ties break
  least-recently-picked, so an idle fleet round-robins.

- **Per-worker health state machine.** Passive failure counting
  (transport errors and timeouts — never HTTP responses: a worker that
  ANSWERS is alive) plus active probes. ``eject_failures`` consecutive
  failures open the circuit; after a cooldown the prober sends a
  half-open trial and re-admits on success, doubling the cooldown on
  failure. A shedding worker is NOT ejected — its 503 + ``Retry-After``
  is the scheduler's overload contract (serving/scheduler.py ShedError)
  and passes through to the client verbatim; ejecting it would shift
  the same overload onto its peers (shed ≠ unhealthy).

- **Bounded single retry, hedging budgeted.** An idempotent query that
  dies in transport retries ONCE on a different worker, inside the
  request's overall deadline, and only while the retry token bucket —
  refilled by a fraction of successful requests — has budget. The
  budget caps retry amplification: when the whole fleet is failing,
  retries stop instead of doubling the offered load the scheduler is
  already shedding.

- **Rolling fleet-wide reload with connection draining.** One worker at
  a time: placement stops (DRAINING), in-flight requests finish,
  ``POST /reload`` runs the worker's own double-buffered warm-before-
  swap (the overlay's ``adopt_keys`` mechanism rides it), and the
  worker is re-admitted only after a live probe confirms it answers —
  so a fleet-wide model swap drops zero queries. Draining never starts
  while no OTHER healthy worker exists (bounded wait), so a
  degraded fleet reloads serially rather than going dark.

- **Elastic join.** Workers announce PORT only after their pow2 ladder
  is warm (tests/fleet_worker.py), and the shared persistent XLA
  compile cache (utils/compile_cache.py, ``PIO_COMPILE_CACHE`` at a
  fleet-shared directory) turns that warmup from a compile wall into a
  disk read — join-to-first-dispatch is seconds, measured by
  ``bench.py bench_frontdoor`` as ``frontdoor_join_to_first_dispatch_s``
  with the cold/warm delta recorded.

Exported series: ``pio_frontdoor_requests_total{worker,outcome}``
(``outcome="unauthorized"`` = accessKey rejected at the door),
``pio_frontdoor_retries_total``, ``pio_frontdoor_worker_healthy{worker}``,
``pio_frontdoor_drain_seconds``, plus the client-observed
``pio_query_latency_seconds{tenant}`` — list the front door in
``PIO_FLEET_TARGETS`` and the fleet ``/slo`` serve_p99 objective
evaluates what clients actually saw through the door, not just
per-worker dispatch walls (docs/observability.md;
docs/production.md "Fleet front door").
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import threading
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple
from urllib.parse import quote, urlencode

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.serving import tenancy
from incubator_predictionio_tpu.utils import times
from incubator_predictionio_tpu.utils.http import (
    HttpServer,
    Request,
    Response,
    Router,
)

logger = logging.getLogger(__name__)

#: per-worker outcome accounting. `worker` is BOUNDED: one label value
#: per fleet member (w0, w1, …, join-ordered), `outcome` is the enum
#: below — never a status code from the wire.
_REQUESTS = obs_metrics.REGISTRY.counter(
    "pio_frontdoor_requests_total",
    "front-door requests by worker and outcome (ok = 2xx/4xx "
    "passthrough; shed = worker 503 passthrough; upstream_error = "
    "worker 5xx passthrough; failed = transport failure not recovered; "
    "no_worker = no healthy worker to place on; unauthorized = query "
    "rejected at the door: unknown/disabled/missing accessKey while a "
    "tenant registry is configured)",
    labels=("worker", "outcome"))
_RETRIES = obs_metrics.REGISTRY.counter(
    "pio_frontdoor_retries_total",
    "transport-failed idempotent queries re-placed on another worker")
_HEALTHY = obs_metrics.REGISTRY.gauge(
    "pio_frontdoor_worker_healthy",
    "1 while the worker takes placements, 0 while ejected/draining",
    labels=("worker",))
_DRAIN_SECONDS = obs_metrics.REGISTRY.histogram(
    "pio_frontdoor_drain_seconds",
    "wall from placement stop to in-flight zero during a rolling reload")
#: the CLIENT-OBSERVED per-query wall: placement + worker roundtrip +
#: any retry, booked into the same family the workers book their batch
#: walls into — so a front door listed in PIO_FLEET_TARGETS makes the
#: fleet /slo serve_p99 objective evaluate what clients actually saw
#: (queueing at the door included), not just per-worker dispatch walls
#: TENANT-LABELED in lockstep with the workers' declaration of the same
#: family (servers/prediction_server.py — the shared registry raises on
#: a labelnames mismatch); values come only from the bounded registry
_FD_LATENCY = obs_metrics.REGISTRY.histogram(
    "pio_query_latency_seconds",
    "per-query serving wall (micro-batch members share the batch wall)",
    labels=("tenant",))

#: health states (module constants, not enum — they serialize into
#: /status JSON and tests compare strings)
HEALTHY = "healthy"
OPEN = "open"          # circuit open: ejected, cooling down
HALF_OPEN = "half_open"  # cooldown elapsed: probe decides
DRAINING = "draining"  # rolling reload: no new placements


@dataclasses.dataclass
class FrontDoorConfig:
    host: str = "127.0.0.1"
    port: int = 0
    #: consecutive TRANSPORT failures that open a worker's circuit
    eject_failures: int = 3
    #: first circuit-open cooldown; doubles per failed half-open probe
    open_cooldown_s: float = 2.0
    max_cooldown_s: float = 30.0
    #: active probe / depth-refresh cadence
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    #: overall per-request deadline (placement + attempts + the retry)
    request_timeout_s: float = 10.0
    #: per-attempt cap inside the deadline
    attempt_timeout_s: float = 5.0
    #: hedging budget: a retry costs one token; every successful
    #: request refills retry_refill tokens up to retry_budget tokens.
    #: At refill 0.1 the front door can amplify offered load by at most
    #: ~10% — bounded by construction, not by hope.
    retry_budget: float = 16.0
    retry_refill: float = 0.1
    #: rolling-reload choreography bounds
    drain_timeout_s: float = 30.0
    drain_capacity_wait_s: float = 30.0
    reload_timeout_s: float = 300.0
    #: idle keep-alive connections retained per worker (beyond the cap
    #: connections close after use instead of pooling)
    pool_size: int = 32
    #: authes the front door's own /reload + /fleet/* verbs AND is
    #: forwarded to each worker's /reload
    server_key: Optional[str] = None


class Worker:
    """One fleet member's routing state. All mutation happens on the
    front door's event loop (handlers + probe loop share it), so no
    lock; cross-thread readers (stats from the bench) see GIL-atomic
    snapshots of scalars."""

    __slots__ = ("name", "host", "port", "state", "fails", "open_until",
                 "cooldown_s", "in_flight", "depth", "requests",
                 "last_picked", "conns", "mips_tail", "mips_age_s")

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = port
        self.state = HEALTHY
        self.fails = 0
        self.open_until = 0.0
        self.cooldown_s = 0.0
        self.in_flight = 0
        self.depth = 0.0          # last reported pio_serve_queue_depth
        self.requests = 0         # successful placements (any response)
        self.last_picked = 0      # placement tie-break: LRU wins
        #: worker's MIPS lifecycle as of the last probe: virtual-id
        #: tail rows awaiting a daemon rebuild + oldest index age —
        #: the fleet-level "is churn outrunning the rebuild cadence"
        #: signal (docs/observability.md runbook)
        self.mips_tail = 0
        self.mips_age_s = 0.0
        #: idle keep-alive connections (reader, writer)
        self.conns: Deque[Tuple[asyncio.StreamReader,
                                asyncio.StreamWriter]] = deque()

    def load(self) -> float:
        return self.in_flight + max(self.depth, 0.0)

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "host": self.host, "port": self.port,
                "state": self.state, "inFlight": self.in_flight,
                "depth": self.depth, "requests": self.requests,
                "consecutiveFails": self.fails,
                "mipsTailVirtual": self.mips_tail,
                "mipsIndexAgeSec": self.mips_age_s}


class FrontDoor:
    """Async front-door router fanning one address across N workers."""

    def __init__(self, workers: Optional[List[Tuple[str, int]]] = None,
                 config: Optional[FrontDoorConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.config = config or FrontDoorConfig()
        self._clock = clock if clock is not None else times.monotonic
        self.workers: List[Worker] = []
        self._next_worker_id = 0
        #: names freed by removals, reused by later joins — the metric
        #: `worker` label set stays bounded by the PEAK fleet size even
        #: under elastic kill-and-replace churn (the registry has no
        #: series removal; an ever-incrementing name would mint a new
        #: series per replacement — the cardinality class pio-lint's
        #: metric-label-cardinality rule exists to prevent)
        self._free_names: List[str] = []
        self._pick_seq = 0
        self._retry_tokens = self.config.retry_budget
        self.counts: Dict[str, int] = {
            "ok": 0, "shed": 0, "upstream_error": 0, "failed": 0,
            "no_worker": 0, "retries": 0, "unauthorized": 0}
        self._reload_lock = asyncio.Lock()
        self._stopping = False
        self.http = HttpServer(self._build_router(), self.config.host,
                               self.config.port, name="frontdoor")
        for host, port in workers or []:
            self._add_worker_locked(host, port)

    # -- membership ---------------------------------------------------------
    def _add_worker_locked(self, host: str, port: int) -> Worker:
        if self._free_names:
            name = self._free_names.pop()
        else:
            name = f"w{self._next_worker_id}"
            self._next_worker_id += 1
        w = Worker(name, host, port)
        self.workers.append(w)
        _HEALTHY.labels(worker=w.name).set(1.0)
        logger.info("front door: worker %s joined at %s:%d", w.name,
                    host, port)
        return w

    def add_worker(self, host: str, port: int) -> str:
        """Thread-safe join: membership mutates on the event loop when
        one is running (the serving path reads it there); before
        startup it mutates directly. The worker is admitted HEALTHY —
        fleet workers announce their port only after ladder warmup —
        and the probe loop ejects it if that promise was a lie."""
        loop = self.http._loop
        if loop is None or not loop.is_running():
            return self._add_worker_locked(host, port).name
        fut = asyncio.run_coroutine_threadsafe(
            self._add_worker_async(host, port), loop)
        return fut.result(timeout=10)

    async def _add_worker_async(self, host: str, port: int) -> str:
        return self._add_worker_locked(host, port).name

    def remove_worker(self, name: str) -> bool:
        loop = self.http._loop
        if loop is None or not loop.is_running():
            return self._remove_worker_locked(name)
        return asyncio.run_coroutine_threadsafe(
            self._remove_worker_async(name), loop).result(timeout=60)

    async def _remove_worker_async(self, name: str) -> bool:
        w = self._worker(name)
        if w is None:
            return False
        await self._drain(w)
        return self._remove_worker_locked(name)

    def _remove_worker_locked(self, name: str) -> bool:
        w = self._worker(name)
        if w is None:
            return False
        self.workers.remove(w)
        _HEALTHY.labels(worker=w.name).set(0.0)
        self._free_names.append(w.name)
        for reader, writer in w.conns:
            try:
                writer.close()
            except Exception:
                pass
        w.conns.clear()
        return True

    def _worker(self, name: str) -> Optional[Worker]:
        for w in self.workers:
            if w.name == name:
                return w
        return None

    # -- health state machine ----------------------------------------------
    def _note_success(self, w: Worker) -> None:
        w.fails = 0
        w.requests += 1
        self._retry_tokens = min(
            self._retry_tokens + self.config.retry_refill,
            self.config.retry_budget)

    def _note_failure(self, w: Worker) -> None:
        """Passive transport failure. Only movement HEALTHY → OPEN
        happens here; recovery is the prober's job."""
        w.fails += 1
        if w.state == HEALTHY and w.fails >= self.config.eject_failures:
            self._open_circuit(w)

    def _open_circuit(self, w: Worker) -> None:
        w.state = OPEN
        w.cooldown_s = (min(w.cooldown_s * 2, self.config.max_cooldown_s)
                        if w.cooldown_s > 0 else self.config.open_cooldown_s)
        w.open_until = self._clock() + w.cooldown_s
        _HEALTHY.labels(worker=w.name).set(0.0)
        # a dead worker's pooled connections are dead too
        for reader, writer in w.conns:
            try:
                writer.close()
            except Exception:
                pass
        w.conns.clear()
        logger.warning("front door: circuit OPEN for %s (%d consecutive "
                       "failures; retry in %.1fs)", w.name, w.fails,
                       w.cooldown_s)

    def _readmit(self, w: Worker) -> None:
        w.state = HEALTHY
        w.fails = 0
        w.cooldown_s = 0.0
        _HEALTHY.labels(worker=w.name).set(1.0)
        logger.info("front door: worker %s re-admitted", w.name)

    async def _probe_pass(self) -> None:
        """One probe cycle: half-open trials for cooled-down OPEN
        circuits, depth refresh for healthy-but-idle workers. Probes
        run CONCURRENTLY — serial probing would let one unreachable
        worker's timeout delay every peer's half-open re-admission by
        a whole probe_timeout_s per dead worker."""
        now = self._clock()

        async def one(w: Worker) -> None:
            if w.state == OPEN and now >= w.open_until:
                w.state = HALF_OPEN
            if w.state == HALF_OPEN:
                ok = await self._probe(w)
                if w.state != HALF_OPEN:
                    # a drain/remove raced the probe await — the reload
                    # choreography owns the state now; re-admitting
                    # here would resume placements mid-drain
                    return
                if ok:
                    self._readmit(w)
                else:
                    self._open_circuit(w)
            elif w.state == HEALTHY and w.in_flight == 0:
                # idle workers never piggyback a depth — refresh it
                # actively, and count a probe failure like a passive
                # one so a worker that died QUIETLY still ejects
                # instead of eating the next burst's first queries.
                # A probe SUCCESS clears the counter like a served
                # query does — the eject contract is CONSECUTIVE
                # failures, and isolated timeouts hours apart must
                # never accumulate into a spurious ejection.
                if await self._probe(w):
                    w.fails = 0
                else:
                    self._note_failure(w)

        await asyncio.gather(*(one(w) for w in list(self.workers)))

    async def _probe(self, w: Worker) -> bool:
        """GET / on the worker; refreshes the reported queue depth from
        the status page's scheduler block. True = the worker answers."""
        try:
            status, _hdrs, body = await self._roundtrip(
                w, "GET", "/", {}, b"", self.config.probe_timeout_s)
        except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError):
            return False
        if status != 200:
            return False
        try:
            info = json.loads(body)
            sched = info.get("scheduler") or {}
            w.depth = float(sum(
                e.get("depth", 0) for e in
                (sched.get("engines") or {}).values()))
        except (ValueError, AttributeError, TypeError):
            w.depth = 0.0
            return True
        try:
            indexes = (info.get("mips") or {}).get("indexes") or []
            w.mips_tail = int(sum(
                i.get("tailVirtual", 0) for i in indexes))
            w.mips_age_s = float(max(
                (i.get("ageSec", 0.0) for i in indexes), default=0.0))
        except (ValueError, AttributeError, TypeError):
            w.mips_tail, w.mips_age_s = 0, 0.0
        return True

    async def _probe_loop(self) -> None:
        while not self._stopping:
            try:
                await self._probe_pass()
            except Exception:
                logger.exception("front door probe pass failed")
            await asyncio.sleep(self.config.probe_interval_s)

    # -- placement ----------------------------------------------------------
    def _pick(self, exclude: Tuple[str, ...] = ()) -> Optional[Worker]:
        """Least-loaded healthy worker (front-door in-flight + reported
        scheduler backlog), ties to the least recently picked."""
        best: Optional[Worker] = None
        for w in self.workers:
            if w.state != HEALTHY or w.name in exclude:
                continue
            if best is None or (w.load(), w.last_picked) < (
                    best.load(), best.last_picked):
                best = w
        if best is not None:
            self._pick_seq += 1
            best.last_picked = self._pick_seq
        return best

    # -- transport ----------------------------------------------------------
    async def _checkout(self, w: Worker, timeout: float):
        while w.conns:
            reader, writer = w.conns.popleft()
            if not writer.is_closing():
                return reader, writer
            writer.close()
        return await asyncio.wait_for(
            asyncio.open_connection(w.host, w.port),
            min(self.config.probe_timeout_s, timeout))

    async def _roundtrip(self, w: Worker, method: str, path: str,
                         headers: Dict[str, str], body: bytes,
                         timeout: float
                         ) -> Tuple[int, Dict[str, str], bytes]:
        """One HTTP/1.1 request on a pooled keep-alive connection.
        ``timeout`` bounds the WHOLE roundtrip — connect, send, headers
        and body share one budget, so a worker that drips its response
        cannot stretch an attempt to a multiple of the cap. Transport
        failures close the connection and propagate — the caller
        classifies them (health, retry)."""
        t_end = self._clock() + timeout

        def remaining() -> float:
            return max(t_end - self._clock(), 0.01)

        reader, writer = await self._checkout(w, remaining())
        try:
            lines = [f"{method} {path} HTTP/1.1", f"Host: {w.host}"]
            for k, v in headers.items():
                lines.append(f"{k}: {v}")
            lines.append(f"Content-Length: {len(body)}")
            writer.write(("\r\n".join(lines) + "\r\n\r\n")
                         .encode("latin-1") + body)
            await asyncio.wait_for(writer.drain(), remaining())
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), remaining())
            head_lines = head.decode("latin-1").split("\r\n")
            try:
                status = int(head_lines[0].split(" ", 2)[1])
            except (IndexError, ValueError) as e:
                # not HTTP (a recycled port, a garbled banner): classify
                # as a TRANSPORT failure so the caller's health/retry
                # machinery engages instead of a raw exception leaking
                # a nonshed 500 to the client
                raise OSError(
                    f"malformed HTTP response from {w.name}: "
                    f"{head_lines[0]!r}") from e
            resp_headers: Dict[str, str] = {}
            for line in head_lines[1:]:
                name, _, value = line.partition(":")
                if _:
                    resp_headers[name.strip().lower()] = value.strip()
            try:
                clen = int(resp_headers.get("content-length", "0") or "0")
            except ValueError as e:
                raise OSError(
                    f"malformed Content-Length from {w.name}") from e
            resp_body = (await asyncio.wait_for(
                reader.readexactly(clen), remaining()) if clen else b"")
        except BaseException:
            writer.close()
            raise
        if resp_headers.get("connection", "keep-alive").lower() == "close" \
                or len(w.conns) >= self.config.pool_size:
            # bounded idle pool: a concurrency burst must not pin its
            # peak's worth of sockets per worker forever
            writer.close()
        else:
            w.conns.append((reader, writer))
        return status, resp_headers, resp_body

    # -- the request path ---------------------------------------------------
    async def handle_query(self, request: Request) -> Response:
        """Place /queries.json on a worker; bounded single retry to a
        DIFFERENT worker on transport failure (idempotent — a query
        reads model state), under the overall request deadline.

        Tenancy: the door authenticates the accessKey against the same
        bounded registry the workers read (serving/tenancy.py) and
        ROUTES by tenant only in its bookkeeping — placement and
        circuit state stay transport-scoped (a worker is healthy or
        not; which tenant a query belongs to never changes where it can
        run). The query string travels verbatim, so the worker re-
        authenticates the same key."""
        try:
            tenant = tenancy.get_registry().authenticate(request)
        except tenancy.TenantAuthError as e:
            self.counts["unauthorized"] += 1
            _REQUESTS.labels(worker="none", outcome="unauthorized").inc()
            return Response(401, {"message": e.message})
        return await self.forward(request, "/queries.json",
                                  tenant=tenant)

    async def forward(self, request: Request,
                      upstream_path: Optional[str] = None,
                      tenant: Optional[str] = None) -> Response:
        """Place one request on a worker under the full door
        discipline — least-loaded pick, circuit breaker, bounded
        token-bucket retry to a DIFFERENT worker, overall deadline.
        The client's query string travels verbatim (accessKey auth at
        the workers depends on it)."""
        t_start = self._clock()
        deadline = t_start + self.config.request_timeout_s
        path = upstream_path if upstream_path is not None else request.path
        if request.query:
            path += "?" + urlencode(request.query)
        fwd_headers = {"Content-Type": request.headers.get(
            "content-type", "application/json")}
        auth = request.headers.get("authorization")
        if auth is not None:
            # a tenant key sent via HTTP Basic lives in this header,
            # not the query string — the worker re-authenticates it
            fwd_headers["Authorization"] = auth
        prio = request.headers.get("x-pio-priority")
        if prio is not None:
            fwd_headers["X-PIO-Priority"] = prio
        # trace contract: the ambient trace ID (accepted or minted by
        # our own HTTP layer) plus THIS hop's span as the parent, so
        # worker span lines link under the front door's
        fwd_headers.update(obs_trace.client_headers())
        tried: Tuple[str, ...] = ()
        while True:
            w = self._pick(exclude=tried)
            if w is None:
                self.counts["no_worker"] += 1
                _REQUESTS.labels(worker="none", outcome="no_worker").inc()
                # no healthy capacity is an overload-class condition:
                # same 503 + Retry-After contract as a scheduler shed,
                # so well-behaved clients back off instead of hammering
                return Response(
                    503, {"message": "No healthy serving worker."},
                    headers={"Retry-After": "1"})
            timeout = min(self.config.attempt_timeout_s,
                          max(deadline - self._clock(), 0.05))
            w.in_flight += 1
            try:
                status, hdrs, body = await self._roundtrip(
                    w, request.method, path, fwd_headers,
                    request.body, timeout)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as e:
                self._note_failure(w)
                peer_exists = any(
                    o.state == HEALTHY and o.name != w.name
                    for o in self.workers)
                if (not tried and peer_exists
                        and self._retry_tokens >= 1.0
                        and self._clock() < deadline
                        and not self._stopping):
                    tried = (w.name,)
                    self._retry_tokens -= 1.0
                    self.counts["retries"] += 1
                    _RETRIES.inc()
                    logger.info("front door: retrying query on another "
                                "worker after %s failed (%r)", w.name, e)
                    continue
                self.counts["failed"] += 1
                _REQUESTS.labels(worker=w.name, outcome="failed").inc()
                return Response(
                    504 if isinstance(e, asyncio.TimeoutError) else 502,
                    {"message": f"upstream worker failed ({e!r})"})
            finally:
                w.in_flight -= 1
            # any HTTP response means the worker is alive
            self._note_success(w)
            depth = hdrs.get("x-pio-queue-depth")
            if depth is not None:
                try:
                    w.depth = float(depth)
                except ValueError:
                    pass
            if status == 503:
                # the scheduler's shed contract passes through verbatim
                # and is NOT a health event (shed ≠ unhealthy) — and
                # never retried: re-offering shed load to a peer would
                # amplify the very overload the fleet is shedding
                self.counts["shed"] += 1
                _REQUESTS.labels(worker=w.name, outcome="shed").inc()
            elif status >= 500:
                self.counts["upstream_error"] += 1
                _REQUESTS.labels(worker=w.name,
                                 outcome="upstream_error").inc()
            else:
                self.counts["ok"] += 1
                _REQUESTS.labels(worker=w.name, outcome="ok").inc()
                # served queries only: a shed answers in microseconds
                # and booking it would deflate the very p99 the shed
                # exists to protect (same rule as the workers, whose
                # scheduler books served batches only). The tenant
                # child comes from the bounded registry (lint contract)
                _FD_LATENCY.labels(
                    tenant=tenancy.get_registry().label(tenant)
                ).observe(max(self._clock() - t_start, 0.0))
            out_headers = {}
            for h in ("retry-after", "x-pio-queue-depth"):
                if h in hdrs:
                    out_headers[h.title()] = hdrs[h]
            return Response(
                status, body=body,
                content_type=hdrs.get("content-type",
                                      "application/json; charset=UTF-8"),
                headers=out_headers)

    # -- rolling reload -----------------------------------------------------
    async def _drain(self, w: Worker) -> int:
        """Stop placement, wait for in-flight zero → stuck count (0 on
        every healthy drain; >0 only past drain_timeout_s)."""
        t0 = self._clock()
        w.state = DRAINING
        _HEALTHY.labels(worker=w.name).set(0.0)
        while w.in_flight > 0 and \
                self._clock() - t0 < self.config.drain_timeout_s:
            await asyncio.sleep(0.02)
        _DRAIN_SECONDS.observe(max(self._clock() - t0, 0.0))
        return w.in_flight

    async def rolling_reload_async(
            self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """Drain → /reload → verify-warm → re-admit, one worker at a
        time. The per-worker /reload is the existing double-buffered
        warm-before-swap (prediction_server.load_models) — the old
        model serves its drained peers' traffic until the new one is
        query-ready, so the fleet-wide swap drops zero queries.

        ``tenant`` scopes each worker's reload to ONE co-resident
        deploy (``/reload?tenant=X``): the other tenants' serving state
        is never swapped, and the drain/readmit choreography is the
        only cross-tenant effect (transport-scoped, as placement always
        is)."""
        async with self._reload_lock:
            out: Dict[str, Any] = {"workers": len(self.workers),
                                   "reloaded": 0, "dropped": 0,
                                   "failed": [], "drainS": [],
                                   "tenant": tenant}
            key = self.config.server_key
            qs = []
            if key:
                qs.append(f"accessKey={quote(key, safe='')}")
            if tenant:
                qs.append(f"tenant={quote(tenant, safe='')}")
            path = "/reload" + ("?" + "&".join(qs) if qs else "")
            # trace contract: a reload triggered by a traced request
            # (the freshness controller's POST /reload, an operator's
            # curl with a trace header) forwards its trace ID + this
            # hop's span to every worker reload — the decision →
            # rolling-swap tree scripts/trace_stitch.py --decisions
            # reconstructs. Captured once here: every worker's swap
            # belongs to the ONE choreography that caused it.
            reload_headers = dict(obs_trace.client_headers())
            for name in [w.name for w in list(self.workers)]:
                w = self._worker(name)
                if w is None or w.state not in (HEALTHY, HALF_OPEN):
                    out["failed"].append(name)
                    continue
                # never drain the LAST healthy worker: wait (bounded)
                # for a peer, and if none appears SKIP this worker —
                # a reload must degrade to "one worker still on the old
                # model" (re-run it later), never to a dark fleet
                t_wait = self._clock()
                while not any(o.state == HEALTHY for o in self.workers
                              if o is not w) and \
                        self._clock() - t_wait < \
                        self.config.drain_capacity_wait_s:
                    await asyncio.sleep(0.1)
                if not any(o.state == HEALTHY for o in self.workers
                           if o is not w):
                    logger.warning(
                        "front door: skipping reload of %s — no other "
                        "healthy worker to carry traffic", name)
                    out["failed"].append(name)
                    continue
                t0 = self._clock()
                stuck = await self._drain(w)
                out["dropped"] += stuck
                try:
                    status, _hdrs, _body = await self._roundtrip(
                        w, "POST", path, reload_headers, b"",
                        self.config.reload_timeout_s)
                except (OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError) as e:
                    logger.warning("front door: reload of %s failed (%r)",
                                   name, e)
                    status = None
                # re-admit only when warm: /reload returns after the
                # new model's ladder warmed (warm-before-swap), and a
                # live probe confirms the serving plane answers
                if status == 200 and await self._probe(w):
                    self._readmit(w)
                    out["reloaded"] += 1
                    out["drainS"].append(round(self._clock() - t0, 3))
                else:
                    self._open_circuit(w)
                    out["failed"].append(name)
            return out

    # -- fleet knob fan-out -------------------------------------------------
    async def knobs_fanout_async(self, body: bytes) -> Dict[str, Any]:
        """Fan the knob controller's vector (obs/knobs.py) to every
        worker's ``POST /knobs``, one at a time under the rolling-
        reload serialization (the same ``_reload_lock`` — a vector
        landing mid-swap would leave half the fleet on each setting).
        Unlike a reload, no drain is needed: every registered knob is a
        call-time env read, so a worker applies the vector between two
        dispatches without dropping a query. Trace headers are captured
        once so every worker hop lands under the ONE decision span that
        caused the fan-out."""
        async with self._reload_lock:
            out: Dict[str, Any] = {"workers": len(self.workers),
                                   "applied": 0, "failed": []}
            key = self.config.server_key
            path = "/knobs" + (
                f"?accessKey={quote(key, safe='')}" if key else "")
            knob_headers = {**obs_trace.client_headers(),
                            "Content-Type": "application/json"}
            results: Dict[str, Any] = {}
            for name in [w.name for w in list(self.workers)]:
                w = self._worker(name)
                if w is None:
                    out["failed"].append(name)
                    continue
                try:
                    status, _hdrs, resp = await self._roundtrip(
                        w, "POST", path, knob_headers, body,
                        self.config.attempt_timeout_s)
                except (OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError) as e:
                    logger.warning(
                        "front door: knob apply on %s failed (%r)",
                        name, e)
                    out["failed"].append(name)
                    continue
                if status == 200:
                    out["applied"] += 1
                    try:
                        results[name] = json.loads(
                            resp.decode("utf-8"))
                    except ValueError:
                        results[name] = None
                else:
                    # a worker that rejects the vector (bad key,
                    # unregistered env) fails the fan-out entry but
                    # never the door: the controller reads the outcome
                    # and keeps its old belief
                    logger.warning(
                        "front door: knob apply on %s rejected "
                        "(HTTP %s)", name, status)
                    out["failed"].append(name)
            out["results"] = results
            return out

    def rolling_reload(self, timeout: Optional[float] = None,
                       tenant: Optional[str] = None) -> Dict[str, Any]:
        """Synchronous wrapper for callers off the loop (bench, CLI)."""
        loop = self.http._loop
        if loop is None or not loop.is_running():
            raise RuntimeError("front door is not running")
        fut = asyncio.run_coroutine_threadsafe(
            self.rolling_reload_async(tenant=tenant), loop)
        return fut.result(timeout=timeout)

    # -- introspection ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "workers": [w.to_json() for w in self.workers],
            "counts": dict(self.counts),
            "retryTokens": round(self._retry_tokens, 2),
        }

    def _check_key(self, request: Request) -> Optional[Response]:
        key = self.config.server_key
        if key is not None and request.query.get("accessKey") != key:
            return Response(401, {"message": "Invalid accessKey."})
        return None

    # -- routes -------------------------------------------------------------
    def _build_router(self) -> Router:
        from incubator_predictionio_tpu.obs.http import (
            add_metrics_route,
            add_recorder_route,
        )

        r = Router()
        r.add("POST", "/queries.json", self.handle_query)

        @r.get("/")
        def status(request: Request) -> Response:
            return Response(200, {"status": "frontdoor", **self.stats()})

        @r.post("/reload")
        async def reload_route(request: Request) -> Response:
            denied = self._check_key(request)
            if denied is not None:
                return denied
            return Response(200, await self.rolling_reload_async(
                tenant=request.query.get("tenant") or None))

        @r.post("/knobs")
        async def post_knobs(request: Request) -> Response:
            denied = self._check_key(request)
            if denied is not None:
                return denied
            return Response(
                200, await self.knobs_fanout_async(request.body or b""))

        @r.post("/fleet/join")
        async def join(request: Request) -> Response:
            denied = self._check_key(request)
            if denied is not None:
                return denied
            spec = request.json()
            name = self._add_worker_locked(spec["host"],
                                           int(spec["port"])).name
            return Response(200, {"worker": name})

        @r.post("/fleet/remove")
        async def remove(request: Request) -> Response:
            denied = self._check_key(request)
            if denied is not None:
                return denied
            name = request.json().get("worker", "")
            ok = await self._remove_worker_async(name)
            return Response(200 if ok else 404, {"removed": bool(ok)})

        add_metrics_route(r)
        # GET /recorder: the door's own pre-breach history (its client-
        # observed latency histogram is the fleet serve_p99 signal)
        add_recorder_route(r)
        return r

    # -- lifecycle ----------------------------------------------------------
    def start_background(self) -> int:
        port = self.http.start_background()
        loop = self.http._loop
        assert loop is not None

        def _spawn_probe() -> None:
            asyncio.ensure_future(self._probe_loop())

        loop.call_soon_threadsafe(_spawn_probe)
        logger.info("front door listening on %s:%d over %d workers",
                    self.config.host, port, len(self.workers))
        return port

    def stop(self) -> None:
        self._stopping = True
        self.http.stop()


class IngestFrontDoor(FrontDoor):
    """The WRITE-side front door: one address spraying event POSTs
    across N event-server writer processes (each with its own writer
    shards in the shared log) under the exact same door discipline the
    query door gives the read path — health-checked least-loaded
    placement, circuit breaker, token-bucket-bounded single retry, and
    zero-downtime rolling writer reload (``POST /reload`` drains one
    writer at a time while its peers absorb the stream, the planet-
    scale-ingest soak's zero-dropped-events leg).

    Delivery is AT-LEAST-ONCE under retry: a transport failure after
    the request body went out may retry an event that the dead writer
    already committed. That is the standard ingest-pipeline contract —
    a duplicate interaction row nudges a count, a dropped one silently
    loses signal — and the retry budget bounds the amplification.
    Clients that need exactly-once send their own event ids and
    deduplicate downstream."""

    #: event-ingest routes forwarded verbatim (path + query string —
    #: accessKey auth happens at the workers). ``/batches/events.json``
    #: is the reference's batch alias; both spellings land on the same
    #: native one-parse-per-batch path at the event server.
    INGEST_PATHS = ("/events.json", "/batch/events.json",
                    "/batches/events.json")

    def _build_router(self) -> Router:
        r = super()._build_router()
        for p in self.INGEST_PATHS:
            r.add("POST", p, self._ingest_handler(p))
        return r

    def _ingest_handler(self, upstream_path: str):
        async def handle(request: Request) -> Response:
            return await self.forward(request, upstream_path)

        return handle
