"""EventServer — REST event collection.

Route/contract parity with data/.../api/EventServer.scala:148-530 on :7070:

- ``GET  /``                        → ``{"status": "alive"}``
- ``POST /events.json``             → 201 ``{"eventId": ...}``
- ``GET  /events/<id>.json``        → 200 event | 404
- ``DELETE /events/<id>.json``      → 200 ``{"message": "Found"}`` | 404
- ``GET  /events.json``             → query (startTime/untilTime/entityType/
  entityId/event/targetEntityType/targetEntityId/limit/reversed)
- ``POST /batch/events.json``       → ≤50 events, per-event status list
- ``GET  /stats.json``              → ingest counters (with ``--stats``)
- ``POST /webhooks/<name>.json``    → JSON connector ingest (+ GET probe)
- ``POST /webhooks/<name>.form``    → form connector ingest (+ GET probe)
- ``GET  /plugins.json`` and ``/plugins/...`` plugin passthrough

Auth (EventServer.scala:93-131): ``accessKey`` query param (with optional
``channel``), or HTTP Basic where the username is the access key. 401
missing/invalid key; 401 invalid channel. Per-event allowed-names check
(:275) → 403.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import logging
from typing import Any, Dict, Optional, Tuple

from incubator_predictionio_tpu.data import webhooks
from incubator_predictionio_tpu.data.event import Event, EventValidationError
from incubator_predictionio_tpu.data.storage import (
    AmbiguousWriteError,
    Storage,
    UnsupportedMethodError,
)
from incubator_predictionio_tpu.data.webhooks import ConnectorError
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs.http import (
    add_metrics_route,
    add_recorder_route,
)
from incubator_predictionio_tpu.servers.plugins import EventInfo, PluginContext
from incubator_predictionio_tpu.servers.stats import Stats
from incubator_predictionio_tpu.data.storage.base import UNSET as _UNSET_Q
from incubator_predictionio_tpu.utils.http import (
    HttpError,
    HttpServer,
    Request,
    Response,
    Router,
)
from incubator_predictionio_tpu.utils.times import parse_iso8601

logger = logging.getLogger(__name__)

#: EventServer.scala:71
MAX_EVENTS_PER_BATCH = 50

#: per-EVENT ingest outcomes (the request-level counters live in the
#: shared HTTP layer): every booked event — accepted or rejected — adds
#: one here, labeled by route pattern and status, FEEDING the
#: reference-parity per-app hourly window in /stats.json, not
#: replacing it (these never rotate; scope = process lifetime)
_INGEST_EVENTS = obs_metrics.REGISTRY.counter(
    "pio_ingest_events_total",
    "events booked by the event server, by route pattern and status",
    labels=("route", "status"))
#: batch-request shape: how many events each /batch/events.json request
#: carried (the group-commit/columnar amortization depends on it)
_INGEST_BATCH_SIZE = obs_metrics.REGISTRY.histogram(
    "pio_ingest_batch_size",
    "events per POST /batch/events.json request",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))


@dataclasses.dataclass
class EventServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7070
    stats: bool = False
    #: batch-route size cap. The default is the reference's wire contract
    #: (EventServer.scala:71 — 50 events per request); bulk loaders
    #: pointing at the columnar fast path can raise it (`pio eventserver
    #: --batch-cap N`) — a 500-event uniform batch amortizes the HTTP +
    #: JSON framing 10× further. Raising it changes the REST contract for
    #: THIS server only; SDK clients built against the reference keep
    #: working either way.
    max_batch: int = MAX_EVENTS_PER_BATCH


@dataclasses.dataclass(frozen=True)
class AuthData:
    """EventServer.scala:83 AuthData."""

    app_id: int
    channel_id: Optional[int]
    events: Tuple[str, ...]


class AuthError(HttpError):
    """401/403 rejection, converted to a JSON response by the http layer."""


class EventServer:
    def __init__(
        self,
        config: Optional[EventServerConfig] = None,
        plugin_context: Optional[PluginContext] = None,
    ):
        self.config = config or EventServerConfig()
        config = self.config
        self.events = Storage.get_events()
        self.access_keys = Storage.get_meta_data_access_keys()
        self.channels = Storage.get_meta_data_channels()
        self.stats = Stats()
        self.plugin_context = plugin_context or PluginContext()
        self.router = self._build_router()
        self.http = HttpServer.from_conf(self.router, config.ip, config.port,
                                         name="event")

    # -- auth (EventServer.scala:93-131) ------------------------------------
    def _authenticate(self, request: Request) -> AuthData:
        key = request.query.get("accessKey")
        channel = request.query.get("channel")
        if key is None:
            auth = request.headers.get("authorization", "")
            if auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[6:]).decode("utf-8")
                    key = decoded.strip().split(":")[0]
                except Exception:
                    raise AuthError(401, "Invalid accessKey.")
        if not key:
            raise AuthError(401, "Missing accessKey.")
        k = self.access_keys.get(key)
        if k is None:
            raise AuthError(401, "Invalid accessKey.")
        channel_id = None
        if channel is not None:
            channel_map = {
                c.name: c.id for c in self.channels.get_by_appid(k.appid)
            }
            if channel not in channel_map:
                raise AuthError(401, f"Invalid channel '{channel}'.")
            channel_id = channel_map[channel]
        return AuthData(k.appid, channel_id, tuple(k.events))

    def _check_allowed(self, auth: AuthData, event_name: str) -> None:
        if auth.events and event_name not in auth.events:
            raise AuthError(403, f"{event_name} events are not allowed")

    def _batch_fast_path(self, auth: AuthData, items) -> Optional[Response]:
        """Uniform batch → columnar insert, straight from the JSON docs.

        Returns None to hand the batch to the generic per-event path
        (non-uniform shape, or a storage failure — the generic path's
        bulk-then-retry semantics then apply from scratch). Per-event
        response isolation is preserved trivially: the gate guarantees a
        uniform event name, so the allowed-names check has one answer
        for every slot."""
        from incubator_predictionio_tpu.data.storage.base import (
            uniform_interactions_from_docs,
        )

        fast = uniform_interactions_from_docs(items)
        if fast is None:
            return None
        return self._columnar_fast_response(auth, fast, len(items))

    _BATCH_ROUTE = "/batch/events.json"

    def _columnar_fast_response(self, auth: AuthData, fast,
                                n: int) -> Optional[Response]:
        """Post-gate leg shared by the doc-level and native-body fast
        paths: allowed-names check, one columnar insert, booking, and
        direct response rendering. Returns None to hand the batch to the
        generic path (storage failure — its bulk-then-retry semantics
        then apply from scratch)."""
        inter, etype, tetype, name, vprop, times = fast
        try:
            self._check_allowed(auth, name)
        except AuthError as e:
            for _ in range(n):
                self._book(auth, e.status, name, route=self._BATCH_ROUTE)
            return Response(200, [
                {"status": e.status, "message": e.message}] * n)
        try:
            ids = self.events.insert_interactions(
                inter, auth.app_id, auth.channel_id, entity_type=etype,
                target_entity_type=tetype, event_name=name,
                value_prop=vprop, times=times)
        except UnsupportedMethodError:
            # a remote box without a columnar write path answers this
            # (once — the proxy caches it); stay on the generic path for
            # the rest of the process, quietly
            self._columnar_unsupported = True
            logger.info(
                "event store has no columnar insert; batch fast path off")
            return None
        except AmbiguousWriteError as e:
            # the remote write MAY have been applied (response lost after
            # the request hit the wire) — re-inserting via the generic
            # path would duplicate the whole batch, so surface the
            # ambiguity instead; the client decides whether to re-POST
            logger.warning("columnar batch insert ambiguous: %s", e)
            return Response(500, {"message": str(e)})
        except Exception:
            logger.exception(
                "columnar batch insert failed; using the generic path")
            return None
        for _ in range(n):
            self._book(auth, 201, name, route=self._BATCH_ROUTE)
        # ids are our own 32-hex strings: render the uniform-status body
        # directly (no json.dumps tree walk on the hot path)
        body = ('[' + ",".join(
            '{"status":201,"eventId":"%s"}' % i for i in ids) + ']')
        return Response(200, body=body.encode("ascii"))

    # -- single-event insert pipeline ---------------------------------------
    def _sniff(self, info: "EventInfo") -> None:
        for sniffer in self.plugin_context.input_sniffers.values():
            try:
                sniffer.process(info, self.plugin_context)
            except Exception:
                logger.exception("input sniffer failed")

    def _insert(self, auth: AuthData, event: Event) -> str:
        """Allowed-names check + blocker veto + insert + sniffers.

        Validation errors surface as 400 from the *parse* step before this is
        called; exceptions here (blocker vetoes, storage failures) are server
        errors — 500, matching the reference's recover path
        (EventServer.scala:409-412).
        """
        self._check_allowed(auth, event.event)
        info = EventInfo(auth.app_id, auth.channel_id, event)
        for blocker in self.plugin_context.input_blockers.values():
            blocker.process(info, self.plugin_context)  # may raise to veto
        event_id = self.events.insert(event, auth.app_id, auth.channel_id)
        self._sniff(info)
        return event_id

    def _ingest(self, auth: AuthData, event: Event,
                route: str = "/events.json") -> Response:
        """Guarded insert shared by /events.json and the webhook routes so
        403/500 outcomes get identical responses and stats booking."""
        try:
            event_id = self._insert(auth, event)
        except AuthError as e:
            self._book(auth, e.status, event.event, route=route)
            raise
        except Exception as e:
            self._book(auth, 500, event.event, route=route)
            return Response(500, {"message": str(e)})
        self._book(auth, 201, event.event, route=route)
        return Response(201, {"eventId": event_id})

    @staticmethod
    def _parse_event(item: Any) -> Event:
        """JSON → validated Event; any failure here is a 400."""
        from incubator_predictionio_tpu.data.event import validate_event

        event = Event.from_jsonable(item)
        validate_event(event)
        return event

    def _book(self, auth: AuthData, status: int, event_name: str,
              route: str = "/events.json") -> None:
        # registry counter always (process-wide, label-bounded by route
        # pattern + status); the per-app/per-event-name hourly window
        # stays behind --stats, exactly the reference contract
        _INGEST_EVENTS.labels(route=route, status=str(status)).inc()
        if self.config.stats:
            self.stats.update(auth.app_id, status, event_name)

    # -- routes -------------------------------------------------------------
    def _build_router(self) -> Router:
        r = Router()

        @r.get("/")
        def alive(request: Request) -> Response:
            return Response(200, {"status": "alive"})

        def _register_post(pattern: str, handler, *,
                           prefer_pool: bool = False) -> None:
            """Ingest hot-path dispatch policy: FAST_LOCAL backends
            (in-process index + native append, sub-ms inserts — memory,
            cpplog) run INLINE on the event loop; the executor round trip
            a sync handler pays (submit → pool thread → self-pipe wakeup)
            costs more than the insert itself and halves single-box REST
            throughput. Networked/disk-fsync backends keep the thread
            pool so a slow insert never stalls every connection — and so
            do requests while input plugins are registered (a blocker/
            sniffer may do arbitrary I/O; decided per REQUEST, since
            plugins can be present at startup only).

            Over a GROUP_COMMIT backend, EVERY ingest route goes to the
            pool (``prefer_pool``): pool threads let N in-flight batches
            merge into one native append, and the native call drops the
            GIL so the next request's Python runs under the previous
            request's C++ write. Crucially this must cover the
            single-event and generic-batch legs too, not just the batch
            fast path — those take the same storage lock, and an inline
            handler blocking the event loop on a lock a pool thread
            holds across a merged append would freeze every connection."""
            if getattr(self.events, "FAST_LOCAL", False) and not prefer_pool:
                async def dispatch(request, _h=handler):
                    ctx = self.plugin_context
                    if ctx.input_blockers or ctx.input_sniffers:
                        import asyncio

                        loop = asyncio.get_running_loop()
                        return await loop.run_in_executor(None, _h, request)
                    return _h(request)

                r.add("POST", pattern, dispatch)
            else:
                r.add("POST", pattern, handler)

        def create_event(request: Request) -> Response:
            auth = self._authenticate(request)
            try:
                event = self._parse_event(request.json())
            except (ValueError, EventValidationError) as e:
                self._book(auth, 400, "<error>")
                return Response(400, {"message": str(e)})
            return self._ingest(auth, event)

        # one policy for every ingest route: a group-committing backend
        # moves them ALL to the pool (see _register_post docstring)
        pool_ingest = getattr(self.events, "GROUP_COMMIT", False)

        _register_post("/events.json", create_event, prefer_pool=pool_ingest)

        @r.get("/events/{event_id}.json")
        def get_event(request: Request) -> Response:
            auth = self._authenticate(request)
            event = self.events.get(
                request.path_params["event_id"], auth.app_id, auth.channel_id
            )
            if event is None:
                return Response(404, {"message": "Not Found"})
            return Response(200, event.to_jsonable())

        @r.delete("/events/{event_id}.json")
        def delete_event(request: Request) -> Response:
            auth = self._authenticate(request)
            found = self.events.delete(
                request.path_params["event_id"], auth.app_id, auth.channel_id
            )
            if not found:
                return Response(404, {"message": "Not Found"})
            return Response(200, {"message": "Found"})

        @r.get("/events.json")
        def find_events(request: Request) -> Response:
            auth = self._authenticate(request)
            q = request.query
            try:
                def time(name: str):
                    return parse_iso8601(q[name]) if name in q else None

                limit = int(q["limit"]) if "limit" in q else 20
                reversed_ = q.get("reversed", "false").lower() == "true"
                events = list(self.events.find(
                    app_id=auth.app_id,
                    channel_id=auth.channel_id,
                    start_time=time("startTime"),
                    until_time=time("untilTime"),
                    entity_type=q.get("entityType"),
                    entity_id=q.get("entityId"),
                    event_names=[q["event"]] if "event" in q else None,
                    target_entity_type=q.get("targetEntityType", _UNSET_Q),
                    target_entity_id=q.get("targetEntityId", _UNSET_Q),
                    limit=limit,
                    reversed=reversed_,
                ))
            except ValueError as e:
                return Response(400, {"message": str(e)})
            if not events:
                return Response(404, {"message": "Not Found"})
            return Response(200, [e.to_jsonable() for e in events])

        def batch_events(request: Request) -> Response:
            auth = self._authenticate(request)
            # native-body fast path: raw bytes → columnar arrays in C++
            # (GIL-released; native/src/jsonparse.cc), skipping even
            # json.loads. Anything the strict-subset parser declines —
            # and any storage failure — falls through to the doc path
            # below, unchanged. The same ≥8 threshold as the doc gate
            # keeps small-batch storage behavior identical.
            if (not self.plugin_context.input_blockers
                    and not self.plugin_context.input_sniffers
                    and not getattr(self, "_columnar_unsupported", False)
                    and hasattr(self.events, "insert_interactions")):
                from incubator_predictionio_tpu.data.storage.base import (
                    uniform_interactions_from_body,
                )

                fast = uniform_interactions_from_body(
                    request.body, self.config.max_batch)
                if fast is not None and len(fast[0]) >= 8:
                    resp = self._columnar_fast_response(
                        auth, fast, len(fast[0]))
                    if resp is not None:
                        # the size histogram books exactly once per
                        # batch request, at whichever leg answers it
                        _INGEST_BATCH_SIZE.observe(len(fast[0]))
                        return resp
            try:
                items = request.json()
            except ValueError as e:
                return Response(400, {"message": str(e)})
            if not isinstance(items, list):
                return Response(400, {"message": "request body must be a JSON array"})
            if len(items) > self.config.max_batch:
                return Response(400, {
                    "message": (
                        "Batch request must have less than or equal to "
                        f"{self.config.max_batch} events"
                    )
                })
            _INGEST_BATCH_SIZE.observe(len(items))
            # doc-level columnar fast path: the uniform interaction shape
            # goes wire → native log without ever constructing Event
            # objects (parse+validate of 50 Events costs more than the
            # write). Only when no plugin needs per-Event visibility and
            # the backend can return ids for a columnar insert; anything
            # the gate rejects — and any storage failure — falls through
            # to the generic per-event path below, unchanged.
            if (len(items) >= 8
                    and not self.plugin_context.input_blockers
                    and not self.plugin_context.input_sniffers
                    and hasattr(self.events, "insert_interactions")
                    and not getattr(self, "_columnar_unsupported", False)):
                resp = self._batch_fast_path(auth, items)
                if resp is not None:
                    return resp
            # gate per event (parse / allowed-names / blocker veto keep
            # per-event isolation, scala :409), then land every survivor
            # in ONE framed bulk write — the storage hot path the
            # reference pays per-event HBase puts for. If the bulk write
            # fails, fall back to per-event inserts so storage-error
            # isolation semantics stay identical to the reference.
            # Plugin visibility note: within ONE batch request, input
            # blockers observe storage as of the request start (events of
            # the same batch are not yet visible to later blockers) —
            # same as the reference's concurrent per-event futures, whose
            # within-batch write visibility was never ordered either.
            results: list = [None] * len(items)
            pending: list = []  # (index, event, info)
            for idx, item in enumerate(items):
                try:
                    event = self._parse_event(item)
                except (ValueError, EventValidationError) as e:
                    results[idx] = {"status": 400, "message": str(e)}
                    self._book(auth, 400, "<error>",
                               route=self._BATCH_ROUTE)
                    continue
                try:
                    self._check_allowed(auth, event.event)
                    info = EventInfo(auth.app_id, auth.channel_id, event)
                    for blocker in \
                            self.plugin_context.input_blockers.values():
                        blocker.process(info, self.plugin_context)
                except AuthError as e:
                    results[idx] = {"status": e.status, "message": e.message}
                    self._book(auth, e.status, event.event,
                               route=self._BATCH_ROUTE)
                    continue
                except Exception as e:
                    results[idx] = {"status": 500, "message": str(e)}
                    self._book(auth, 500, event.event,
                               route=self._BATCH_ROUTE)
                    continue
                pending.append((idx, event, info))
            ids: Optional[list] = None
            if pending:
                try:
                    ids = self.events.insert_batch(
                        [e for _, e, _ in pending], auth.app_id,
                        auth.channel_id)
                except AmbiguousWriteError as e:
                    # the remote write MAY have been applied — a per-event
                    # retry would duplicate the whole batch; fail the
                    # pending slots honestly and let the client decide
                    logger.warning("bulk insert ambiguous: %s", e)
                    for idx, event, _info in pending:
                        results[idx] = {"status": 500, "message": str(e)}
                        self._book(auth, 500, event.event,
                                   route=self._BATCH_ROUTE)
                    pending = []
                except Exception:
                    # Best-effort recovery window (documented): the failed
                    # bulk attempt rolls back its auto-id inserts, but a
                    # rollback-delete that itself fails (logged at warning
                    # by base.Events.insert_batch) leaves an event the
                    # per-event retry will DUPLICATE; and explicit-id
                    # events that landed before the failure are re-upserted
                    # here, which moves them to the end of their
                    # timestamp tie-break group relative to a clean single
                    # attempt. Operators reconciling after a 500-mixed
                    # batch response should check for both.
                    logger.exception(
                        "bulk insert failed; retrying per event")
            if ids is not None:
                for (idx, event, info), event_id in zip(pending, ids):
                    results[idx] = {"status": 201, "eventId": event_id}
                    self._book(auth, 201, event.event,
                               route=self._BATCH_ROUTE)
                    self._sniff(info)
            else:
                for idx, event, info in pending:
                    try:
                        event_id = self.events.insert(
                            event, auth.app_id, auth.channel_id)
                        results[idx] = {"status": 201, "eventId": event_id}
                        self._book(auth, 201, event.event,
                                   route=self._BATCH_ROUTE)
                        self._sniff(info)
                    except Exception as e:
                        results[idx] = {"status": 500, "message": str(e)}
                        self._book(auth, 500, event.event,
                                   route=self._BATCH_ROUTE)
            return Response(200, results)

        _register_post("/batch/events.json", batch_events,
                       prefer_pool=pool_ingest)
        # the SDKs' pluralized spelling of the batch route — the SAME
        # handler, so both spellings ride the native one-parse-per-batch
        # fast path and book pio_ingest_batch_size identically
        _register_post("/batches/events.json", batch_events,
                       prefer_pool=pool_ingest)

        @r.post("/reload")
        def reload_route(request: Request) -> Response:
            # the rolling-writer-reload seam (serving/frontdoor.py
            # IngestFrontDoor drains this writer, POSTs here, probes,
            # re-admits): push every buffered append to a durability
            # point so the reloaded writer rejoins with nothing only it
            # knows about. Safe under concurrent traffic — sync takes
            # the storage client's own lock.
            self._authenticate(request)
            client = getattr(self.events, "client", None)
            sync = getattr(client, "sync", None)
            if sync is None:
                # remote/memory backends: durability is the storage
                # server's concern; the drain itself was the reload
                return Response(200, {"message": "Reloaded",
                                      "synced": False})
            try:
                sync()
            except Exception as e:
                return Response(500, {"message": f"sync failed: {e}"})
            return Response(200, {"message": "Reloaded", "synced": True})

        @r.get("/stats.json")
        def stats_route(request: Request) -> Response:
            auth = self._authenticate(request)
            if not self.config.stats:
                return Response(404, {
                    "message": "To see stats, launch Event Server with --stats argument."
                })
            body = self.stats.get(auth.app_id)
            gc_stats = getattr(self.events, "group_commit_stats", None)
            if gc_stats is not None:
                # additive key beyond the reference's Stats shape: how
                # well concurrent wire batches coalesced into appends.
                # Scope differs from the per-app hourly counters above —
                # the payload says so explicitly ("scope" field)
                body["groupCommit"] = gc_stats()
            return Response(200, body)

        # -- webhooks (EventServer.scala webhooks routes + Webhooks.scala) --
        @r.post("/webhooks/{name}.json")
        def webhook_json(request: Request) -> Response:
            auth = self._authenticate(request)
            connector = webhooks.json_connector(request.path_params["name"])
            if connector is None:
                return Response(404, {
                    "message": f"webhooks connection for {request.path_params['name']} is not supported."
                })
            try:
                event_json = connector.to_event_json(request.json())
                event = self._parse_event(event_json)
            except (ConnectorError, ValueError, EventValidationError) as e:
                self._book(auth, 400, "<error>",
                           route="/webhooks/{name}.json")
                return Response(400, {"message": str(e)})
            return self._ingest(auth, event, route="/webhooks/{name}.json")

        @r.get("/webhooks/{name}.json")
        def webhook_json_probe(request: Request) -> Response:
            self._authenticate(request)
            if webhooks.json_connector(request.path_params["name"]) is None:
                return Response(404, {"message": "Not Found"})
            return Response(200, {"message": "Ok"})

        @r.post("/webhooks/{name}.form")
        def webhook_form(request: Request) -> Response:
            auth = self._authenticate(request)
            connector = webhooks.form_connector(request.path_params["name"])
            if connector is None:
                return Response(404, {
                    "message": f"webhooks connection for {request.path_params['name']} is not supported."
                })
            try:
                event_json = connector.to_event_json(request.form())
                event = self._parse_event(event_json)
            except (ConnectorError, ValueError, EventValidationError) as e:
                self._book(auth, 400, "<error>",
                           route="/webhooks/{name}.form")
                return Response(400, {"message": str(e)})
            return self._ingest(auth, event, route="/webhooks/{name}.form")

        @r.get("/webhooks/{name}.form")
        def webhook_form_probe(request: Request) -> Response:
            self._authenticate(request)
            if webhooks.form_connector(request.path_params["name"]) is None:
                return Response(404, {"message": "Not Found"})
            return Response(200, {"message": "Ok"})

        @r.get("/plugins.json")
        def plugins_list(request: Request) -> Response:
            return Response(200, {
                "plugins": {
                    "inputblockers": {
                        n: {"name": n} for n in self.plugin_context.input_blockers
                    },
                    "inputsniffers": {
                        n: {"name": n} for n in self.plugin_context.input_sniffers
                    },
                }
            })

        @r.get("/plugins/{tail...}")
        def plugins_rest(request: Request) -> Response:
            parts = request.path_params["tail"].split("/")
            plugin = self.plugin_context.plugin(parts[0])
            if plugin is None:
                return Response(404, {"message": "Not Found"})
            return Response(
                200,
                plugin.handle_rest("/".join(parts[1:]), dict(request.query)),
            )

        add_metrics_route(r)
        # GET /recorder: flight-recorder window (obs/recorder.py)
        add_recorder_route(r)
        return r

    # -- lifecycle ----------------------------------------------------------
    def start_background(self) -> int:
        port = self.http.start_background()
        logger.info("EventServer started on %s:%d", self.config.ip, port)
        return port

    async def serve_forever(self) -> None:
        await self.http.serve_forever()

    def stop(self) -> None:
        self.http.stop()


def create_event_server(
    config: Optional[EventServerConfig] = None,
) -> EventServer:
    """EventServer.createEventServer:614."""
    return EventServer(config)
