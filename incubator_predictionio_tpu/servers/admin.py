"""AdminAPI — REST admin mirroring the CLI app commands.

Parity: tools/.../admin/AdminAPI.scala:38-160 + CommandClient.scala on
:7071 — ``GET /`` status, ``GET /cmd/app`` list, ``POST /cmd/app`` create
(generates a default access key like the CLI), ``DELETE /cmd/app/{name}``,
``DELETE /cmd/app/{name}/data``.

Beyond parity, the admin process is the fleet's control-plane brain: it
hosts the self-driving freshness controller (obs/controller.py) —
``GET /controller`` serves the decision audit trail, ``POST
/controller`` is the live kill switch — and the self-tuning knob
controller (obs/knobs.py) behind the same pair on ``/knobs``, alongside
``/federate``, ``/slo`` and ``/profile``. Both GET responses carry the
``recorder``/``incident`` armed-state, so one status call shows the
whole control plane.
"""

from __future__ import annotations

import logging
from typing import Optional

from typing import TYPE_CHECKING

from incubator_predictionio_tpu.data.storage import AccessKey, App, Storage
from incubator_predictionio_tpu.obs.http import (
    add_federate_route,
    add_incident_routes,
    add_metrics_route,
    add_profile_route,
    add_recorder_route,
    add_slo_route,
)

if TYPE_CHECKING:  # pragma: no cover
    from incubator_predictionio_tpu.obs.controller import (
        FreshnessController,
    )
    from incubator_predictionio_tpu.obs.knobs import KnobController
from incubator_predictionio_tpu.utils.annotations import experimental
from incubator_predictionio_tpu.utils.http import (
    HttpServer,
    Request,
    Response,
    Router,
)

logger = logging.getLogger(__name__)


@experimental
class AdminServer:
    def __init__(self, ip: str = "127.0.0.1", port: int = 7071,
                 controller: "FreshnessController" = None,
                 knobs: "KnobController" = None):
        self.apps = Storage.get_meta_data_apps()
        self.access_keys = Storage.get_meta_data_access_keys()
        self.channels = Storage.get_meta_data_channels()
        self.events = Storage.get_events()
        # the self-driving freshness controller (obs/controller.py):
        # the admin process hosts its evaluation loop and exposes its
        # decision audit trail. A custom-wired instance (retrain/reload
        # actuators, bench harnesses) can be injected; the default is
        # the env-wired process controller.
        if controller is None:
            from incubator_predictionio_tpu.obs.controller import (
                get_controller,
            )

            controller = get_controller()
        self.controller = controller
        # the self-tuning knob controller (obs/knobs.py): same hosting
        # contract — injectable for bench harnesses, env-wired default
        if knobs is None:
            from incubator_predictionio_tpu.obs.knobs import (
                get_knob_controller,
            )

            knobs = get_knob_controller()
        self.knobs = knobs
        self.http = HttpServer.from_conf(self._build_router(), ip, port,
                                         name="admin")

    @staticmethod
    def _armed_state() -> dict:
        """The rest of the control plane, in one glance: is the flight
        recorder sampling, is incident capture armed? Folded into both
        controllers' GET responses so an operator never has to infer
        "would a breach actually freeze a bundle?" from env vars."""
        from incubator_predictionio_tpu.obs.recorder import (
            get_capture,
            get_recorder,
        )

        recorder = get_recorder()
        capture = get_capture()
        return {
            "recorder": {
                "armed": recorder is not None,
                "samples": (recorder.index()["samples"]
                            if recorder is not None else None),
            },
            "incident": {
                "armed": capture is not None,
                "directory": (capture.directory
                              if capture is not None else None),
            },
        }

    def _build_router(self) -> Router:
        r = Router()

        @r.get("/")
        def index(request: Request) -> Response:
            return Response(200, {
                "status": "alive",
                "description": "PredictionIO-TPU Admin API",
            })

        @r.get("/cmd/app")
        def list_apps(request: Request) -> Response:
            out = []
            for app in self.apps.get_all():
                keys = self.access_keys.get_by_appid(app.id)
                out.append({
                    "name": app.name, "id": app.id,
                    "description": app.description,
                    "accessKeys": [k.key for k in keys],
                })
            return Response(200, out)

        @r.post("/cmd/app")
        def new_app(request: Request) -> Response:
            try:
                body = request.json()
            except ValueError as e:
                return Response(400, {"message": str(e)})
            name = body.get("name")
            if not name:
                return Response(400, {"message": "app name is required"})
            if self.apps.get_by_name(name) is not None:
                return Response(400, {
                    "message": f"App {name} already exists. Aborting."
                })
            app_id = self.apps.insert(App(
                int(body.get("id", 0)), name, body.get("description")
            ))
            if app_id is None:
                return Response(400, {"message": f"Unable to create app {name}."})
            key = self.access_keys.insert(AccessKey("", app_id, ()))
            self.events.init(app_id)
            return Response(200, {
                "name": name, "id": app_id, "accessKey": key,
            })

        @r.delete("/cmd/app/{name}")
        def delete_app(request: Request) -> Response:
            app = self.apps.get_by_name(request.path_params["name"])
            if app is None:
                return Response(404, {"message": "App not found."})
            for channel in self.channels.get_by_appid(app.id):
                self.events.remove(app.id, channel.id)
                self.channels.delete(channel.id)
            self.events.remove(app.id)
            for key in self.access_keys.get_by_appid(app.id):
                self.access_keys.delete(key.key)
            self.apps.delete(app.id)
            return Response(200, {"message": f"App {app.name} deleted."})

        @r.delete("/cmd/app/{name}/data")
        def delete_app_data(request: Request) -> Response:
            app = self.apps.get_by_name(request.path_params["name"])
            if app is None:
                return Response(404, {"message": "App not found."})
            self.events.remove(app.id)
            self.events.init(app.id)
            return Response(200, {"message": f"App {app.name} data deleted."})

        @r.get("/controller")
        def controller_state(request: Request) -> Response:
            # the decision audit trail: current state + the bounded
            # ring, newest first (?limit=N, default 50)
            try:
                limit = int(request.query.get("limit", "50"))
            except ValueError:
                return Response(400,
                                {"message": "limit must be an integer"})
            return Response(200, {
                **self.controller.stats(),
                **self._armed_state(),
                "decisions": self.controller.decisions(limit=limit),
            })

        @r.post("/controller")
        def controller_mode(request: Request) -> Response:
            # the LIVE kill switch: {"mode": "off"|"observe"|"act"}
            # takes effect within one evaluation interval
            try:
                body = request.json()
            except ValueError as e:
                return Response(400, {"message": str(e)})
            if not isinstance(body, dict):
                return Response(400, {
                    "message": 'body must be a JSON object like '
                               '{"mode": "off"|"observe"|"act"}'})
            try:
                mode = self.controller.set_mode(body.get("mode", ""))
            except ValueError as e:
                return Response(400, {"message": str(e)})
            return Response(200, {"mode": mode,
                                  **self.controller.stats()})

        @r.get("/knobs")
        def knobs_state(request: Request) -> Response:
            # the knob audit trail: registry state + live vector + the
            # bounded decision ring, newest first (?limit=N)
            try:
                limit = int(request.query.get("limit", "50"))
            except ValueError:
                return Response(400,
                                {"message": "limit must be an integer"})
            return Response(200, {
                **self.knobs.stats(),
                **self._armed_state(),
                "values": self.knobs.values(),
                "decisions": self.knobs.decisions(limit=limit),
            })

        @r.post("/knobs")
        def knobs_mode_route(request: Request) -> Response:
            # the LIVE kill switch for the knob loop: {"mode": ...}
            try:
                body = request.json()
            except ValueError as e:
                return Response(400, {"message": str(e)})
            if not isinstance(body, dict):
                return Response(400, {
                    "message": 'body must be a JSON object like '
                               '{"mode": "off"|"observe"|"act"}'})
            try:
                mode = self.knobs.set_mode(body.get("mode", ""))
            except ValueError as e:
                return Response(400, {"message": str(e)})
            return Response(200, {"mode": mode, **self.knobs.stats()})

        add_metrics_route(r)
        # GET /recorder: the admin's own flight-recorder window
        # (obs/recorder.py); the fleet-merged pre-breach history lives
        # in the incident bundles, which pull every WORKER's /recorder
        add_recorder_route(r)
        # GET /incidents + POST /incident: SLO-breach-frozen bundles
        # under PIO_INCIDENT_DIR (docs/observability.md "Flight
        # recorder & incidents")
        add_incident_routes(r)
        # GET /federate: scrape the PIO_FLEET_TARGETS workers' /metrics
        # and re-expose the merged fleet series under an `instance`
        # label — the one-scrape fleet truth the ROADMAP-2 load-shedder
        # and ROADMAP-3 controller consume (docs/observability.md
        # "Fleet")
        add_federate_route(r)
        # GET /slo: the burn-rate engine's JSON evaluation — the signal
        # the autonomous retrain controller (ROADMAP-3) will consume;
        # ?fleet=1 evaluates the same objectives over the federation
        add_slo_route(r)
        # POST /profile?seconds=N: on-demand jax.profiler xplane capture
        # for the kernel/MFU work (ROADMAP-5); runs on the executor so
        # the capture window never blocks other admin requests
        add_profile_route(r)
        return r

    def _wire_breach_listeners(self) -> None:
        """Arm the knob controller's incident rollback on the same
        burn engine(s) the incident capture rides: a breach inside the
        newest knob step's cooldown rolls the vector back."""
        from incubator_predictionio_tpu.obs import slo as obs_slo

        try:
            self.knobs.install(obs_slo.get_engine())
        except Exception:
            logger.exception("knob breach listener wiring failed")

    def _wire_capture(self) -> None:
        """Point the incident-capture engine (if PIO_INCIDENT_DIR
        enables one) at THIS admin's hosted controller ring — an
        injected controller's decisions must land in the bundles, not
        the env-wired singleton's empty ring."""
        from incubator_predictionio_tpu.obs.controller import (
            export_ring_fn,
        )
        from incubator_predictionio_tpu.obs.recorder import get_capture

        capture = get_capture()
        if capture is not None:
            capture.decisions_fn = export_ring_fn(self.controller)
            # the knob ring rides the same duck-typed export seam: the
            # bundle's "knobs" block must show the hosted controller's
            # decisions (obs/recorder.py capture_now)
            capture.knobs_fn = export_ring_fn(self.knobs)
            # tenant block: freeze the registry's policy + per-tenant
            # SLO state into bundles so a noisy-neighbor incident shows
            # who shed and who was protected
            from incubator_predictionio_tpu.serving import tenancy

            capture.tenants_fn = tenancy.export_tenants_fn()

    def start_background(self) -> int:
        port = self.http.start_background()
        # the loops run in every mode (an off controller idles its
        # tick), so a live POST /controller or /knobs flip to act
        # resumes actuation within one interval with no restart
        self.controller.start()
        self.knobs.start()
        self._wire_capture()
        self._wire_breach_listeners()
        return port

    async def serve_forever(self) -> None:
        self.controller.start()
        self.knobs.start()
        self._wire_capture()
        self._wire_breach_listeners()
        await self.http.serve_forever()

    def stop(self) -> None:
        self.controller.stop()
        self.knobs.stop()
        self.http.stop()
