"""Dashboard — evaluation results UI + live serving/SLO panels.

Parity: tools/.../dashboard/Dashboard.scala:46-162 on :9000 — lists
completed EvaluationInstances newest-first with links to each instance's
stored HTML results (the reference renders the same data through Twirl),
with CORS enabled (CorsSupport.scala:30-66) so external dashboards can
fetch the JSON results cross-origin.

On top of parity, the index renders live panels from the process
registry: p50/p95/p99 serving latency (the running average the
reference shows hides tail regressions entirely), the end-to-end
freshness histogram's quantiles, and the SLO burn-rate summary —
``GET /slo`` serves the same evaluation as JSON. The panels read THIS
process's registry (a co-hosted stack sees everything; a split
deployment points Grafana at the per-process /metrics instead).
"""

from __future__ import annotations

import html
import logging

from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.obs.http import (
    add_metrics_route,
    add_recorder_route,
    add_slo_route,
    render_latency_panels,
    render_slo_panel,
    render_tenant_panel,
)
from incubator_predictionio_tpu.utils.http import (
    HttpServer,
    Request,
    Response,
    Router,
)
from incubator_predictionio_tpu.utils.times import format_iso8601

logger = logging.getLogger(__name__)


class DashboardServer:
    def __init__(self, ip: str = "127.0.0.1", port: int = 9000):
        self.evaluation_instances = Storage.get_meta_data_evaluation_instances()
        self.http = HttpServer.from_conf(self._build_router(), ip, port,
                                         name="dashboard")

    def _build_router(self) -> Router:
        r = Router(cors=True)

        @r.get("/")
        def index(request: Request) -> Response:
            rows = []
            for i in self.evaluation_instances.get_completed():
                rows.append(
                    "<tr>"
                    f"<td><a href='/engine_instances/{i.id}'>{i.id}</a></td>"
                    f"<td>{html.escape(i.evaluation_class)}</td>"
                    f"<td>{html.escape(i.engine_params_generator_class)}</td>"
                    f"<td>{format_iso8601(i.start_time)}</td>"
                    f"<td>{format_iso8601(i.end_time)}</td>"
                    f"<td>{html.escape(i.evaluator_results)}</td>"
                    "</tr>"
                )
            try:
                panels = (render_latency_panels() + render_slo_panel()
                          + render_tenant_panel())
            except Exception:
                logger.exception("dashboard panels failed to render")
                panels = "<p>panels unavailable</p>"
            body = (
                "<html><head><title>PredictionIO-TPU Dashboard</title></head>"
                "<body><h1>Completed Evaluations</h1>"
                "<table border=1><tr><th>ID</th><th>Evaluation</th>"
                "<th>Params Generator</th><th>Start</th><th>End</th>"
                f"<th>Result</th></tr>{''.join(rows)}</table>"
                f"{panels}</body></html>"
            )
            return Response(200, body=body.encode(),
                            content_type="text/html; charset=UTF-8")

        @r.get("/engine_instances/{instance_id}")
        def detail(request: Request) -> Response:
            i = self.evaluation_instances.get(request.path_params["instance_id"])
            if i is None or not i.evaluator_results_html:
                return Response(404, {"message": "Not Found"})
            return Response(200, body=i.evaluator_results_html.encode(),
                            content_type="text/html; charset=UTF-8")

        @r.get("/engine_instances/{instance_id}/evaluator_results.json")
        def detail_json(request: Request) -> Response:
            i = self.evaluation_instances.get(request.path_params["instance_id"])
            if i is None:
                return Response(404, {"message": "Not Found"})
            return Response(
                200,
                body=(i.evaluator_results_json or "{}").encode(),
            )

        add_metrics_route(r)
        # GET /recorder: flight-recorder window (obs/recorder.py)
        add_recorder_route(r)
        add_slo_route(r)
        return r

    def start_background(self) -> int:
        return self.http.start_background()

    async def serve_forever(self) -> None:
        await self.http.serve_forever()

    def stop(self) -> None:
        self.http.stop()
