"""PredictionServer — query serving from TPU-resident model state.

Parity: core/.../workflow/CreateServer.scala:115-725 on :8000:

- ``GET  /``              → status (JSON or HTML): engine info, params,
  request count, average/last serving seconds (:426-428,611-618)
- ``POST /queries.json``  → supplement → predict(∀ algorithms) → serve with
  the ORIGINAL query → optional feedback event → output plugins (:498-650)
- ``POST /reload``        → hot-swap to the latest COMPLETED instance
  (key-authed, :340-366)
- ``POST /stop``          → shutdown (key-authed)
- ``GET  /plugins.json``, ``/plugins/...`` engine-plugin passthrough

The feedback loop posts a ``predict`` event (entityType ``pio_pr``) carrying
engineInstanceId/query/prediction back to the EventServer with ``prId``
(:534-604). The MasterActor deploy/undeploy lifecycle collapses into
``PredictionServerLauncher`` semantics: resolve latest COMPLETED instance →
restore models via ``Engine.prepare_deploy`` (device-resident) → bind.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import secrets
import threading
import time
import traceback
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from incubator_predictionio_tpu.core.engine import Engine
from incubator_predictionio_tpu.core.params import EngineParams, WorkflowParams
from incubator_predictionio_tpu.data.storage import EngineInstance, Storage
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.obs.http import (
    add_metrics_route,
    add_recorder_route,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.servers.plugins import PluginContext
from incubator_predictionio_tpu.serving import tenancy
from incubator_predictionio_tpu.serving.scheduler import (
    BatchScheduler,
    ladder_cap,
)
from incubator_predictionio_tpu.utils import json_codec
from incubator_predictionio_tpu.utils.http import (
    HttpError,
    HttpServer,
    Request,
    Response,
    RetryableError,
    RetryPolicy,
    Router,
    parse_retry_after,
)
from incubator_predictionio_tpu.utils.times import (
    ensure_aware,
    format_iso8601,
    now_utc,
)
from incubator_predictionio_tpu.workflow import CoreWorkflow
from incubator_predictionio_tpu.workflow.workflow import make_runtime_context

logger = logging.getLogger(__name__)

#: per-QUERY serving latency (every query in a fused micro-batch took
#: the batch wall — CreateServer.scala:611-618 per-query semantics, at
#: one histogram observe per BATCH). p50/p95/p99 derive from the fixed
#: exponential buckets; /status reports them too (no scraper needed).
#: Booked on the micro-batch dispatcher thread AFTER the device
#: dispatch resolves — host-side ints only, never inside traced code.
#: TENANT-LABELED (serving/tenancy.py): label values come only from the
#: bounded registry (the unscoped-tenant-metric lint contract);
#: unlabeled family reads (quantile/count/sum) aggregate the children.
_QUERY_LATENCY = obs_metrics.REGISTRY.histogram(
    "pio_query_latency_seconds",
    "per-query serving wall (micro-batch members share the batch wall)",
    labels=("tenant",))
#: instantaneous micro-batcher backlog per tenant, read at scrape time
_QUEUE_DEPTH = obs_metrics.REGISTRY.gauge(
    "pio_serve_queue_depth",
    "queries waiting in the micro-batching queue (scrape-time "
    "snapshot, per tenant)",
    labels=("tenant",))
#: age of the deployed instance, read at scrape time — the gauge the
#: staleness SLO (obs/slo.py) evaluates its bound against; /status's
#: modelStalenessSec reports the same figure
_STALENESS = obs_metrics.REGISTRY.gauge(
    "pio_model_staleness_seconds",
    "seconds since the served engine instance finished training "
    "(scrape-time snapshot)")


@dataclasses.dataclass
class ServerConfig:
    """CreateServer.scala:89-113 ServerConfig."""

    ip: str = "0.0.0.0"
    port: int = 8000
    engine_instance_id: Optional[str] = None  # default: latest COMPLETED
    engine_id: str = "default"
    engine_version: str = "NOT_VERSIONED"
    engine_variant: str = "default"
    event_server_ip: str = "0.0.0.0"
    event_server_port: int = 7070
    access_key: Optional[str] = None
    feedback: bool = False
    server_key: Optional[str] = None  # auth for /stop and /reload
    verbose: bool = False
    #: LADDER CAP for the continuous-batching scheduler (0 disables
    #: batching; the reference serves queries one at a time —
    #: CreateServer.scala:523 "TODO: Parallelize"). This is no longer a
    #: fixed fuse width: the scheduler (serving/scheduler.py) picks the
    #: batch per dispatch from live queue depth on a pow2 rung ladder
    #: and only reaches the cap under sustained pressure, so a large
    #: cap costs idle traffic nothing. Default PIO_SERVE_MAX_BATCH
    #: (512) — the old fixed 64 capped concurrent QPS exactly when the
    #: queue was deepest
    micro_batch: int = dataclasses.field(default_factory=ladder_cap)
    #: micro-batch dispatcher threads. 1 measured best on the host-mirror
    #: path at ML-20M shape (3.8k QPS vs 3.3k at 2 and 2.8k at 4: extra
    #: workers fragment the natural batches and fight the BLAS pool for
    #: cores). The knob exists for the device path, where a second worker
    #: can hide host-side parse/render behind the in-flight dispatch
    serve_workers: int = dataclasses.field(
        default_factory=lambda: int(os.environ.get("PIO_SERVE_WORKERS",
                                                   "1")))
    #: ship query errors to a remote collector (CreateServer.scala:449-460)
    log_url: Optional[str] = None
    log_prefix: str = ""


#: compat alias — the fixed micro-batcher grew into the continuous-
#: batching scheduler (serving/scheduler.py): per-engine admission
#: queues, queue-depth-adaptive pow2 batch widths, the
#: PIO_SERVE_MAX_WAIT_MS age bound, and SLO-driven load shedding. The
#: constructor signature is unchanged (handle_batch, max_batch,
#: workers=…); ``max_batch`` is now the ladder CAP.
_MicroBatcher = BatchScheduler

#: retry choreography for the fire-and-forget posters (feedback events,
#: --log-url shipping): the shared utils/http.RetryPolicy — jittered
#: exponential backoff under a hard deadline, honoring Retry-After on a
#: 503 shed. Only failures that provably never executed server-side
#: (connection refused before send) or that the server explicitly
#: deferred (503) are wrapped retryable — see _post_with_retries.
_POST_RETRY = RetryPolicy(attempts=3, base_delay_s=0.5, max_delay_s=5.0,
                          deadline_s=20.0)


def _post_with_retries(url: str, payload: bytes,
                       headers: Dict[str, str], what: str,
                       expect_status: Optional[int] = None) -> None:
    """One JSON POST under _POST_RETRY; runs on a poster worker thread.

    Retry classification: a refused connection never carried the body
    (safe for any payload), and a 503 is the receiving server's own
    shed contract — it did NOT process the event and told us when to
    come back (Retry-After floors the backoff). Anything else — 4xx,
    non-503 5xx, a timeout mid-flight — fails after one try: the event
    may have been applied, and these posters must never double-apply
    training data. Failures only ever log; posters are fire-and-forget.
    """
    def attempt() -> None:
        req = urllib.request.Request(url, data=payload, headers=headers,
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                if expect_status is not None and resp.status != expect_status:
                    logger.error("%s POST returned status %d", what,
                                 resp.status)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                raise RetryableError(
                    e, retry_after_s=parse_retry_after(
                        e.headers.get("Retry-After"))) from e
            raise
        except urllib.error.URLError as e:
            if isinstance(e.reason, ConnectionRefusedError):
                raise RetryableError(e) from e
            raise

    try:
        _POST_RETRY.call(attempt)
    except Exception as e:
        logger.error("%s failed: %s", what, e)


class _AsyncPoster:
    """Bounded worker pool for fire-and-forget HTTP posts. Bounds the
    resource cost of an error storm against a slow collector: excess posts
    drop with a local log line instead of spawning a thread + socket per
    failure. Feedback events and --log-url shipping get SEPARATE posters so
    a hung diagnostics collector can never starve feedback delivery
    (feedback is training data, not telemetry)."""

    def __init__(self, name: str, workers: int = 2, maxsize: int = 1024):
        import queue

        self._queue: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.dropped = 0  # surfaced on the status page (feedback is data)
        self._dropped_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"pio-poster-{name}-{i}")
            for i in range(max(workers, 1))
        ]
        for t in self._threads:
            t.start()

    def submit(self, fn, what: str) -> None:
        import queue

        # never blocks: submit runs on the serving hot path (a micro-batch
        # dispatcher thread — possibly several under PIO_SERVE_WORKERS>1),
        # where even a brief put(timeout=...) under a collector outage
        # would stall every query behind it
        try:
            self._queue.put_nowait(fn)
        except queue.Full:
            with self._dropped_lock:
                self.dropped += 1
                n = self.dropped
            logger.error("async post queue full; dropping %s (%d dropped "
                         "total)", what, n)

    def stop(self) -> None:
        import queue

        for _ in self._threads:
            try:
                # blocking put with a timeout: when the queue is full of
                # backlog, the sentinel must still land or workers never
                # exit (drained posts run first — stop() is fire-and-forget)
                self._queue.put(None, timeout=5)
            except queue.Full:
                logger.warning(
                    "async post queue still full at stop; a worker may "
                    "keep draining in the background")

    def _run(self) -> None:
        while True:
            fn = self._queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                logger.exception("async post failed")


class PredictionServer:
    def __init__(
        self,
        engine: Engine,
        config: Optional[ServerConfig] = None,
        plugin_context: Optional[PluginContext] = None,
        ctx: Optional[RuntimeContext] = None,
    ):
        self.engine = engine
        self.config = config or ServerConfig()
        config = self.config
        self.plugin_context = plugin_context or PluginContext()
        self.ctx = ctx or make_runtime_context(None)
        self._lock = threading.Lock()
        #: serializes /reload end-to-end: with pre-swap warmup the
        #: resolve→swap window is seconds long, and two unserialized
        #: reloads could last-writer-swap an OLDER instance back in
        self._reload_lock = threading.Lock()
        # serving state (swapped atomically on /reload)
        self.engine_instance: Optional[EngineInstance] = None
        self.engine_params: Optional[EngineParams] = None
        self.algorithms: List[Any] = []
        self.serving: Any = None
        self.models: List[Any] = []
        # latency bookkeeping (CreateServer.scala:426-428)
        self.start_time = now_utc()
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        self.max_batch_served = 0  # largest micro-batch fused so far
        from incubator_predictionio_tpu.utils.ssl_config import load_server_key

        # loaded once, like the reference's ServerKey config object
        self._conf_server_key = (
            load_server_key() if config.server_key is None else None
        )
        # bind-retry 3×/1 s for occupied ports (CreateServer.scala:371-381)
        self.http = HttpServer.from_conf(self._build_router(), config.ip,
                                         config.port, bind_retries=3,
                                         name="prediction")
        #: per-tenant deploys beyond the default one (tenant id →
        #: {engine_instance, engine_params, algorithms, serving,
        #: models}); a registered tenant with no entry here SHARES the
        #: default deploy — co-resident deploys only materialize when a
        #: tenant pins its own engine/variant or tenant-scoped-reloads
        self._deploys: Dict[str, Dict[str, Any]] = {}
        self._batcher = (
            # the p99 feed takes the tenant (non-defaulted — the
            # scheduler arity-detects per-tenant feeds): the shed
            # projection must read the tenant's OWN tail, never a noisy
            # neighbor's
            BatchScheduler(self._handle_batch, config.micro_batch,
                           workers=config.serve_workers,
                           p99_fn=lambda tenant: _QUERY_LATENCY.labels(
                               tenant=tenancy.get_registry().label(tenant)
                           ).quantile(0.99))
            if config.micro_batch > 0 else None
        )
        self._sync_tenant_policy()
        if self._batcher is not None:
            self.register_queue_collector()
        # scrape-time model-staleness gauge (weakref for the same
        # reason as the queue collector: telemetry must never pin a
        # stopped server's models)
        import weakref as _weakref

        server_ref = _weakref.ref(self)

        def _collect_staleness() -> None:
            s = server_ref()
            if s is None:
                return
            with s._lock:
                instance = s.engine_instance
            if instance is None:
                return
            _STALENESS.set(max(
                (now_utc() - ensure_aware(instance.end_time))
                .total_seconds(), 0.0))

        obs_metrics.REGISTRY.register_collector(
            "prediction_model_staleness", _collect_staleness)
        # feedback events are training data: a deep queue so only a
        # sustained collector outage drops (drops counted and shown on the
        # status page); --log-url diagnostics stay shallow and lossy
        self._feedback_poster = _AsyncPoster("feedback", maxsize=16384)
        self._log_poster = _AsyncPoster("log", workers=1, maxsize=256)
        #: live speed-layer overlays (speed/overlay.py), rebuilt per
        #: deploy/reload — the Lambda speed leg between retrains
        self._speed_overlays: List[Any] = []

    # -- tenancy ------------------------------------------------------------
    def register_queue_collector(self) -> None:
        """Register the scrape-time ``pio_serve_queue_depth`` collector.

        The named collector replaces any prior server's hook so
        re-deploys never accumulate dead closures, and it weakrefs the
        SERVER (not the batcher — harnesses and tests may swap
        ``_batcher`` after construction; the collector must follow the
        live one) so a stopped server's engine + models stay
        collectable — the registry must never pin model memory.
        Harnesses that build a server via ``__new__`` (tests/
        fleet_worker.py) call this after wiring their own batcher."""
        import weakref

        server_ref = weakref.ref(self)

        def _collect_queue_depth() -> None:
            s = server_ref()
            b = s._batcher if s is not None else None
            if b is None:
                return
            depths = b.depths_by_tenant()
            depths.setdefault(tenancy.DEFAULT_TENANT, 0)
            reg = tenancy.get_registry()
            for t in reg.tenant_ids():
                depths.setdefault(t, 0)
            for t, d in depths.items():
                _QUEUE_DEPTH.labels(tenant=reg.label(t)).set(float(d))

        obs_metrics.REGISTRY.register_collector(
            "prediction_queue_depth", _collect_queue_depth)

    def _sync_tenant_policy(self) -> None:
        """Push the tenant registry's isolation policy (weights, quotas)
        into the scheduler — at construction and after every /reload, so
        a registry change lands without restart."""
        batcher = getattr(self, "_batcher", None)
        if batcher is None:
            return
        reg = tenancy.get_registry()
        batcher.set_tenant_policy(reg.weights(), reg.quotas())

    # -- deploy lifecycle ---------------------------------------------------
    def _resolve_instance(
            self, engine_id: Optional[str] = None,
            engine_variant: Optional[str] = None) -> EngineInstance:
        instances = Storage.get_meta_data_engine_instances()
        if engine_id is None and engine_variant is None \
                and self.config.engine_instance_id:
            instance = instances.get(self.config.engine_instance_id)
            if instance is None:
                raise ValueError(
                    f"Invalid engine instance ID {self.config.engine_instance_id}."
                )
        else:
            instance = instances.get_latest_completed(
                engine_id or self.config.engine_id,
                self.config.engine_version,
                engine_variant or self.config.engine_variant,
            )
            if instance is None:
                raise ValueError(
                    "No valid engine instance found for engine "
                    f"{self.config.engine_id} {self.config.engine_version} "
                    f"{self.config.engine_variant}. The engine id is derived "
                    "from the engine directory's absolute path — if the "
                    "engine was trained from a different path (moved, "
                    "re-cloned, other mount), its instances are keyed under "
                    "a different id; redeploy from the training path or pass "
                    "--engine-instance-id explicitly."
                )
        return instance

    def load_models(self, warm_before_swap: bool = False,
                    tenant: Optional[str] = None) -> None:
        """createServerActorWithEngine (:207-266): restore + prepare_deploy.

        ``warm_before_swap`` is the /reload path's double-buffered
        refresh: the OLD models keep serving while the replacements
        compile their dispatches and build host mirrors (algo.warmup), and
        the swap happens only once they are query-ready — a reload never
        spikes live p50 with compiles or a tunnel-priced device→host
        fetch. Initial deploy keeps warmup async (nothing serves yet;
        binding fast matters more).

        ``tenant`` scopes the refresh to ONE co-resident deploy
        (``/reload?tenant=X``): only that tenant's state swaps, so
        rolling-reloading one tenant never drains another's serving."""
        if tenant is not None and tenant != tenancy.DEFAULT_TENANT:
            self._load_tenant_models(tenant, warm_before_swap)
            return
        instance = self._resolve_instance()
        engine_params = self.engine.engine_params_from_instance(instance)
        models = CoreWorkflow.load_models(
            instance.id, self.engine, engine_params, ctx=self.ctx
        )
        _ds, _prep, algorithms, serving = self.engine.components(engine_params)
        if warm_before_swap:
            self._warm_models(algorithms, models)
        overlays = self._build_speed_overlays(engine_params, algorithms,
                                              models)
        with self._lock:
            self.engine_instance = instance
            self.engine_params = engine_params
            self.algorithms = algorithms
            self.serving = serving
            self.models = models
            # getattr: tests and the bench build servers via __new__
            # with hand-injected state
            old_overlays = getattr(self, "_speed_overlays", [])
            self._speed_overlays = overlays
        # hot model swap: the OLD overlays' vectors were solved against
        # the old factors — invalidated wholesale and stopped. Their KEYS
        # (fresh sessions the new model may still not know) carry over as
        # dirty marks so the new overlays re-solve them against the new
        # factors instead of dropping fresh users until their next event.
        # Both lists are ALGORITHM-ALIGNED (None where an algorithm has
        # no overlay), so adoption can never pair across algorithms.
        for old, ov in zip(old_overlays, overlays):
            if old is None or ov is None:
                continue
            try:
                ov.adopt_keys(old.known_keys())
            except Exception:
                logger.exception("speed overlay key adoption failed")
        for ov in old_overlays:
            if ov is None:
                continue
            try:
                ov.invalidate_all()
                ov.stop()
            except Exception:
                logger.exception("speed overlay teardown failed")
        for ov in overlays:
            if ov is not None:
                ov.start()
        # host the MIPS rebuild daemon next to the overlay pollers: it
        # folds published virtual-id tails, re-tiers cold buckets and
        # swaps indexes off the serving path (ops/mips_daemon.py).
        # Acquired ONCE per server — a /reload must not stack refs.
        with self._lock:
            want_daemon = not getattr(self, "_mips_daemon_held", False)
            if want_daemon:
                self._mips_daemon_held = True
        if want_daemon:
            try:
                from incubator_predictionio_tpu.ops import mips_daemon

                mips_daemon.acquire()
            except Exception:
                logger.exception("mips rebuild daemon start failed")
                with self._lock:
                    self._mips_daemon_held = False
        logger.info(
            "Engine instance %s deployed (%d algorithms, %d speed "
            "overlays)", instance.id, len(self.algorithms),
            sum(1 for ov in overlays if ov is not None),
        )

    def _load_tenant_models(self, tenant_id: str,
                            warm_before_swap: bool) -> None:
        """Load/refresh ONE tenant's co-resident deploy (the tenant-
        scoped half of :meth:`load_models`). Rides the same warm-before-
        swap discipline; the swap touches only ``self._deploys[tenant]``
        so every other tenant — including the default deploy — keeps
        serving untouched. Speed overlays stay a default-deploy feature
        (tenant deploys serve the model-of-record)."""
        reg = tenancy.get_registry()
        t = reg.get(tenant_id)
        if t is None:
            raise HttpError(404, f"Unknown tenant {tenant_id!r}.")
        instance = self._resolve_instance(
            engine_id=t.engine_id or self.config.engine_id,
            engine_variant=t.engine_variant or self.config.engine_variant)
        engine_params = self.engine.engine_params_from_instance(instance)
        models = CoreWorkflow.load_models(
            instance.id, self.engine, engine_params, ctx=self.ctx
        )
        _ds, _prep, algorithms, serving = self.engine.components(
            engine_params)
        if warm_before_swap:
            self._warm_models(algorithms, models)
        with self._lock:
            self._deploys[tenant_id] = {
                "engine_instance": instance,
                "engine_params": engine_params,
                "algorithms": algorithms,
                "serving": serving,
                "models": models,
            }
        logger.info(
            "Tenant %s deployed engine instance %s (%d algorithms)",
            tenant_id, instance.id, len(algorithms))

    def _build_speed_overlays(self, engine_params, algorithms,
                              models) -> List[Any]:
        """One overlay per algorithm that offers a fold-in config
        (core/base.py Algorithm.make_speed_overlay), attached to the
        algorithm for its predict path. Gated by PIO_SPEED_LAYER
        (default on); any construction failure disables the overlay for
        that algorithm only — serving never depends on the speed leg.
        The returned list is ALGORITHM-ALIGNED (None placeholders), so
        hot-swap key adoption pairs old and new overlays by algorithm."""
        dsp = engine_params.data_source_params[1]
        app_name = getattr(dsp, "app_name", None)
        channel_name = getattr(dsp, "channel_name", None)
        disabled = os.environ.get("PIO_SPEED_LAYER", "1").lower() in (
            "0", "off", "false")
        overlays: List[Any] = []
        for algo, model in zip(algorithms, models):
            overlay = None
            if not disabled:
                try:
                    overlay = algo.make_speed_overlay(
                        model, app_name, channel_name,
                        data_source_params=dsp)
                    if overlay is not None and not overlay.enabled:
                        overlay = None  # backend without tail support
                except Exception:
                    logger.exception(
                        "speed overlay unavailable for %s",
                        type(algo).__name__)
                    overlay = None
            algo.attach_speed_overlay(overlay)
            overlays.append(overlay)
        return overlays

    # -- query pipeline -----------------------------------------------------
    def _handle_query(self, body: bytes,
                      tenant: str = tenancy.DEFAULT_TENANT) -> Any:
        res = self._handle_batch([body], self.config.engine_id, tenant)[0]
        if isinstance(res, Exception):
            raise res
        return res

    def _handle_batch(self, bodies: List[bytes], engine: str,
                      tenant: str) -> List[Any]:
        """Serve a batch of query bodies in one pass: parse + supplement per
        query, then ONE ``batch_predict`` per algorithm (a single device
        dispatch for the whole batch, ops/topk.py batch_score_top_k), then
        per-query serve/feedback/plugins. Per-query failures become entries
        in the result list — one bad query never fails its batchmates.
        A batch of one is the plain sequential path.

        ``engine``/``tenant`` are non-defaulted so the scheduler's arity
        detection routes each batch here with its queue's tenant — a
        batch is single-tenant by construction, and serves from that
        tenant's own deploy when one is resident."""
        t0 = time.perf_counter()
        with self._lock:
            # getattr: tests and the bench build servers via __new__
            # with hand-injected state
            dep = (getattr(self, "_deploys", {}).get(tenant)
                   if tenant != tenancy.DEFAULT_TENANT else None)
            if dep is not None:
                algorithms = dep["algorithms"]
                serving = dep["serving"]
                models = dep["models"]
                instance = dep["engine_instance"]
            else:
                algorithms = self.algorithms
                serving = self.serving
                models = self.models
                instance = self.engine_instance
        n = len(bodies)
        if not algorithms or instance is None:
            return [HttpError(503, "No engine instance deployed.")] * n
        query_class = algorithms[0].query_class
        results: List[Any] = [None] * n
        raws: List[Any] = [None] * n
        for idx, body in enumerate(bodies):
            try:
                raws[idx] = json.loads(body.decode("utf-8"))
            except Exception as e:
                results[idx] = e
        # columnar serving fast path (core/base.py batch_serve_json): only
        # when the rendered bytes are observably identical to the object
        # path — one algorithm, declared first-prediction serving with the
        # inherited identity supplement, and nothing downstream that needs
        # the result as an object (feedback loop, output plugins)
        from incubator_predictionio_tpu.core.base import Serving

        # the flag must be declared on the serving's OWN class: a subclass
        # that overrides serve() would silently inherit True and its
        # serve() would never run on fast-path responses
        if (len(algorithms) == 1
                and type(serving).__dict__.get("FIRST_PREDICTION_ONLY",
                                               False)
                and type(serving).supplement is Serving.supplement
                and not self.config.feedback
                and not self.plugin_context.output_blockers
                and not self.plugin_context.output_sniffers):
            try:
                fast = algorithms[0].batch_serve_json(
                    models[0],
                    [r if results[i] is None else None
                     for i, r in enumerate(raws)])
            except Exception:
                logger.exception(
                    "batch_serve_json failed; using the object path")
                fast = None
            if fast:
                for idx, payload in enumerate(fast):
                    if payload is not None and results[idx] is None:
                        results[idx] = payload
        parsed: List[Any] = []  # [idx, raw, query, supplemented]
        for idx, body in enumerate(bodies):
            if results[idx] is not None:
                continue
            try:
                raw = raws[idx]
                query = (
                    json_codec.extract(query_class, raw)
                    if query_class is not None else raw
                )
                parsed.append([idx, raw, query, serving.supplement(query)])
            except Exception as e:
                results[idx] = e
        # one prediction per algorithm per live query; a batch of >1 goes
        # through the algorithm's batched path
        preds: Dict[int, List[Any]] = {p[0]: [] for p in parsed}
        for a, m in zip(algorithms, models):
            live = [(idx, supp) for idx, _r, _q, supp in parsed
                    if results[idx] is None]
            if not live:
                break
            if len(live) > 1:
                try:
                    got = dict(a.batch_predict(m, live))
                    # all-or-nothing: resolve every idx BEFORE mutating
                    # preds, so a partial batch_predict result (missing
                    # idx → KeyError here) falls through to the per-query
                    # path without leaving duplicate appends behind
                    vals = [got[idx] for idx, _supp in live]
                    for (idx, _supp), v in zip(live, vals):
                        preds[idx].append(v)
                    continue
                except Exception:
                    logger.exception(
                        "batch_predict failed; falling back to per-query")
            for idx, supp in live:
                try:
                    preds[idx].append(a.predict(m, supp))
                except Exception as e:
                    results[idx] = e
        for idx, raw, query, _supp in parsed:
            if results[idx] is not None:
                continue
            try:
                # by design, serve sees the ORIGINAL query
                # (CreateServer.scala:526)
                prediction = serving.serve(query, preds[idx])
                result = json_codec.to_jsonable(prediction)
                if self.config.feedback:
                    result = self._feedback(instance, raw, result)
                for blocker in self.plugin_context.output_blockers.values():
                    result = blocker.process(
                        instance.engine_variant, raw, result,
                        self.plugin_context)
                for sniffer in self.plugin_context.output_sniffers.values():
                    try:
                        sniffer.process(
                            instance.engine_variant, raw, result,
                            self.plugin_context)
                    except Exception:
                        logger.exception("output sniffer failed")
                results[idx] = result
            except Exception as e:
                results[idx] = e
        if self.config.log_url:
            for idx, res in enumerate(results):
                if isinstance(res, Exception) and not isinstance(
                        res, HttpError):
                    self._remote_log(
                        f"Query:\n{bodies[idx][:2048]!r}\n\nStack Trace:\n"
                        + "".join(traceback.format_exception(res)))
        dt = time.perf_counter() - t0
        with self._lock:
            # every query in the batch took dt wall-clock (they shared one
            # dispatch) — the counters keep CreateServer.scala:611-618
            # per-query semantics
            self.request_count += n
            self.avg_serving_sec = (
                self.avg_serving_sec * (self.request_count - n) + dt * n
            ) / self.request_count
            self.last_serving_sec = dt
            self.max_batch_served = max(self.max_batch_served, n)
        # n same-valued observations in one bucket add: per-query tail
        # latency (p50/p95/p99) at per-batch bookkeeping cost; the
        # tenant child comes from the bounded registry (lint contract)
        _QUERY_LATENCY.labels(
            tenant=tenancy.get_registry().label(tenant)).observe(dt, n)
        return results

    def _remote_log(self, message: str) -> None:
        """POST a query error to the --log-url collector, prefixed with
        --log-prefix (remoteLog, CreateServer.scala:449-460). Fire-and-
        forget on a daemon thread; collector failures only log locally."""
        with self._lock:
            instance = self.engine_instance
        payload = (self.config.log_prefix or "") + json.dumps({
            "engineInstance": {
                "id": instance.id if instance else None,
                "engineId": instance.engine_id if instance else None,
                "engineVariant": (
                    instance.engine_variant if instance else None),
            },
            "message": message,
        })

        # trace headers captured HERE: the poster runs on its own daemon
        # thread where the request's contextvars are gone
        trace_headers = obs_trace.client_headers()
        self._log_poster.submit(
            lambda: _post_with_retries(
                self.config.log_url, payload.encode(),
                {"Content-Type": "application/json", **trace_headers},
                "remote log"),
            "remote log")

    def _feedback(
        self, instance: EngineInstance, query_json: Any, prediction_json: Any
    ) -> Any:
        """Post the predict event back to the EventServer (:534-604)."""
        pr_id = prediction_json.get("prId") if isinstance(
            prediction_json, dict) else None
        if not pr_id:
            pr_id = secrets.token_hex(32)
        data = {
            "event": "predict",
            "eventTime": format_iso8601(now_utc()),
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": {
                "engineInstanceId": instance.id,
                "query": query_json,
                "prediction": prediction_json,
            },
        }
        if isinstance(query_json, dict) and query_json.get("prId"):
            data["prId"] = query_json["prId"]
        url = (
            f"http://{self.config.event_server_ip}:"
            f"{self.config.event_server_port}/events.json"
            f"?accessKey={self.config.access_key or ''}"
        )

        # trace headers captured before the executor hop (see _remote_log)
        trace_headers = obs_trace.client_headers()
        self._feedback_poster.submit(
            lambda: _post_with_retries(
                url, json.dumps(data).encode(),
                {"Content-Type": "application/json", **trace_headers},
                "feedback event", expect_status=201),
            "feedback event")
        # inject prId into the served result when the prediction carries one
        if isinstance(prediction_json, dict) and "prId" in prediction_json:
            prediction_json = dict(prediction_json, prId=pr_id)
        return prediction_json

    def _speed_status_locked(self) -> Dict[str, Any]:
        """Aggregate speed-overlay stats for /status (caller holds
        self._lock). size/hits/misses/foldins sum over the deployed
        algorithms' overlays; cursorLagEvents is the worst lag."""
        overlays = [ov for ov in getattr(self, "_speed_overlays", [])
                    if ov is not None]
        out = {"overlays": len(overlays), "size": 0,
               "hits": 0, "misses": 0, "foldins": 0, "cursorLagEvents": 0}
        for ov in overlays:
            try:
                s = ov.stats()
            except Exception:
                continue
            out["size"] += s["size"]
            out["hits"] += s["hits"]
            out["misses"] += s["misses"]
            out["foldins"] += s["foldins"]
            out["cursorLagEvents"] = max(out["cursorLagEvents"],
                                         s["cursorLagEvents"])
        return out

    @staticmethod
    def _mips_status() -> Dict[str, Any]:
        """MIPS index lifecycle block for /status: one stats() dict per
        registered index plus the rebuild daemon's state. Never raises
        — /status must survive a racing swap."""
        try:
            from incubator_predictionio_tpu.ops import (
                mips,
                mips_daemon,
            )

            return {"indexes": mips.status_snapshot(),
                    "daemon": mips_daemon.stats()}
        except Exception:
            logger.exception("mips status block failed")
            return {"indexes": [], "daemon": None}

    def _tenant_status_locked(self) -> Optional[Dict[str, Any]]:
        """The /status per-tenant block (caller holds ``self._lock``):
        registry policy + which deploy each tenant serves from + its
        queue depth / shed count / model staleness. ``None`` in
        single-tenant mode so pre-tenancy status readers see nothing
        new to misparse."""
        reg = tenancy.get_registry()
        deploys = getattr(self, "_deploys", {})
        if not reg and not deploys:
            return None
        batcher = getattr(self, "_batcher", None)
        sched = batcher.stats()["tenants"] if batcher is not None else {}
        out: Dict[str, Any] = {}
        for tid, desc in reg.describe().items():
            dep = deploys.get(tid)
            instance = (dep["engine_instance"] if dep is not None
                        else self.engine_instance)
            srow = sched.get(tid, {})
            out[tid] = {
                **desc,
                "engineInstanceId": instance.id if instance else None,
                "sharedDeploy": dep is None,
                "modelStalenessSec": (
                    max((now_utc() - ensure_aware(instance.end_time))
                        .total_seconds(), 0.0)
                    if instance is not None else None),
                "queueDepth": srow.get("depth", 0),
                "shed": srow.get("shed", 0),
                "servingSecP99": _QUERY_LATENCY.labels(
                    tenant=reg.label(tid)).quantile(0.99) or 0.0,
            }
        return out

    # -- auth for /stop, /reload (common/.../KeyAuthentication.scala:34) ----
    def _check_server_key(self, request: Request) -> None:
        provided = request.query.get("accessKey")
        if self.config.server_key is not None:
            if provided != self.config.server_key:
                raise HttpError(401, "Invalid accessKey.")
            return
        # No explicit key on the config: fall back to server.conf enforcement
        # (KeyAuthentication.ServerKey.authEnforced, KeyAuthentication.scala:39)
        if (self._conf_server_key is not None
                and not self._conf_server_key.check(provided)):
            raise HttpError(401, "Invalid accessKey.")

    # -- routes -------------------------------------------------------------
    def _build_router(self) -> Router:
        r = Router()

        @r.get("/")
        def status(request: Request) -> Response:
            with self._lock:
                instance = self.engine_instance
                info = {
                    "status": "alive",
                    "engineInstanceId": instance.id if instance else None,
                    "engineFactory": instance.engine_factory if instance else None,
                    "engineVariant": instance.engine_variant if instance else None,
                    "algorithms": [type(a).__name__ for a in self.algorithms],
                    "startTime": format_iso8601(self.start_time),
                    "requestCount": self.request_count,
                    "avgServingSec": self.avg_serving_sec,
                    "lastServingSec": self.last_serving_sec,
                    # tail latency from the query histogram: the running
                    # average the reference keeps (:426-428) hides tail
                    # regressions entirely — p50/p95/p99 on the status
                    # page make them visible without a scraper. Scope:
                    # process-wide histogram (all queries this process
                    # served), like requestCount after a /reload. 0.0
                    # before the first query — type-stable next to the
                    # always-numeric avgServingSec
                    "servingSecP50": _QUERY_LATENCY.quantile(0.50) or 0.0,
                    "servingSecP95": _QUERY_LATENCY.quantile(0.95) or 0.0,
                    "servingSecP99": _QUERY_LATENCY.quantile(0.99) or 0.0,
                    "maxBatchServed": self.max_batch_served,
                    "feedbackEventsDropped": self._feedback_poster.dropped,
                    # model staleness: seconds since the served instance
                    # finished training — the figure the speed layer
                    # exists to make tolerable (docs/production.md
                    # "Freshness between retrains")
                    "modelStalenessSec": (
                        max((now_utc() - ensure_aware(instance.end_time))
                            .total_seconds(), 0.0)
                        if instance is not None else None),
                    "speedOverlay": self._speed_status_locked(),
                    # per-index MIPS lifecycle state (tail, ext block,
                    # tiering split, age) + the rebuild daemon's trigger
                    # thresholds and recent swaps — the operator's view
                    # of "is churn outrunning the rebuild cadence"
                    # (docs/observability.md runbook)
                    "mips": self._mips_status(),
                    # continuous-batching scheduler state: per-engine
                    # queue depth + live ladder rung + shed count
                    # (serving/scheduler.py; docs/production.md
                    # "Serving fleet")
                    "scheduler": (self._batcher.stats()
                                  if self._batcher is not None else None),
                    # per-tenant block (deploys, queue depth, shed,
                    # staleness) — one status call answers "which
                    # tenant is hurting" (docs/production.md
                    # "Multi-tenant platform")
                    "tenants": self._tenant_status_locked(),
                }
            accept = request.headers.get("accept", "")
            if "text/html" in accept:
                rows = "".join(
                    f"<tr><th>{k}</th><td>{v}</td></tr>" for k, v in info.items()
                )
                return Response(
                    200,
                    body=(
                        "<html><head><title>PredictionIO-TPU Server</title>"
                        f"</head><body><h1>Engine is deployed and running.</h1>"
                        f"<table>{rows}</table></body></html>"
                    ).encode(),
                    content_type="text/html; charset=UTF-8",
                )
            return Response(200, info)

        @r.post("/queries.json")
        async def queries(request: Request) -> Response:
            import asyncio

            from incubator_predictionio_tpu.utils.http import sync

            try:
                # access-key auth (serving/tenancy.py): the same
                # accessKey grammar as the event server, mapped to a
                # tenant. Empty registry = single-tenant compatibility
                # mode (unauthenticated, tenant "default"); unknown or
                # disabled keys raise 401 here
                tenant = tenancy.get_registry().authenticate(request)
                if self._batcher is not None:
                    # priority orders only the scheduler's SHED decision
                    # (higher survives an overload longer) — admitted
                    # requests stay FIFO; malformed values mean 0
                    try:
                        prio = int(request.headers.get(
                            "x-pio-priority", "0"))
                    except ValueError:
                        prio = 0
                    result = await asyncio.wrap_future(
                        self._batcher.submit(
                            request.body, priority=prio,
                            engine=self.config.engine_id,
                            tenant=tenant))
                else:
                    result = await sync(self._handle_query, request.body,
                                        tenant)
            except HttpError as e:
                # the depth signal matters MOST on a shed: without it
                # the front door would keep the overloaded worker's
                # last (pre-overload) low reading and keep routing to
                # it (serving/frontdoor.py placement)
                if self._batcher is not None:
                    e.headers.setdefault("X-PIO-Queue-Depth",
                                         str(self._batcher.depth()))
                raise
            except (ValueError, KeyError) as e:
                return Response(400, {"message": str(e)})
            # queue-depth piggyback: the front door's placement signal,
            # refreshed for free on every response instead of waiting
            # for its next /metrics scrape (serving/frontdoor.py)
            depth_headers = (
                {"X-PIO-Queue-Depth": str(self._batcher.depth())}
                if self._batcher is not None else {})
            if isinstance(result, (bytes, bytearray)):
                # batch_serve_json fast path: body already rendered
                return Response(200, body=bytes(result),
                                headers=depth_headers)
            return Response(200, result, headers=depth_headers)

        @r.post("/reload")
        def reload(request: Request) -> Response:
            self._check_server_key(request)
            # double-buffered: new models warm (compiles + host mirrors,
            # shapes may differ — catalog size, rank) BEFORE the swap;
            # the old models serve every query until then. Serialized so
            # overlapping reloads cannot swap instances out of order.
            # ?tenant=X scopes the refresh to one co-resident deploy —
            # every other tenant keeps serving through it.
            tenant = request.query.get("tenant") or None
            with self._reload_lock:
                self.load_models(warm_before_swap=True, tenant=tenant)
            self._sync_tenant_policy()
            return Response(200, {
                "message": (f"Reloaded tenant {tenant}." if tenant
                            else "Reloaded.")})

        @r.post("/knobs")
        def post_knobs(request: Request) -> Response:
            # the worker half of the audited knob seam (obs/knobs.py):
            # the knob controller's front-door fan-out lands here with
            # the decision's trace headers. Every registered knob is a
            # call-time env read, so writing the env + one scheduler
            # refresh applies the vector without restart or drain. The
            # unaudited-knob-write lint rule sanctions knob env writes
            # in exactly this route (and KnobController._apply).
            self._check_server_key(request)
            from incubator_predictionio_tpu.obs import knobs as obs_knobs

            try:
                payload = json.loads(request.body or b"{}")
                values = payload.get("values") or {}
                items = {str(k): int(v) for k, v in values.items()}
            except (ValueError, TypeError, AttributeError) as e:
                return Response(400, {"message": f"bad knob body: {e}"})
            unknown = sorted(set(items) - obs_knobs.KNOB_ENV_VARS)
            if unknown:
                # reject the WHOLE vector: a partial apply would leave
                # the fleet on a vector no decision record describes
                return Response(400, {
                    "message": "unregistered knob env vars",
                    "unknown": unknown,
                })
            applied = {}
            for env, v in sorted(items.items()):
                os.environ[env] = str(v)
                applied[env] = v
            scheduler = (self._batcher.apply_knobs()
                         if self._batcher is not None else None)
            return Response(200, {"applied": applied,
                                  "scheduler": scheduler})

        @r.post("/stop")
        def stop_route(request: Request) -> Response:
            self._check_server_key(request)
            # daemonized: if the process is torn down some other way
            # first, a pending non-daemon timer would block exit
            timer = threading.Timer(0.2, self.stop)
            timer.daemon = True
            timer.start()
            return Response(200, {"message": "Shutting down."})

        @r.get("/plugins.json")
        def plugins_list(request: Request) -> Response:
            return Response(200, {
                "plugins": {
                    "outputblockers": {
                        n: {"name": n}
                        for n in self.plugin_context.output_blockers
                    },
                    "outputsniffers": {
                        n: {"name": n}
                        for n in self.plugin_context.output_sniffers
                    },
                }
            })

        @r.get("/plugins/{tail...}")
        def plugins_rest(request: Request) -> Response:
            parts = request.path_params["tail"].split("/")
            plugin = self.plugin_context.plugin(parts[0])
            if plugin is None:
                return Response(404, {"message": "Not Found"})
            return Response(
                200, plugin.handle_rest("/".join(parts[1:]), dict(request.query))
            )

        add_metrics_route(r)
        # GET /recorder: pre-breach metric history on the worker itself —
        # the admin's incident capture pulls this (docs/observability.md
        # "Flight recorder & incidents")
        add_recorder_route(r)
        return r

    # -- lifecycle ----------------------------------------------------------
    def undeploy_existing(self) -> None:
        """Stop any engine server already deployed at this address before
        binding (MasterActor.undeploy, CreateServer.scala:283-308): 200 →
        old deployment stopped; connection refused → nothing there; any
        other response → a foreign process owns the port (bind-retry will
        surface the conflict). The scheme follows this server's own TLS
        config (a stale deployment shares server.conf), and the key falls
        back to server.conf like /stop auth itself does."""
        if self.config.port == 0:
            return  # ephemeral port: nothing can be squatting on it
        ip = self.config.ip if self.config.ip != "0.0.0.0" else "127.0.0.1"
        key = self.config.server_key
        if key is None and self._conf_server_key is not None:
            key = self._conf_server_key.key
        scheme = "https" if self.http.ssl_context is not None else "http"
        try:
            status = _stop_request(ip, self.config.port, key, scheme=scheme)
            if status == 200:
                logger.info(
                    "Undeployed existing engine server at %s:%d",
                    ip, self.config.port)
                time.sleep(0.5)  # give the old process time to unbind
            else:
                logger.error(
                    "Another process is using %s:%d (HTTP %d on /stop). "
                    "Unable to undeploy.", ip, self.config.port, status)
        except ConnectionRefusedError:
            logger.debug("Nothing at %s:%d", ip, self.config.port)
        except urllib.error.URLError as e:
            if isinstance(e.reason, ConnectionRefusedError):
                logger.debug("Nothing at %s:%d", ip, self.config.port)
            else:
                # something answered the socket but not the protocol
                # (hung process, TLS mismatch, timeout) — that is NOT
                # "nothing there"; say so before bind-retry fights it
                logger.warning(
                    "A process at %s:%d did not respond properly to "
                    "/stop (%s); unable to undeploy.",
                    ip, self.config.port, e.reason)
        except Exception as e:
            logger.warning(
                "A process at %s:%d did not respond properly to /stop "
                "(%s); unable to undeploy.", ip, self.config.port, e)

    def _warm_models(self, algorithms, models) -> None:
        """Warm every algorithm's serving dispatches (compiles + host
        mirrors). One copy of the max_batch rule and the per-algo
        except-log-continue contract, shared by the async startup warmup
        and the pre-swap /reload warmup. Failures are logged, never
        fatal: warmup is an optimization, the query path compiles on
        demand regardless."""
        # a disabled micro-batcher means live traffic never reaches the
        # batched dispatch — don't compile it
        max_batch = self.config.micro_batch if self._batcher is not None else 0
        for algo, model in zip(algorithms, models):
            try:
                algo.warmup(model, max_batch=max_batch)
            except Exception:
                logger.exception(
                    "serving warmup failed for %s (first queries will "
                    "compile on demand)", type(algo).__name__)

    def _warmup_async(self) -> None:
        """Pre-compile serving dispatches on a daemon thread AFTER the
        server binds — the first real query otherwise pays the XLA compile
        (seconds on TPU). The thread waits on the HTTP server's started
        event so warmup tracing never delays the bind (the foreground
        serve_forever path spawns this before the loop starts)."""
        algorithms, models = self.algorithms, self.models

        def run() -> None:
            if not self.http.wait_started(60.0):
                logger.warning(
                    "serving warmup skipped: server did not bind within "
                    "60s (queries will compile on demand if it ever does)")
                return
            t0 = time.perf_counter()
            self._warm_models(algorithms, models)
            logger.info("serving warmup done in %.1fs",
                        time.perf_counter() - t0)

        threading.Thread(target=run, daemon=True,
                         name="pio-serving-warmup").start()

    def start_background(self) -> int:
        self.load_models()
        self.undeploy_existing()
        port = self.http.start_background()
        self._warmup_async()
        logger.info("PredictionServer started on %s:%d", self.config.ip, port)
        return port

    async def serve_forever(self) -> None:
        self.load_models()
        self.undeploy_existing()
        self._warmup_async()
        await self.http.serve_forever()

    def stop(self) -> None:
        if self._batcher is not None:
            self._batcher.stop()
        with self._lock:
            held = getattr(self, "_mips_daemon_held", False)
            self._mips_daemon_held = False
        if held:
            try:
                from incubator_predictionio_tpu.ops import mips_daemon

                mips_daemon.release()
            except Exception:
                logger.exception("mips rebuild daemon stop failed")
        for ov in getattr(self, "_speed_overlays", []):
            if ov is None:
                continue
            try:
                ov.stop()
            except Exception:
                logger.exception("speed overlay stop failed")
        self._feedback_poster.stop()
        self._log_poster.stop()
        self.http.stop()


def _stop_request(ip: str, port: int, server_key: Optional[str],
                  scheme: str = "http", timeout: float = 5.0) -> int:
    """POST /stop → HTTP status (one shared implementation for the CLI
    undeploy verb and undeploy-before-deploy). Raises on connection
    failure. https uses an unverified context (the reference's
    allowUnsafeSSL — self-signed server.conf material is the norm)."""
    import ssl as ssl_mod
    from urllib.parse import quote

    url = f"{scheme}://{ip}:{port}/stop"
    if server_key:
        url += f"?accessKey={quote(server_key, safe='')}"
    ctx = ssl_mod._create_unverified_context() if scheme == "https" else None
    req = urllib.request.Request(url, method="POST", data=b"")
    try:
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def undeploy(ip: str, port: int, server_key: Optional[str] = None,
             scheme: str = "http") -> bool:
    """POST /stop to a running server (commands/Engine.undeploy:341)."""
    try:
        return _stop_request(ip, port, server_key, scheme=scheme) == 200
    except Exception:
        return False
