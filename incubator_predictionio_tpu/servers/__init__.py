"""HTTP servers: event ingestion, prediction serving, admin, dashboard.

Parity: EventServer (data/.../api/EventServer.scala), PredictionServer
(core/.../workflow/CreateServer.scala), AdminAPI (tools/.../admin/),
Dashboard (tools/.../dashboard/) — rebuilt on the asyncio micro-framework
(utils/http.py) with TPU-resident model state in the prediction server.
"""
