"""Server plugin SPI.

Parity: EventServerPlugin (data/.../api/EventServerPlugin.scala:21-33 —
``inputBlockers`` veto events synchronously, ``inputSniffers`` observe
asynchronously) and EngineServerPlugin (core/.../workflow/
EngineServerPlugin.scala:24-40 — ``outputBlockers`` rewrite/veto
predictions, ``outputSniffers`` observe). The reference loads plugins via
JVM ServiceLoader; here registration is explicit (or importable via the
``PIO_PLUGINS`` env var: comma-separated ``module:attr`` entries).
"""

from __future__ import annotations

import dataclasses
import importlib
import logging
import os
from typing import Any, Dict, List, Optional

from incubator_predictionio_tpu.data.event import Event

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class EventInfo:
    """EventServerPlugin.scala EventInfo."""

    app_id: int
    channel_id: Optional[int]
    event: Event


class EventServerPlugin:
    """Subclass and set ``input_blocker=True`` to veto (raise) or
    ``input_sniffer=True`` to observe."""

    input_blocker = False
    input_sniffer = False

    def process(self, event_info: EventInfo, context: "PluginContext") -> None:
        raise NotImplementedError

    def handle_rest(self, path: str, params: Dict[str, Any]) -> Any:
        """GET /plugins/... passthrough (EventServer.scala:462-520)."""
        return {"message": "plugin has no REST handler"}


class EngineServerPlugin:
    output_blocker = False
    output_sniffer = False

    def process(self, engine_variant: str, query: Any, prediction: Any,
                context: "PluginContext") -> Any:
        """Blockers return the (possibly rewritten) prediction."""
        raise NotImplementedError

    def handle_rest(self, path: str, params: Dict[str, Any]) -> Any:
        return {"message": "plugin has no REST handler"}


class PluginContext:
    """EventServerPluginContext / EngineServerPluginContext."""

    def __init__(self, plugins: Optional[List[Any]] = None,
                 params: Optional[Dict[str, Any]] = None):
        self.plugins: List[Any] = list(plugins or [])
        self.params: Dict[str, Any] = dict(params or {})
        self.plugins.extend(_load_env_plugins())

    # -- event-server side --------------------------------------------------
    @property
    def input_blockers(self) -> Dict[str, EventServerPlugin]:
        return {
            type(p).__name__: p for p in self.plugins
            if getattr(p, "input_blocker", False)
        }

    @property
    def input_sniffers(self) -> Dict[str, EventServerPlugin]:
        return {
            type(p).__name__: p for p in self.plugins
            if getattr(p, "input_sniffer", False)
        }

    # -- engine-server side -------------------------------------------------
    @property
    def output_blockers(self) -> Dict[str, EngineServerPlugin]:
        return {
            type(p).__name__: p for p in self.plugins
            if getattr(p, "output_blocker", False)
        }

    @property
    def output_sniffers(self) -> Dict[str, EngineServerPlugin]:
        return {
            type(p).__name__: p for p in self.plugins
            if getattr(p, "output_sniffer", False)
        }

    def plugin(self, name: str) -> Optional[Any]:
        for p in self.plugins:
            if type(p).__name__ == name:
                return p
        return None


def _load_env_plugins() -> List[Any]:
    """PIO_PLUGINS=pkg.mod:PluginClass,other.mod:Other — the explicit
    replacement for ServiceLoader classpath scanning."""
    spec = os.environ.get("PIO_PLUGINS", "")
    out: List[Any] = []
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        try:
            module_name, _, attr = entry.partition(":")
            cls = getattr(importlib.import_module(module_name), attr)
            out.append(cls())
        except Exception:
            logger.exception("failed to load plugin %r", entry)
    return out
