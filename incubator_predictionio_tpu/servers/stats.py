"""In-memory ingest statistics.

Parity: data/.../api/{Stats,StatsActor}.scala — per-app counters keyed by
(status, event name), kept for the previous and current hour (hourly
cutoff, Stats.scala:51-80), served by ``GET /stats.json``.
"""

from __future__ import annotations

import threading
from datetime import datetime, timedelta, timezone
from typing import Dict, Tuple

from incubator_predictionio_tpu.utils.times import format_iso8601, now_utc

KPI = Dict[Tuple[int, str], int]  # (status, event-name) -> count


def _hour_start(dt: datetime) -> datetime:
    return dt.replace(minute=0, second=0, microsecond=0)


class Stats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hour = _hour_start(now_utc())
        self._current: Dict[int, KPI] = {}
        self._previous: Dict[int, KPI] = {}

    def _rotate(self) -> None:
        """Hourly cutoff — must run on reads too, so a quiet server doesn't
        report stale hours as the current window (Stats.scala:51-80)."""
        now = _hour_start(now_utc())
        if now == self._hour:
            return
        # counts from exactly the last hour become "previous"; older ones drop
        self._previous = (
            self._current if now - self._hour == timedelta(hours=1) else {}
        )
        self._current = {}
        self._hour = now

    def update(self, app_id: int, status: int, event_name: str) -> None:
        with self._lock:
            self._rotate()
            kpi = self._current.setdefault(app_id, {})
            key = (status, event_name)
            kpi[key] = kpi.get(key, 0) + 1

    def get(self, app_id: int) -> dict:
        """Previous + current hour counts for an app (Stats.get)."""
        with self._lock:
            self._rotate()
            merged: KPI = {}
            for source in (self._previous, self._current):
                for key, n in source.get(app_id, {}).items():
                    merged[key] = merged.get(key, 0) + n
            return {
                "startTime": format_iso8601(self._hour - timedelta(hours=1)),
                "until": format_iso8601(now_utc()),
                "appId": app_id,
                "status": [
                    {"status": status, "event": event, "count": n}
                    for (status, event), n in sorted(merged.items())
                ],
            }
