"""ctypes wrapper for the native COO → padded-rows builder.

Produces exactly the same bucket layout as the numpy path in
``ops/sparse.py`` (stable within-row order, power-of-two widths, heavy rows
split at ``max_width``) — the test suite asserts bit-equality — but the
per-row fill loop runs in C++ (``src/csr_builder.cc``) instead of the
Python interpreter, which is what makes ML-20M-scale training reads cheap.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu import native


def _as_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def native_available() -> bool:
    return native.load() is not None


def bucket_counts_from_degrees(
    degrees: np.ndarray, min_width: int, max_width: int, n_buckets: int
) -> np.ndarray:
    """Per-bucket segment counts from a per-row degree histogram — the
    same numbers ``pio_csr_plan`` derives from one O(nnz) pass over the
    rows array, computed instead from degrees alone (O(n_rows),
    vectorized). The pipelined ingest path accumulates the degree
    histogram per scan shard WHILE the scan is still running, so the
    plan pass is already paid when prep starts."""
    d = np.asarray(degrees, np.int64)
    counts = np.zeros(n_buckets, np.int64)
    # rows longer than max_width split into full-width segments + a tail
    counts[n_buckets - 1] += int((d // max_width).sum())
    rem = d % max_width
    rem = rem[rem > 0]
    widths = np.int64(min_width) << np.arange(n_buckets, dtype=np.int64)
    counts += np.bincount(
        np.searchsorted(widths, rem, side="left"), minlength=n_buckets)
    return counts


def build_buckets_native(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    min_width: int,
    max_width: int,
    degrees: Optional[np.ndarray] = None,
) -> Optional[List[Tuple[int, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]]:
    """Returns [(width, row_ids, cols, vals, mask)] per non-empty bucket,
    width-ascending, or None when the native library is unavailable.

    ``degrees`` (optional, int64[n_rows] with ``degrees.sum() == nnz``):
    a precomputed per-row nnz histogram replacing the native plan pass.
    The fill is safe against a wrong histogram: the native fill bound-
    checks every bucket and reports the segment total, and any mismatch
    falls back to the exact plan — worst case is one wasted allocation,
    never corrupt buckets."""
    lib = native.load()
    if lib is None:
        return None
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if len(rows) and (
        int(rows.max()) >= 2**31 or int(cols.max()) >= 2**31
        or int(rows.min()) < 0 or int(cols.min()) < 0
    ):
        # int32 cast below would silently wrap; let the caller take the
        # numpy (int64) path instead of corrupting buckets
        return None
    rows32 = np.ascontiguousarray(rows, np.int32)
    cols32 = np.ascontiguousarray(cols, np.int32)
    vals32 = np.ascontiguousarray(vals, np.float32)
    nnz = rows32.shape[0]
    n_buckets = 1
    while (min_width << (n_buckets - 1)) < max_width:
        n_buckets += 1

    def exact_counts() -> np.ndarray:
        counts = np.zeros(n_buckets, np.int64)
        rc = lib.pio_csr_plan(
            _as_ptr(rows32, ctypes.c_int32), nnz, n_rows,
            min_width, max_width, n_buckets, _as_ptr(counts, ctypes.c_int64),
        )
        if rc != 0:
            raise ValueError("csr plan failed (row index out of range?)")
        return counts

    counts = None
    if degrees is not None:
        d = np.asarray(degrees, np.int64)
        if d.shape == (n_rows,) and (
                len(d) == 0 or int(d.min()) >= 0) and int(d.sum()) == nnz:
            counts = bucket_counts_from_degrees(
                d, min_width, max_width, n_buckets)
    from_degrees = counts is not None
    if counts is None:
        counts = exact_counts()

    row_ids = [np.zeros(int(c), np.int32) for c in counts]
    out_cols = [np.zeros((int(c), min_width << b), np.int32)
                for b, c in enumerate(counts)]
    out_vals = [np.zeros((int(c), min_width << b), np.float32)
                for b, c in enumerate(counts)]
    out_mask = [np.zeros((int(c), min_width << b), np.float32)
                for b, c in enumerate(counts)]

    def ptr_array(arrs, ctype):
        pp = (ctypes.POINTER(ctype) * n_buckets)()
        for i, a in enumerate(arrs):
            pp[i] = _as_ptr(a, ctype)
        return pp

    rc = lib.pio_csr_fill(
        _as_ptr(rows32, ctypes.c_int32), _as_ptr(cols32, ctypes.c_int32),
        _as_ptr(vals32, ctypes.c_float), nnz, n_rows,
        min_width, max_width, n_buckets, _as_ptr(counts, ctypes.c_int64),
        ptr_array(row_ids, ctypes.c_int32), ptr_array(out_cols, ctypes.c_int32),
        ptr_array(out_vals, ctypes.c_float), ptr_array(out_mask, ctypes.c_float),
    )
    if rc != int(counts.sum()):
        # a degree-derived plan disagreed with the data (under-allocation
        # is rejected natively, over-allocation shows as a segment-count
        # shortfall): redo with the exact plan — never serve junk rows
        if from_degrees:
            return build_buckets_native(
                rows32, cols32, vals32, n_rows, min_width, max_width)
        raise ValueError("csr fill failed")
    return [
        (min_width << b, row_ids[b], out_cols[b], out_vals[b], out_mask[b])
        for b in range(n_buckets) if counts[b] > 0
    ]
