"""ctypes wrapper for the native COO → padded-rows builder.

Produces exactly the same bucket layout as the numpy path in
``ops/sparse.py`` (stable within-row order, power-of-two widths, heavy rows
split at ``max_width``) — the test suite asserts bit-equality — but the
per-row fill loop runs in C++ (``src/csr_builder.cc``) instead of the
Python interpreter, which is what makes ML-20M-scale training reads cheap.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu import native


def _as_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def native_available() -> bool:
    return native.load() is not None


def build_buckets_native(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    min_width: int,
    max_width: int,
) -> Optional[List[Tuple[int, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]]:
    """Returns [(width, row_ids, cols, vals, mask)] per non-empty bucket,
    width-ascending, or None when the native library is unavailable."""
    lib = native.load()
    if lib is None:
        return None
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if len(rows) and (
        int(rows.max()) >= 2**31 or int(cols.max()) >= 2**31
        or int(rows.min()) < 0 or int(cols.min()) < 0
    ):
        # int32 cast below would silently wrap; let the caller take the
        # numpy (int64) path instead of corrupting buckets
        return None
    rows32 = np.ascontiguousarray(rows, np.int32)
    cols32 = np.ascontiguousarray(cols, np.int32)
    vals32 = np.ascontiguousarray(vals, np.float32)
    nnz = rows32.shape[0]
    n_buckets = 1
    while (min_width << (n_buckets - 1)) < max_width:
        n_buckets += 1
    counts = np.zeros(n_buckets, np.int64)
    rc = lib.pio_csr_plan(
        _as_ptr(rows32, ctypes.c_int32), nnz, n_rows,
        min_width, max_width, n_buckets, _as_ptr(counts, ctypes.c_int64),
    )
    if rc != 0:
        raise ValueError("csr plan failed (row index out of range?)")

    row_ids = [np.zeros(int(c), np.int32) for c in counts]
    out_cols = [np.zeros((int(c), min_width << b), np.int32)
                for b, c in enumerate(counts)]
    out_vals = [np.zeros((int(c), min_width << b), np.float32)
                for b, c in enumerate(counts)]
    out_mask = [np.zeros((int(c), min_width << b), np.float32)
                for b, c in enumerate(counts)]

    def ptr_array(arrs, ctype):
        pp = (ctypes.POINTER(ctype) * n_buckets)()
        for i, a in enumerate(arrs):
            pp[i] = _as_ptr(a, ctype)
        return pp

    rc = lib.pio_csr_fill(
        _as_ptr(rows32, ctypes.c_int32), _as_ptr(cols32, ctypes.c_int32),
        _as_ptr(vals32, ctypes.c_float), nnz, n_rows,
        min_width, max_width, n_buckets,
        ptr_array(row_ids, ctypes.c_int32), ptr_array(out_cols, ctypes.c_int32),
        ptr_array(out_vals, ctypes.c_float), ptr_array(out_mask, ctypes.c_float),
    )
    if rc != 0:
        raise ValueError("csr fill failed")
    return [
        (min_width << b, row_ids[b], out_cols[b], out_vals[b], out_mask[b])
        for b in range(n_buckets) if counts[b] > 0
    ]
