"""Native (C++) runtime components and their lazy build.

The reference is pure JVM — its native performance arrives transitively via
Spark/netlib (SURVEY.md "Languages"). This framework's compute path is
XLA/Pallas; the *runtime around it* is native where it is hot:

- ``src/eventlog.cc``  — append-only event-store engine (the HBase-driver
  role, data/.../storage/hbase/ in the reference)
- ``src/csr_builder.cc`` — COO → degree-bucketed padded rows (the host data
  loader feeding device ingest)

The shared library is compiled on first use with the system ``g++`` (no pip
deps, mirroring how the reference compiles engines on demand via ``pio
build`` → sbt, tools/.../commands/Engine.scala:158-225) and cached next to
the sources keyed on their mtimes. Everything degrades gracefully: callers
check :func:`load` for ``None`` and fall back to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = Path(__file__).parent / "src"
_BUILD_DIR = Path(__file__).parent / "_build"
_SOURCES = ("eventlog.cc", "csr_builder.cc", "jsonparse.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def lib_path() -> Path:
    return _BUILD_DIR / "libpio_native.so"


def _needs_build(so: Path) -> bool:
    if not so.exists():
        return True
    so_mtime = so.stat().st_mtime
    return any(
        (_SRC_DIR / s).stat().st_mtime > so_mtime for s in _SOURCES
    )


def build(force: bool = False) -> Path:
    """Compile the native library (idempotent; mtime-cached)."""
    so = lib_path()
    if not force and not _needs_build(so):
        return so
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # compile to a process-unique temp name, then atomically rename: two
    # processes racing a cold build must never CDLL a half-written .so
    tmp = so.with_suffix(f".so.tmp{os.getpid()}")
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *[str(_SRC_DIR / s) for s in _SOURCES],
        "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, so)
    finally:
        tmp.unlink(missing_ok=True)
    return so


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    u64p = c.POINTER(c.c_uint64)
    i64p = c.POINTER(c.c_int64)
    # eventlog
    lib.pio_evlog_open.restype = c.c_void_p
    lib.pio_evlog_open.argtypes = [c.c_char_p]
    lib.pio_evlog_close.restype = None
    lib.pio_evlog_close.argtypes = [c.c_void_p]
    lib.pio_evlog_append.restype = c.c_int64
    lib.pio_evlog_append.argtypes = [
        c.c_void_p, c.c_int64, c.c_uint64, c.c_uint64, c.c_uint64,
        c.c_uint64, c.c_char_p, c.c_uint32,
    ]
    lib.pio_evlog_tombstone.restype = c.c_int64
    lib.pio_evlog_tombstone.argtypes = [c.c_void_p, c.c_int64]
    lib.pio_evlog_count.restype = c.c_int64
    lib.pio_evlog_count.argtypes = [c.c_void_p]
    lib.pio_evlog_compact_copy.restype = c.c_int64
    lib.pio_evlog_compact_copy.argtypes = [c.c_void_p, c.c_char_p]
    lib.pio_evlog_query.restype = c.c_int64
    lib.pio_evlog_query.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64, c.c_uint64, c.c_uint64,
        u64p, c.c_int32, c.c_int32, c.c_int64, i64p, c.c_int64,
    ]
    lib.pio_evlog_find_id.restype = c.c_int64
    lib.pio_evlog_find_id.argtypes = [c.c_void_p, c.c_uint64, i64p, c.c_int64]
    lib.pio_evlog_read.restype = c.c_int32
    lib.pio_evlog_read.argtypes = [
        c.c_void_p, c.c_int64, c.c_char_p, c.c_int32,
    ]
    lib.pio_evlog_sync.restype = c.c_int64
    lib.pio_evlog_sync.argtypes = [c.c_void_p]
    lib.pio_evlog_entry_count.restype = c.c_int64
    lib.pio_evlog_entry_count.argtypes = [c.c_void_p]
    lib.pio_evlog_dead_count.restype = c.c_int64
    lib.pio_evlog_dead_count.argtypes = [c.c_void_p]
    lib.pio_evlog_file_size.restype = c.c_int64
    lib.pio_evlog_file_size.argtypes = [c.c_void_p]
    lib.pio_evlog_read_frames.restype = c.c_int64
    lib.pio_evlog_read_frames.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64, c.c_char_p, i64p]
    lib.pio_evlog_append_frames.restype = c.c_int64
    lib.pio_evlog_append_frames.argtypes = [c.c_void_p, c.c_char_p,
                                            c.c_int64]
    lib.pio_evlog_hash_ids.restype = c.c_int64
    lib.pio_evlog_hash_ids.argtypes = [c.c_char_p, i64p, c.c_int64,
                                       c.POINTER(c.c_uint64)]
    # columnar interaction scan ([min, max) entry range + thread count; the
    # mutex is held only for the header snapshot — see eventlog.cc)
    lib.pio_evlog_scan_interactions.restype = c.c_void_p
    lib.pio_evlog_scan_interactions.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64, c.c_int64, c.c_int64, c.c_char_p,
        c.c_char_p, c.POINTER(c.c_char_p), c.POINTER(c.c_double), c.c_int32,
        c.c_char_p, c.c_double, c.c_int32,
    ]
    lib.pio_scan_nnz.restype = c.c_int64
    lib.pio_scan_nnz.argtypes = [c.c_void_p]
    lib.pio_scan_lock_held_ns.restype = c.c_int64
    lib.pio_scan_lock_held_ns.argtypes = [c.c_void_p]
    lib.pio_scan_n_ids.restype = c.c_int64
    lib.pio_scan_n_ids.argtypes = [c.c_void_p, c.c_int32]
    lib.pio_scan_ids_bytes.restype = c.c_int64
    lib.pio_scan_ids_bytes.argtypes = [c.c_void_p, c.c_int32]
    lib.pio_scan_fill.restype = None
    lib.pio_scan_fill.argtypes = [
        c.c_void_p, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        c.POINTER(c.c_float),
    ]
    lib.pio_scan_fill_times.restype = None
    lib.pio_scan_fill_times.argtypes = [c.c_void_p, i64p]
    lib.pio_scan_copy_ids.restype = None
    lib.pio_scan_copy_ids.argtypes = [
        c.c_void_p, c.c_int32, c.c_char_p, i64p,
    ]
    lib.pio_scan_free.restype = None
    lib.pio_scan_free.argtypes = [c.c_void_p]
    lib.pio_evlog_append_bulk.restype = c.c_int64
    lib.pio_evlog_append_bulk.argtypes = [
        c.c_void_p, c.c_int64, i64p, c.c_char_p, i64p, c.c_char_p,
    ]
    lib.pio_evlog_append_interactions.restype = c.c_int64
    lib.pio_evlog_append_interactions.argtypes = [
        c.c_void_p, c.c_int64, i64p,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_float),
        c.c_char_p, i64p, c.c_int64,
        c.c_char_p, i64p, c.c_int64,
        c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p, c.c_uint64,
    ]
    # csr builder
    pp_i32 = c.POINTER(c.POINTER(c.c_int32))
    pp_f32 = c.POINTER(c.POINTER(c.c_float))
    lib.pio_csr_plan.restype = c.c_int64
    lib.pio_csr_plan.argtypes = [
        c.POINTER(c.c_int32), c.c_int64, c.c_int64, c.c_int32, c.c_int32,
        c.c_int32, i64p,
    ]
    lib.pio_csr_fill.restype = c.c_int64
    lib.pio_csr_fill.argtypes = [
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_float),
        c.c_int64, c.c_int64, c.c_int32, c.c_int32, c.c_int32, i64p,
        pp_i32, pp_i32, pp_f32, pp_f32,
    ]
    # uniform-batch JSON parser (REST ingest hot path)
    lib.pio_parse_uniform_batch.restype = c.c_int64
    lib.pio_parse_uniform_batch.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_float),
        c.c_char_p, c.c_int64, i64p, i64p,
        c.c_char_p, c.c_int64, i64p, i64p,
        c.c_char_p, c.c_int64, i64p,
    ]


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            so = build()
            lib = ctypes.CDLL(str(so))
            _declare(lib)
            _lib = lib
        except Exception as exc:  # toolchain missing / compile error
            _load_failed = True
            logger.warning(
                "native library unavailable (%s); using pure-Python "
                "fallbacks", exc)
    return _lib


def fnv1a64(data: bytes) -> int:
    """FNV-1a 64-bit — the hash the eventlog headers use for predicate
    pushdown. 0 is reserved as the "no filter" sentinel, so real hashes of 0
    are mapped to 1 (a one-in-2⁶⁴ bias, invisible next to the exact-match
    recheck in the DAO)."""
    h = 0xCBF29CE484222325
    for byte in data:
        h ^= byte
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h or 1


def fnv1a64_table(blob: bytes, offsets):
    """FNV-1a of every entry of an interned id table (blob + int64
    offsets, the IdTable layout) in ONE native crossing — the
    writer-shard spray hashes whole tables per batch, and a per-id
    Python loop is ~1000x the cost of the hash itself. Returns a
    uint64 array of len(offsets)-1; falls back to pure Python when the
    native library is unavailable."""
    import numpy as np

    n = max(len(offsets) - 1, 0)
    offs = np.ascontiguousarray(offsets, np.int64)
    out = np.empty(n, np.uint64)
    lib = load()
    if lib is not None:
        rc = lib.pio_evlog_hash_ids(
            blob, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        if rc == n:
            return out
    for i in range(n):
        out[i] = fnv1a64(blob[offs[i]:offs[i + 1]])
    return out
