// Native uniform-batch JSON parser — the REST ingest hot path's
// body-bytes → columnar-arrays leg, in C++ so it runs GIL-released.
//
// Scope is a STRICT SUBSET of the Python doc gate
// (data/storage/base.py uniform_interactions_from_docs): anything this
// parser accepts, the Python gate provably accepts with identical output
// (pinned by a randomized differential test); anything unusual — string
// escapes, eventTime, reserved-prefix names, non-f32-exact values,
// numbers near double precision, oversized fields — returns -1 and the
// caller falls back to the Python path, which owns the full semantics.
// The reference's ingest parses every event into a case class on the JVM
// (data/.../api/EventServer.scala + EventJson4sSupport); here the
// machine-generated wire shape never materializes per-event objects in
// either language.
//
// Build: compiled into libpio_native.so next to eventlog.cc (see
// native/__init__.py _SOURCES).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool lit(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    ok = false;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == c;
  }
};

// String without escapes: returns the raw byte span between quotes.
// Rejects backslash (escape semantics stay in Python), control chars,
// and unterminated strings.
bool parse_string(Cursor& c, std::string_view* out) {
  if (!c.lit('"')) return false;
  const char* start = c.p;
  while (c.p < c.end) {
    unsigned char ch = (unsigned char)*c.p;
    if (ch == '"') {
      *out = std::string_view(start, (size_t)(c.p - start));
      ++c.p;
      return true;
    }
    if (ch == '\\' || ch < 0x20) return false;
    ++c.p;
  }
  return false;
}

// Strict JSON number grammar, with conservative precision screens so the
// double arithmetic below provably matches Python's arbitrary-precision
// comparison: <=15 significant digits and |exponent| <= 30.
bool parse_number(Cursor& c, double* out) {
  c.ws();
  const char* start = c.p;
  if (c.p < c.end && *c.p == '-') ++c.p;
  if (c.p >= c.end) return false;
  int int_digits = 0;
  if (*c.p == '0') {
    ++c.p;
    int_digits = 1;
  } else if (*c.p >= '1' && *c.p <= '9') {
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') {
      ++c.p;
      ++int_digits;
    }
  } else {
    return false;
  }
  int frac_digits = 0;
  if (c.p < c.end && *c.p == '.') {
    ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') {
      ++c.p;
      ++frac_digits;
    }
  }
  long expv = 0;
  if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
    ++c.p;
    bool neg = false;
    if (c.p < c.end && (*c.p == '+' || *c.p == '-')) {
      neg = (*c.p == '-');
      ++c.p;
    }
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') {
      expv = expv * 10 + (*c.p - '0');
      if (expv > 1000) return false;
      ++c.p;
    }
    if (neg) expv = -expv;
  }
  if (int_digits + frac_digits > 15) return false;
  if (expv < -30 || expv > 30) return false;
  std::string buf(start, (size_t)(c.p - start));
  char* endp = nullptr;
  double v = strtod(buf.c_str(), &endp);
  if (endp != buf.c_str() + buf.size()) return false;
  *out = v;
  return true;
}

constexpr size_t kMaxField = 200;  // ids and scalar fields, bytes

// Strict UTF-8 validation (rejects overlongs, surrogates, >U+10FFFF) —
// Python's utf-8 decode on the json.loads path rejects the same set, so
// accepting less keeps the strict-subset contract: an undecodable id
// must 400 via the generic path, never persist as raw bytes.
bool valid_utf8(std::string_view s) {
  size_t i = 0;
  const size_t n = s.size();
  while (i < n) {
    unsigned char c = (unsigned char)s[i];
    if (c < 0x80) {
      ++i;
      continue;
    }
    int extra;
    unsigned cp, cp_min;
    if ((c & 0xE0) == 0xC0) {
      extra = 1;
      cp = c & 0x1F;
      cp_min = 0x80;
    } else if ((c & 0xF0) == 0xE0) {
      extra = 2;
      cp = c & 0x0F;
      cp_min = 0x800;
    } else if ((c & 0xF8) == 0xF0) {
      extra = 3;
      cp = c & 0x07;
      cp_min = 0x10000;
    } else {
      return false;
    }
    if (i + (size_t)extra >= n) return false;
    for (int k = 1; k <= extra; ++k) {
      unsigned char cc = (unsigned char)s[i + (size_t)k];
      if ((cc & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (cp < cp_min || cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF))
      return false;
    i += (size_t)extra + 1;
  }
  return true;
}

bool reserved_prefix(std::string_view s) {
  // conservative superset of the Python reserved screens: anything
  // starting with '$' or 'pio_' falls back (the Python gate knows the
  // builtin whitelists; this parser does not need to)
  return (!s.empty() && s[0] == '$') ||
         (s.size() >= 4 && s.substr(0, 4) == "pio_");
}

struct Intern {
  std::unordered_map<std::string_view, int32_t> map;
  char* blob;
  int64_t cap;
  int64_t used = 0;
  int64_t* offs;  // [max_n + 1]
  int64_t n = 0;

  explicit Intern(char* b, int64_t c, int64_t* o) : blob(b), cap(c), offs(o) {
    offs[0] = 0;
  }
  // returns dense index or -1 on blob overflow
  int32_t put(std::string_view id) {
    auto it = map.find(id);
    if (it != map.end()) return it->second;
    if (used + (int64_t)id.size() > cap) return -1;
    memcpy(blob + used, id.data(), id.size());
    // keys must view the BLOB copy: the request body the string_views
    // point into outlives this call, but interning against the copy is
    // self-contained and keeps the invariant local
    std::string_view stored(blob + used, id.size());
    used += (int64_t)id.size();
    int32_t idx = (int32_t)n;
    offs[++n] = used;
    map.emplace(stored, idx);
    return idx;
  }
};

}  // namespace

extern "C" {

// Parse a strict-subset uniform batch. Returns the doc count n (>= 1)
// when eligible, or -1 for "fall back to the Python path" (not an
// error). Output arrays are caller-allocated: uidx/iidx/vals sized
// max_n; ublob/iblob byte caps with offs arrays sized max_n+1; scalars
// holds etype|name|tetype|vprop concatenated with lengths in
// scalar_lens[4].
int64_t pio_parse_uniform_batch(
    const char* body, int64_t body_len, int64_t max_n,
    int32_t* uidx, int32_t* iidx, float* vals,
    char* ublob, int64_t ublob_cap, int64_t* uoffs, int64_t* n_users_out,
    char* iblob, int64_t iblob_cap, int64_t* ioffs, int64_t* n_items_out,
    char* scalars, int64_t scalars_cap, int64_t* scalar_lens) {
  Cursor c{body, body + body_len};
  if (!c.lit('[')) return -1;
  if (c.peek(']')) return -1;  // empty batch: Python path owns the reply

  std::string_view name, etype, tetype, vprop;
  Intern users(ublob, ublob_cap, uoffs);
  Intern items(iblob, iblob_cap, ioffs);
  int64_t n = 0;

  enum KeyBit {
    kEvent = 1, kEtype = 2, kEid = 4, kTetype = 8, kTid = 16, kProps = 32,
  };

  while (true) {
    if (!c.lit('{')) return -1;
    unsigned seen = 0;
    std::string_view d_name, d_etype, d_eid, d_tetype, d_tid, d_vprop;
    double value = 0.0;
    if (!c.peek('}')) {
      while (true) {
        std::string_view key;
        if (!parse_string(c, &key)) return -1;
        if (!c.lit(':')) return -1;
        unsigned bit;
        std::string_view* dst = nullptr;
        if (key == "event") {
          bit = kEvent;
          dst = &d_name;
        } else if (key == "entityType") {
          bit = kEtype;
          dst = &d_etype;
        } else if (key == "entityId") {
          bit = kEid;
          dst = &d_eid;
        } else if (key == "targetEntityType") {
          bit = kTetype;
          dst = &d_tetype;
        } else if (key == "targetEntityId") {
          bit = kTid;
          dst = &d_tid;
        } else if (key == "properties") {
          bit = kProps;
        } else {
          // unknown key OR eventTime: the Python path owns both (the
          // gate rejects unknowns; eventTime needs tz semantics)
          return -1;
        }
        if (seen & bit) return -1;  // duplicate key: json.loads keeps
        seen |= bit;                // the LAST; we keep neither — fallback
        if (dst != nullptr) {
          if (!parse_string(c, dst)) return -1;
        } else {  // properties: exactly one numeric prop
          if (!c.lit('{')) return -1;
          if (!parse_string(c, &d_vprop)) return -1;
          if (!c.lit(':')) return -1;
          if (!parse_number(c, &value)) return -1;
          if (!c.lit('}')) return -1;
        }
        if (c.peek(',')) {
          c.lit(',');
          continue;
        }
        break;
      }
    }
    if (!c.lit('}')) return -1;
    if (seen != (kEvent | kEtype | kEid | kTetype | kTid | kProps))
      return -1;
    if (d_eid.empty() || d_eid.size() > kMaxField || d_tid.empty() ||
        d_tid.size() > kMaxField)
      return -1;
    if (!valid_utf8(d_eid) || !valid_utf8(d_tid)) return -1;
    // f32-exactness, same predicate as the gate's vectorized screen
    float f = (float)value;
    if ((double)f != value) return -1;

    if (n == 0) {
      name = d_name;
      etype = d_etype;
      tetype = d_tetype;
      vprop = d_vprop;
      if (name.empty() || name.size() > kMaxField || etype.empty() ||
          etype.size() > kMaxField || tetype.empty() ||
          tetype.size() > kMaxField || vprop.empty() ||
          vprop.size() > kMaxField)
        return -1;
      if (reserved_prefix(name) || reserved_prefix(etype) ||
          reserved_prefix(tetype) || reserved_prefix(vprop))
        return -1;
      if (!valid_utf8(name) || !valid_utf8(etype) || !valid_utf8(tetype) ||
          !valid_utf8(vprop))
        return -1;
    } else {
      if (d_name != name || d_etype != etype || d_tetype != tetype ||
          d_vprop != vprop)
        return -1;
    }
    if (n >= max_n) return -1;  // over the wire cap: Python owns the 400
    int32_t u = users.put(d_eid);
    int32_t t = items.put(d_tid);
    if (u < 0 || t < 0) return -1;  // blob overflow
    uidx[n] = u;
    iidx[n] = t;
    vals[n] = f;
    ++n;

    if (c.peek(',')) {
      c.lit(',');
      continue;
    }
    break;
  }
  if (!c.lit(']')) return -1;
  c.ws();
  if (c.p != c.end) return -1;  // trailing bytes: not a pure array

  int64_t total_scalars =
      (int64_t)(etype.size() + name.size() + tetype.size() + vprop.size());
  if (total_scalars > scalars_cap) return -1;
  char* s = scalars;
  memcpy(s, etype.data(), etype.size());
  s += etype.size();
  memcpy(s, name.data(), name.size());
  s += name.size();
  memcpy(s, tetype.data(), tetype.size());
  s += tetype.size();
  memcpy(s, vprop.data(), vprop.size());
  scalar_lens[0] = (int64_t)etype.size();
  scalar_lens[1] = (int64_t)name.size();
  scalar_lens[2] = (int64_t)tetype.size();
  scalar_lens[3] = (int64_t)vprop.size();
  *n_users_out = users.n;
  *n_items_out = items.n;
  return n;
}

}  // extern "C"
