// Append-only event log — the native event-store engine.
//
// Plays the role the HBase driver plays in the reference
// (data/.../storage/hbase/: hashed row keys + column-family scans feeding the
// event DAO): a high-throughput, file-backed event store with header-level
// predicate pushdown. The design is TPU-serving-native instead of a
// translation: one framed append-only log per (app, channel), a 48-byte
// fixed header per record carrying the event time and FNV-1a hashes of the
// filterable fields, and an in-memory index built on open so time-range /
// entity / event-name scans never parse JSON. The Python DAO
// (data/storage/cpplog.py) keeps payloads as JSON and does the final
// exact-match check on the (rare) hash candidates.
//
// Concurrency: one process owns a log file at a time (like the localfs
// model store); within the process all calls are serialized by a mutex.
// Deletes are tombstone records so the file stays append-only.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <unistd.h>
#include <string>
#include <vector>

extern "C" {

struct __attribute__((packed)) RecHeader {
  int64_t time_ms;
  uint64_t etype_hash;  // entity type
  uint64_t eid_hash;    // entity id
  uint64_t name_hash;   // event name
  uint64_t id_hash;     // event id
  uint32_t payload_len;
  uint32_t flags;       // 1 = tombstone (payload = 8-byte target index)
};

static_assert(sizeof(RecHeader) == 48, "header layout is the disk format");

struct Entry {
  int64_t time_ms;
  uint64_t etype_hash, eid_hash, name_hash, id_hash;
  uint64_t offset;      // of payload
  uint32_t payload_len;
  bool dead;
};

struct EventLog {
  FILE* f = nullptr;
  std::vector<Entry> entries;
  std::vector<int64_t> sorted;  // indices ordered by (time_ms, idx)
  bool sorted_dirty = true;
  int64_t last_time = INT64_MIN; // fast-path: appends already in order
  std::mutex mu;
};

static void resort(EventLog* log) {
  if (!log->sorted_dirty) return;
  log->sorted.resize(log->entries.size());
  for (size_t i = 0; i < log->sorted.size(); ++i) log->sorted[i] = (int64_t)i;
  std::stable_sort(log->sorted.begin(), log->sorted.end(),
                   [&](int64_t a, int64_t b) {
                     return log->entries[a].time_ms < log->entries[b].time_ms;
                   });
  log->sorted_dirty = false;
}

void* pio_evlog_open(const char* path) {
  FILE* f = fopen(path, "a+b");
  if (!f) return nullptr;
  auto* log = new EventLog();
  log->f = f;
  // Build the index: one sequential header scan. A crash mid-append (the
  // in-process ftruncate recovery only covers fwrite failures) can leave a
  // torn tail record whose header or payload extends past EOF; indexing it
  // would make later appends start inside its claimed payload range and
  // misframe every subsequent record. Validate each record's extent
  // against the file size and truncate away a torn tail.
  fseeko(f, 0, SEEK_END);
  const off_t file_size = ftello(f);
  fseeko(f, 0, SEEK_SET);
  RecHeader h;
  off_t rec_start = 0;
  bool torn_tail = false;   // extent past EOF — safe to truncate
  bool read_error = false;  // transient I/O failure — must NOT truncate
  while (rec_start + (off_t)sizeof(h) <= file_size) {
    if (fread(&h, sizeof(h), 1, f) != 1) {
      // a full header should fit here; a short read is an I/O problem
      // (or the file shrank underneath us), not a torn tail
      read_error = true;
      break;
    }
    uint64_t off = (uint64_t)rec_start + sizeof(h);
    const off_t rec_end = (off_t)(off + h.payload_len);
    if (rec_end > file_size) {  // torn tail: payload past EOF
      torn_tail = true;
      break;
    }
    if (h.flags & 1) {  // tombstone
      int64_t target = -1;
      if (h.payload_len == 8 && fread(&target, 8, 1, f) == 1 &&
          target >= 0 && (size_t)target < log->entries.size()) {
        log->entries[target].dead = true;
      } else {
        fseeko(f, rec_end, SEEK_SET);
      }
      log->entries.push_back({0, 0, 0, 0, 0, off, h.payload_len, true});
    } else {
      log->last_time = std::max(log->last_time, h.time_ms);
      log->entries.push_back({h.time_ms, h.etype_hash, h.eid_hash,
                              h.name_hash, h.id_hash, off, h.payload_len,
                              false});
      fseeko(f, rec_end, SEEK_SET);
    }
    rec_start = rec_end;
  }
  // Truncate ONLY a genuine torn tail (payload extent past EOF, or a
  // partial header at EOF). A mid-file fread error must leave the file
  // untouched — truncating there would destroy valid later records.
  if (!read_error && rec_start < file_size &&
      (torn_tail || rec_start + (off_t)sizeof(h) > file_size)) {
    (void)!ftruncate(fileno(f), rec_start);
  }
  log->sorted_dirty = true;
  fseeko(f, 0, SEEK_END);
  return log;
}

// Flush buffered appends to the OS and the disk (fdatasync). The hot ingest
// path only fflush()es — torn tails are recovered at open — so durability
// is opt-in: the Python DAO calls this on close and on demand.
int64_t pio_evlog_sync(void* handle) {
  auto* log = (EventLog*)handle;
  if (!log || !log->f) return -1;
  std::lock_guard<std::mutex> g(log->mu);
  if (fflush(log->f) != 0) return -1;
#if defined(__APPLE__)
  return fsync(fileno(log->f)) == 0 ? 0 : -1;
#else
  return fdatasync(fileno(log->f)) == 0 ? 0 : -1;
#endif
}

void pio_evlog_close(void* handle) {
  auto* log = (EventLog*)handle;
  if (!log) return;
  if (log->f) fclose(log->f);
  delete log;
}

int64_t pio_evlog_append(void* handle, int64_t time_ms, uint64_t etype_hash,
                         uint64_t eid_hash, uint64_t name_hash,
                         uint64_t id_hash, const uint8_t* payload,
                         uint32_t len) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  RecHeader h{time_ms, etype_hash, eid_hash, name_hash, id_hash, len, 0};
  fseeko(log->f, 0, SEEK_END);
  off_t rec_start = ftello(log->f);
  uint64_t off = (uint64_t)rec_start + sizeof(h);
  if (fwrite(&h, sizeof(h), 1, log->f) != 1 ||
      (len && fwrite(payload, 1, len, log->f) != len)) {
    // never leave a partial record: it would misframe every later record
    // on the reopen scan
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), rec_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  log->entries.push_back(
      {time_ms, etype_hash, eid_hash, name_hash, id_hash, off, len, false});
  if (time_ms >= log->last_time && !log->sorted_dirty) {
    log->sorted.push_back((int64_t)log->entries.size() - 1);  // stays sorted
  } else {
    log->sorted_dirty = true;
  }
  log->last_time = std::max(log->last_time, time_ms);
  return (int64_t)log->entries.size() - 1;
}

int64_t pio_evlog_tombstone(void* handle, int64_t index) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  if (index < 0 || (size_t)index >= log->entries.size()) return -1;
  if (log->entries[index].dead) return -1;
  RecHeader h{0, 0, 0, 0, 0, 8, 1};
  fseeko(log->f, 0, SEEK_END);
  off_t rec_start = ftello(log->f);
  uint64_t off = (uint64_t)rec_start + sizeof(h);
  if (fwrite(&h, sizeof(h), 1, log->f) != 1 ||
      fwrite(&index, 8, 1, log->f) != 1) {
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), rec_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  log->entries[index].dead = true;
  log->entries.push_back({0, 0, 0, 0, 0, off, 8, true});
  log->sorted_dirty = true;
  return 0;
}

int64_t pio_evlog_count(void* handle) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  int64_t n = 0;
  for (auto& e : log->entries)
    if (!e.dead) ++n;
  return n;
}

// Header-level scan. 0 hash = "no filter" (the Python side maps real hashes
// of 0 to 1). Returns the number of record indices written to `out`,
// time-ordered (ties by append order), reversed/limit applied like
// LEvents.futureFind (reference data/.../storage/LEvents.scala:167-182).
int64_t pio_evlog_query(void* handle, int64_t start_ms, int64_t until_ms,
                        uint64_t etype_hash, uint64_t eid_hash,
                        const uint64_t* name_hashes, int32_t n_names,
                        int32_t reversed, int64_t limit, int64_t* out,
                        int64_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  resort(log);
  int64_t n = 0;
  int64_t total = (int64_t)log->sorted.size();
  for (int64_t step = 0; step < total; ++step) {
    int64_t idx = log->sorted[reversed ? total - 1 - step : step];
    const Entry& e = log->entries[idx];
    if (e.dead) continue;
    if (e.time_ms < start_ms || e.time_ms >= until_ms) continue;
    if (etype_hash && e.etype_hash != etype_hash) continue;
    if (eid_hash && e.eid_hash != eid_hash) continue;
    if (n_names > 0) {
      bool hit = false;
      for (int32_t i = 0; i < n_names; ++i)
        if (e.name_hash == name_hashes[i]) { hit = true; break; }
      if (!hit) continue;
    }
    if (n >= cap) break;
    out[n++] = idx;
    if (limit >= 0 && n >= limit) break;
  }
  return n;
}

int64_t pio_evlog_find_id(void* handle, uint64_t id_hash, int64_t* out,
                          int64_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  int64_t n = 0;
  for (size_t i = 0; i < log->entries.size() && n < cap; ++i) {
    const Entry& e = log->entries[i];
    if (!e.dead && e.id_hash == id_hash) out[n++] = (int64_t)i;
  }
  return n;
}

// Returns the payload length; copies into buf only when it fits. Dead or
// out-of-range records return -1.
int32_t pio_evlog_read(void* handle, int64_t index, uint8_t* buf,
                       int32_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  if (index < 0 || (size_t)index >= log->entries.size()) return -1;
  const Entry& e = log->entries[index];
  if (e.dead) return -1;
  if ((int32_t)e.payload_len <= cap) {
    fseeko(log->f, (off_t)e.offset, SEEK_SET);
    if (fread(buf, 1, e.payload_len, log->f) != e.payload_len) return -1;
    fseeko(log->f, 0, SEEK_END);
  }
  return (int32_t)e.payload_len;
}

}  // extern "C"
