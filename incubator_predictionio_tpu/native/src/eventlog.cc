// Append-only event log — the native event-store engine.
//
// Plays the role the HBase driver plays in the reference
// (data/.../storage/hbase/: hashed row keys + column-family scans feeding the
// event DAO): a high-throughput, file-backed event store with header-level
// predicate pushdown. The design is TPU-serving-native instead of a
// translation: one framed append-only log per (app, channel), a 48-byte
// fixed header per record carrying the event time and FNV-1a hashes of the
// filterable fields, and an in-memory index built on open so time-range /
// entity / event-name scans never parse JSON. The Python DAO
// (data/storage/cpplog.py) keeps payloads as JSON and does the final
// exact-match check on the (rare) hash candidates.
//
// Concurrency: one process owns a log file at a time (like the localfs
// model store); within the process all calls are serialized by a mutex.
// Deletes are tombstone records so the file stays append-only.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <deque>
#include <functional>
#include <iterator>
#include <mutex>
#include <string>
#include <sched.h>
#include <string_view>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

struct __attribute__((packed)) RecHeader {
  int64_t time_ms;
  uint64_t etype_hash;  // entity type
  uint64_t eid_hash;    // entity id
  uint64_t name_hash;   // event name
  uint64_t id_hash;     // event id
  uint32_t payload_len;
  uint32_t flags;       // bit0 = tombstone (payload = 8-byte target index)
                        // bit1 = payload starts with a binary sidecar block
};

// flags bit1: the payload is [sidecar block][JSON] instead of bare JSON.
// The sidecar carries the scan-relevant fields in binary so the columnar
// training scan never parses JSON. Layout (little-endian, packed):
//   u32 block_len (including this field)
//   u8  n_numeric_props
//   u16 etype_len, name_len, eid_len, tetype_len (0xFFFF = no target),
//       teid_len
//   bytes: etype, name, eid, tetype, teid
//   per prop: u8 key_len, key bytes, f64 value
static constexpr uint32_t kTombstone = 1;
static constexpr uint32_t kSidecar = 2;
//: record stores ONLY the sidecar (plus a trailing 32-char event id inside
//: the sidecar block); the JSON document is rendered on read. Interaction
//: bulk imports write this flavor — it cuts bytes/record ~3x, which is the
//: whole game on a disk-bound 20M-event seed, and the columnar scan never
//: wanted the JSON anyway.
static constexpr uint32_t kCompact = 4;
static constexpr uint16_t kNoTarget = 0xFFFF;

static_assert(sizeof(RecHeader) == 48, "header layout is the disk format");

struct Entry {
  int64_t time_ms;
  uint64_t etype_hash, eid_hash, name_hash, id_hash;
  uint64_t offset;      // of payload
  uint32_t payload_len;
  uint32_t flags;
  bool dead;
};

struct EventLog {
  FILE* f = nullptr;
  std::vector<Entry> entries;
  std::vector<int64_t> sorted;  // indices ordered by (time_ms, idx)
  bool sorted_dirty = true;
  int64_t last_time = INT64_MIN; // fast-path: appends already in order
  // id_hash → entry index, built LAZILY on the first find_id (explicit-id
  // upserts/re-imports); plain ingest never pays its memory. A sorted flat
  // vector (16 B/record — a node-based hash map would cost ~4×) plus a
  // logarithmic tail: a ≤4096-entry unsorted buffer and carry-merged
  // sorted runs of geometrically increasing size (Bentley–Saxe), so an
  // interleaved lookup+append re-import pays O(log) amortized per append
  // and O(log² N) per lookup instead of a linear tail walk or an O(N)
  // merge every fixed-size flush. Tombstoned entries are filtered at
  // query time, so marking dead needs no upkeep.
  std::vector<std::pair<uint64_t, int64_t>> id_sorted;
  std::vector<std::pair<uint64_t, int64_t>> id_buf;
  std::vector<std::vector<std::pair<uint64_t, int64_t>>> id_runs;
  size_t id_tail_total = 0;  // id_buf + all id_runs
  bool id_index_built = false;
  // entries with dead==true (tombstone markers + their targets). The
  // Python training-projection cache (cpplog.py) stores this at write
  // time: any change means a cached row may have died, invalidating the
  // projection without walking the log.
  int64_t dead_count = 0;
  std::mutex mu;
};

static void flush_id_buf(EventLog* log) {
  if (log->id_buf.empty()) return;
  std::sort(log->id_buf.begin(), log->id_buf.end());
  std::vector<std::pair<uint64_t, int64_t>> run = std::move(log->id_buf);
  log->id_buf.clear();
  // carry-merge: absorb every trailing run no larger than the incoming
  // one, so run sizes stay geometric (largest first) and each entry is
  // re-merged only O(log) times on its way toward id_sorted
  while (!log->id_runs.empty() && log->id_runs.back().size() <= run.size()) {
    std::vector<std::pair<uint64_t, int64_t>> merged;
    merged.reserve(run.size() + log->id_runs.back().size());
    std::merge(run.begin(), run.end(), log->id_runs.back().begin(),
               log->id_runs.back().end(), std::back_inserter(merged));
    run = std::move(merged);
    log->id_runs.pop_back();
  }
  log->id_runs.push_back(std::move(run));
}

static void merge_id_tail_into_main(EventLog* log) {
  flush_id_buf(log);
  for (auto& run : log->id_runs) {
    const size_t mid = log->id_sorted.size();
    log->id_sorted.insert(log->id_sorted.end(), run.begin(), run.end());
    std::inplace_merge(log->id_sorted.begin(),
                       log->id_sorted.begin() + mid, log->id_sorted.end());
  }
  log->id_runs.clear();
  log->id_tail_total = 0;
}

static void index_new_entry(EventLog* log, int64_t idx) {
  if (!log->id_index_built || log->entries[idx].dead) return;
  log->id_buf.emplace_back(log->entries[idx].id_hash, idx);
  ++log->id_tail_total;
  if (log->id_buf.size() >= 4096) flush_id_buf(log);
  // geometric schedule into the main run: amortized O(1) of main-merge
  // work per append, while lookups stay logarithmic via the runs
  if (log->id_tail_total > 4096 &&
      log->id_tail_total > log->id_sorted.size() / 8)
    merge_id_tail_into_main(log);
}

static void resort(EventLog* log) {
  if (!log->sorted_dirty) return;
  log->sorted.resize(log->entries.size());
  for (size_t i = 0; i < log->sorted.size(); ++i) log->sorted[i] = (int64_t)i;
  std::stable_sort(log->sorted.begin(), log->sorted.end(),
                   [&](int64_t a, int64_t b) {
                     return log->entries[a].time_ms < log->entries[b].time_ms;
                   });
  log->sorted_dirty = false;
}

void* pio_evlog_open(const char* path) {
  FILE* f = fopen(path, "a+b");
  if (!f) return nullptr;
  auto* log = new EventLog();
  log->f = f;
  // Build the index: one sequential header scan. A crash mid-append (the
  // in-process ftruncate recovery only covers fwrite failures) can leave a
  // torn tail record whose header or payload extends past EOF; indexing it
  // would make later appends start inside its claimed payload range and
  // misframe every subsequent record. Validate each record's extent
  // against the file size and truncate away a torn tail.
  fseeko(f, 0, SEEK_END);
  const off_t file_size = ftello(f);
  fseeko(f, 0, SEEK_SET);
  RecHeader h;
  off_t rec_start = 0;
  bool torn_tail = false;   // extent past EOF — safe to truncate
  bool read_error = false;  // transient I/O failure — must NOT truncate
  while (rec_start + (off_t)sizeof(h) <= file_size) {
    if (fread(&h, sizeof(h), 1, f) != 1) {
      // a full header should fit here; a short read is an I/O problem
      // (or the file shrank underneath us), not a torn tail
      read_error = true;
      break;
    }
    uint64_t off = (uint64_t)rec_start + sizeof(h);
    const off_t rec_end = (off_t)(off + h.payload_len);
    if (rec_end > file_size) {  // torn tail: payload past EOF
      torn_tail = true;
      break;
    }
    if (h.flags & 1) {  // tombstone
      int64_t target = -1;
      if (h.payload_len == 8 && fread(&target, 8, 1, f) == 1 &&
          target >= 0 && (size_t)target < log->entries.size()) {
        if (!log->entries[target].dead) ++log->dead_count;
        log->entries[target].dead = true;
      } else {
        fseeko(f, rec_end, SEEK_SET);
      }
      ++log->dead_count;  // the marker entry itself
      log->entries.push_back({0, 0, 0, 0, 0, off, h.payload_len, h.flags,
                              true});
    } else {
      log->last_time = std::max(log->last_time, h.time_ms);
      log->entries.push_back({h.time_ms, h.etype_hash, h.eid_hash,
                              h.name_hash, h.id_hash, off, h.payload_len,
                              h.flags, false});
      fseeko(f, rec_end, SEEK_SET);
    }
    rec_start = rec_end;
  }
  // Truncate ONLY a genuine torn tail (payload extent past EOF, or a
  // partial header at EOF). A mid-file fread error must leave the file
  // untouched — truncating there would destroy valid later records.
  if (!read_error && rec_start < file_size &&
      (torn_tail || rec_start + (off_t)sizeof(h) > file_size)) {
    (void)!ftruncate(fileno(f), rec_start);
  }
  log->sorted_dirty = true;
  fseeko(f, 0, SEEK_END);
  return log;
}

// Flush buffered appends to the OS and the disk (fdatasync). The hot ingest
// path only fflush()es — torn tails are recovered at open — so durability
// is opt-in: the Python DAO calls this on close and on demand.
int64_t pio_evlog_sync(void* handle) {
  auto* log = (EventLog*)handle;
  if (!log || !log->f) return -1;
  std::lock_guard<std::mutex> g(log->mu);
  if (fflush(log->f) != 0) return -1;
#if defined(__APPLE__)
  return fsync(fileno(log->f)) == 0 ? 0 : -1;
#else
  return fdatasync(fileno(log->f)) == 0 ? 0 : -1;
#endif
}

void pio_evlog_close(void* handle) {
  auto* log = (EventLog*)handle;
  if (!log) return;
  if (log->f) fclose(log->f);
  delete log;
}

int64_t pio_evlog_append(void* handle, int64_t time_ms, uint64_t etype_hash,
                         uint64_t eid_hash, uint64_t name_hash,
                         uint64_t id_hash, const uint8_t* payload,
                         uint32_t len) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  RecHeader h{time_ms, etype_hash, eid_hash, name_hash, id_hash, len, 0};
  fseeko(log->f, 0, SEEK_END);
  off_t rec_start = ftello(log->f);
  uint64_t off = (uint64_t)rec_start + sizeof(h);
  if (fwrite(&h, sizeof(h), 1, log->f) != 1 ||
      (len && fwrite(payload, 1, len, log->f) != len)) {
    // never leave a partial record: it would misframe every later record
    // on the reopen scan
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), rec_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  log->entries.push_back(
      {time_ms, etype_hash, eid_hash, name_hash, id_hash, off, len, 0,
       false});
  index_new_entry(log, (int64_t)log->entries.size() - 1);
  if (time_ms >= log->last_time && !log->sorted_dirty) {
    log->sorted.push_back((int64_t)log->entries.size() - 1);  // stays sorted
  } else {
    log->sorted_dirty = true;
  }
  log->last_time = std::max(log->last_time, time_ms);
  return (int64_t)log->entries.size() - 1;
}

int64_t pio_evlog_tombstone(void* handle, int64_t index) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  if (index < 0 || (size_t)index >= log->entries.size()) return -1;
  if (log->entries[index].dead) return -1;
  RecHeader h{0, 0, 0, 0, 0, 8, 1};
  fseeko(log->f, 0, SEEK_END);
  off_t rec_start = ftello(log->f);
  uint64_t off = (uint64_t)rec_start + sizeof(h);
  if (fwrite(&h, sizeof(h), 1, log->f) != 1 ||
      fwrite(&index, 8, 1, log->f) != 1) {
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), rec_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  log->entries[index].dead = true;
  log->entries.push_back({0, 0, 0, 0, 0, off, 8, kTombstone, true});
  log->dead_count += 2;  // the target + the marker entry
  log->sorted_dirty = true;
  return 0;
}

// Raw entry count (live + dead + tombstone markers) — the projection
// cache's high-water mark: entries at index >= a stored count are exactly
// the records appended after the cache was written.
int64_t pio_evlog_entry_count(void* handle) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  return (int64_t)log->entries.size();
}

int64_t pio_evlog_dead_count(void* handle) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  return log->dead_count;
}

int64_t pio_evlog_count(void* handle) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  int64_t n = 0;
  for (auto& e : log->entries)
    if (!e.dead) ++n;
  return n;
}

// Header-level scan. 0 hash = "no filter" (the Python side maps real hashes
// of 0 to 1). Returns the number of record indices written to `out`,
// time-ordered (ties by append order), reversed/limit applied like
// LEvents.futureFind (reference data/.../storage/LEvents.scala:167-182).
int64_t pio_evlog_query(void* handle, int64_t start_ms, int64_t until_ms,
                        uint64_t etype_hash, uint64_t eid_hash,
                        const uint64_t* name_hashes, int32_t n_names,
                        int32_t reversed, int64_t limit, int64_t* out,
                        int64_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  resort(log);
  int64_t n = 0;
  int64_t total = (int64_t)log->sorted.size();
  for (int64_t step = 0; step < total; ++step) {
    int64_t idx = log->sorted[reversed ? total - 1 - step : step];
    const Entry& e = log->entries[idx];
    if (e.dead) continue;
    if (e.time_ms < start_ms || e.time_ms >= until_ms) continue;
    if (etype_hash && e.etype_hash != etype_hash) continue;
    if (eid_hash && e.eid_hash != eid_hash) continue;
    if (n_names > 0) {
      bool hit = false;
      for (int32_t i = 0; i < n_names; ++i)
        if (e.name_hash == name_hashes[i]) { hit = true; break; }
      if (!hit) continue;
    }
    if (n >= cap) break;
    out[n++] = idx;
    if (limit >= 0 && n >= limit) break;
  }
  return n;
}

int64_t pio_evlog_find_id(void* handle, uint64_t id_hash, int64_t* out,
                          int64_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  if (!log->id_index_built) {
    // one linear pass + sort on the FIRST lookup; afterwards appends keep
    // the index current. An M-event explicit-id re-import into an N-record
    // log costs O(N log N) for this build, O(log) amortized per append
    // (carry-merged runs), and O(log² N) + a ≤4096 linear buffer walk per
    // lookup — far below the O(M·N) of a per-event scan
    log->id_sorted.reserve(log->entries.size());
    for (size_t i = 0; i < log->entries.size(); ++i)
      if (!log->entries[i].dead)
        log->id_sorted.emplace_back(log->entries[i].id_hash, (int64_t)i);
    std::sort(log->id_sorted.begin(), log->id_sorted.end());
    log->id_index_built = true;
  }
  int64_t n = 0;
  const auto probe = std::make_pair(id_hash, INT64_MIN);
  auto lo = std::lower_bound(
      log->id_sorted.begin(), log->id_sorted.end(), probe);
  for (; lo != log->id_sorted.end() && lo->first == id_hash && n < cap; ++lo)
    if (!log->entries[lo->second].dead) out[n++] = lo->second;
  for (const auto& run : log->id_runs) {
    auto it = std::lower_bound(run.begin(), run.end(), probe);
    for (; it != run.end() && it->first == id_hash && n < cap; ++it)
      if (!log->entries[it->second].dead) out[n++] = it->second;
  }
  for (const auto& kv : log->id_buf)
    if (n < cap && kv.first == id_hash && !log->entries[kv.second].dead)
      out[n++] = kv.second;
  return n;
}

// ---------------------------------------------------------------------------
// Columnar interaction scan — the training-ingest fast path.
//
// Plays the role of the reference's parallel HBase read
// (hbase/HBPEvents.scala:63-88 newAPIHadoopRDD): streams matching events
// straight into int32 COO arrays + interned id tables without ever
// materializing per-event objects in Python. The JSON payloads are written
// by this framework's own DAO (compact json.dumps), so a small
// depth-tracking scanner suffices; all header-hash candidates are
// re-checked with exact string compares, so hash collisions cannot corrupt
// the output.
// ---------------------------------------------------------------------------

static uint64_t fnv1a64(const char* s, size_t n) {
  uint64_t h = 0xCBF29CE484222325ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= (uint8_t)s[i];
    h *= 0x100000001B3ull;
  }
  return h ? h : 1;  // 0 is the "no filter" sentinel (native/__init__.py)
}

// Scan a compact JSON object for a top-level key; returns the byte position
// of the first character of its value, or npos. Tracks string/escape state
// and brace depth so key text inside nested values never matches.
static size_t json_toplevel_value(const std::string& s, const char* key) {
  const std::string pat = std::string("\"") + key + "\"";
  int depth = 0;
  bool in_str = false;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (in_str) {
      if (c == '\\') { ++i; continue; }
      if (c == '"') in_str = false;
      continue;
    }
    if (c == '{' || c == '[') { ++depth; continue; }
    if (c == '}' || c == ']') { --depth; continue; }
    if (c == '"') {
      if (depth == 1 && s.compare(i, pat.size(), pat) == 0) {
        size_t j = i + pat.size();
        while (j < s.size() && (s[j] == ' ' || s[j] == '\t')) ++j;
        if (j < s.size() && s[j] == ':') {
          ++j;
          while (j < s.size() && (s[j] == ' ' || s[j] == '\t')) ++j;
          return j;
        }
      }
      in_str = true;
    }
  }
  return std::string::npos;
}

// Decode the JSON string whose opening quote is at s[pos]; false when the
// value there is not a string. Handles \", \\, \/, \b, \f, \n, \r, \t and
// \uXXXX (incl. surrogate pairs) — json.dumps default ensure_ascii=True
// escapes all non-ASCII ids this way.
static bool json_decode_string(const std::string& s, size_t pos,
                               std::string* out) {
  if (pos == std::string::npos || pos >= s.size() || s[pos] != '"')
    return false;
  out->clear();
  for (size_t i = pos + 1; i < s.size(); ++i) {
    char c = s[i];
    if (c == '"') return true;
    if (c != '\\') { out->push_back(c); continue; }
    if (++i >= s.size()) return false;
    char e = s[i];
    switch (e) {
      case '"': out->push_back('"'); break;
      case '\\': out->push_back('\\'); break;
      case '/': out->push_back('/'); break;
      case 'b': out->push_back('\b'); break;
      case 'f': out->push_back('\f'); break;
      case 'n': out->push_back('\n'); break;
      case 'r': out->push_back('\r'); break;
      case 't': out->push_back('\t'); break;
      case 'u': {
        if (i + 4 >= s.size()) return false;
        auto hex4 = [&](size_t p) -> int {
          int v = 0;
          for (int k = 0; k < 4; ++k) {
            char hc = s[p + k];
            v <<= 4;
            if (hc >= '0' && hc <= '9') v |= hc - '0';
            else if (hc >= 'a' && hc <= 'f') v |= hc - 'a' + 10;
            else if (hc >= 'A' && hc <= 'F') v |= hc - 'A' + 10;
            else return -1;
          }
          return v;
        };
        int cp = hex4(i + 1);
        if (cp < 0) return false;
        i += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF && i + 6 < s.size() &&
            s[i + 1] == '\\' && s[i + 2] == 'u') {
          int lo = hex4(i + 3);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            i += 6;
          }
        }
        // utf-8 encode
        if (cp < 0x80) out->push_back((char)cp);
        else if (cp < 0x800) {
          out->push_back((char)(0xC0 | (cp >> 6)));
          out->push_back((char)(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
          out->push_back((char)(0xE0 | (cp >> 12)));
          out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out->push_back((char)(0x80 | (cp & 0x3F)));
        } else {
          out->push_back((char)(0xF0 | (cp >> 18)));
          out->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
          out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out->push_back((char)(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default: return false;
    }
  }
  return false;  // unterminated
}

// Extract "properties".<key> as a double; false when absent / not numeric.
static bool json_property_number(const std::string& s, const char* key,
                                 double* out) {
  size_t props = json_toplevel_value(s, "properties");
  if (props == std::string::npos || props >= s.size() || s[props] != '{')
    return false;
  // find the matching close brace of the properties object
  int depth = 0;
  bool in_str = false;
  size_t end = props;
  for (size_t i = props; i < s.size(); ++i) {
    char c = s[i];
    if (in_str) {
      if (c == '\\') { ++i; continue; }
      if (c == '"') in_str = false;
      continue;
    }
    if (c == '"') { in_str = true; continue; }
    if (c == '{' || c == '[') ++depth;
    else if (c == '}' || c == ']') {
      if (--depth == 0) { end = i + 1; break; }
    }
  }
  std::string sub = s.substr(props, end - props);
  size_t vpos = json_toplevel_value(sub, key);
  if (vpos == std::string::npos || vpos >= sub.size()) return false;
  char c = sub[vpos];
  if (c != '-' && (c < '0' || c > '9')) return false;  // not a number
  char* endp = nullptr;
  *out = strtod(sub.c_str() + vpos, &endp);
  return endp != sub.c_str() + vpos;
}

struct ScanResult {
  std::vector<int32_t> uidx, iidx;
  std::vector<float> vals;
  std::vector<int64_t> times;        // per-row event time (projection cache)
  std::string ubuf, ibuf;            // concatenated utf-8 id bytes
  std::vector<int64_t> uoff, ioff;   // n_ids + 1 offsets into the buffers
  int64_t lock_ns = 0;               // wall spent holding the log mutex
};

// ---- single-pass payload field extraction (span-based, zero-copy) --------

struct Span {
  size_t pos = 0, len = 0;
  bool esc = false, present = false;
};

struct Fields {
  Span event, etype, eid, tetype, teid, props;
};

// One pass over a compact JSON object, recording the value spans of the six
// keys the scan needs. Strings are kept raw (escape flag only); object
// values record their full balanced extent.
static bool extract_fields(std::string_view s, Fields* f) {
  size_t i = 0;
  const size_t n = s.size();
  int depth = 0;
  while (i < n) {
    char c = s[i];
    if (c == '{' || c == '[') { ++depth; ++i; continue; }
    if (c == '}' || c == ']') { --depth; ++i; continue; }
    if (c != '"') { ++i; continue; }
    if (depth != 1) {  // a string inside a nested value: skip it
      ++i;
      while (i < n && s[i] != '"') i += (s[i] == '\\') ? 2 : 1;
      ++i;
      continue;
    }
    // depth-1 string reached outside a value ⇒ it is a key
    size_t kstart = ++i;
    bool kesc = false;
    while (i < n && s[i] != '"') {
      if (s[i] == '\\') { kesc = true; i += 2; } else ++i;
    }
    if (i >= n) return false;
    std::string_view key = s.substr(kstart, i - kstart);
    ++i;
    while (i < n && (s[i] == ' ' || s[i] == '\t')) ++i;
    if (i >= n || s[i] != ':') return false;
    ++i;
    while (i < n && (s[i] == ' ' || s[i] == '\t')) ++i;
    if (i >= n) return false;
    Span v;
    if (s[i] == '"') {
      size_t vstart = ++i;
      bool vesc = false;
      while (i < n && s[i] != '"') {
        if (s[i] == '\\') { vesc = true; i += 2; } else ++i;
      }
      if (i >= n) return false;
      v = {vstart, i - vstart, vesc, true};
      ++i;
    } else if (s[i] == '{' || s[i] == '[') {
      size_t vstart = i;
      int d2 = 0;
      bool instr = false;
      while (i < n) {
        char c2 = s[i];
        if (instr) {
          if (c2 == '\\') { i += 2; continue; }
          if (c2 == '"') instr = false;
          ++i;
          continue;
        }
        if (c2 == '"') { instr = true; ++i; continue; }
        if (c2 == '{' || c2 == '[') ++d2;
        else if (c2 == '}' || c2 == ']') {
          if (--d2 == 0) { ++i; break; }
        }
        ++i;
      }
      v = {vstart, i - vstart, false, true};
      // the balanced walk above consumed the closing brace, keeping the
      // outer `depth` unchanged — do not let the main loop see it
    } else {
      size_t vstart = i;
      while (i < n && s[i] != ',' && s[i] != '}') ++i;
      v = {vstart, i - vstart, false, true};
    }
    if (!kesc) {
      if (key == "event") f->event = v;
      else if (key == "entityType") f->etype = v;
      else if (key == "entityId") f->eid = v;
      else if (key == "targetEntityType") f->tetype = v;
      else if (key == "targetEntityId") f->teid = v;
      else if (key == "properties") f->props = v;
    }
  }
  return true;
}

// Decode JSON string escapes of a raw (quote-less) span. Mirrors
// json_decode_string (incl. \uXXXX surrogate pairs).
static bool decode_escapes(std::string_view raw, std::string* out) {
  std::string quoted;
  quoted.reserve(raw.size() + 2);
  quoted.push_back('"');
  quoted.append(raw);
  quoted.push_back('"');
  return json_decode_string(quoted, 0, out);
}

// Materialize a span as a string id: direct slice when unescaped.
static bool span_id(std::string_view payload, const Span& v,
                    std::string* out) {
  if (!v.present) return false;
  std::string_view raw = payload.substr(v.pos, v.len);
  if (!v.esc) {
    out->assign(raw);
    return true;
  }
  return decode_escapes(raw, out);
}

static bool span_equals(std::string_view payload, const Span& v,
                        std::string_view want, std::string* scratch) {
  if (!v.present) return false;
  std::string_view raw = payload.substr(v.pos, v.len);
  if (!v.esc) return raw == want;
  if (!decode_escapes(raw, scratch)) return false;
  return *scratch == want;
}

// properties.<key> as a double from the raw props span (an object).
static bool span_property_number(std::string_view props,
                                 std::string_view key, double* out) {
  size_t i = 0;
  const size_t n = props.size();
  int depth = 0;
  while (i < n) {
    char c = props[i];
    if (c == '{' || c == '[') { ++depth; ++i; continue; }
    if (c == '}' || c == ']') { --depth; ++i; continue; }
    if (c != '"') { ++i; continue; }
    if (depth != 1) {
      ++i;
      while (i < n && props[i] != '"') i += (props[i] == '\\') ? 2 : 1;
      ++i;
      continue;
    }
    size_t kstart = ++i;
    bool kesc = false;
    while (i < n && props[i] != '"') {
      if (props[i] == '\\') { kesc = true; i += 2; } else ++i;
    }
    if (i >= n) return false;
    std::string_view k = props.substr(kstart, i - kstart);
    ++i;
    while (i < n && (props[i] == ' ' || props[i] == '\t')) ++i;
    if (i >= n || props[i] != ':') return false;
    ++i;
    while (i < n && (props[i] == ' ' || props[i] == '\t')) ++i;
    if (i >= n) return false;
    if (!kesc && k == key) {
      char c2 = props[i];
      if (c2 != '-' && (c2 < '0' || c2 > '9')) return false;  // not a number
      char buf[64];
      size_t m = 0;
      while (i < n && m < 63 && props[i] != ',' && props[i] != '}' &&
             props[i] != ' ')
        buf[m++] = props[i++];
      buf[m] = 0;
      char* endp = nullptr;
      *out = strtod(buf, &endp);
      return endp != buf;
    }
    // skip this value
    char c2 = props[i];
    if (c2 == '"') {
      ++i;
      while (i < n && props[i] != '"') i += (props[i] == '\\') ? 2 : 1;
      ++i;
    } else if (c2 == '{' || c2 == '[') {
      int d2 = 0;
      bool instr = false;
      while (i < n) {
        char c3 = props[i];
        if (instr) {
          if (c3 == '\\') { i += 2; continue; }
          if (c3 == '"') instr = false;
          ++i;
          continue;
        }
        if (c3 == '"') { instr = true; ++i; continue; }
        if (c3 == '{' || c3 == '[') ++d2;
        else if (c3 == '}' || c3 == ']') {
          if (--d2 == 0) { ++i; break; }
        }
        ++i;
      }
    } else {
      while (i < n && props[i] != ',' && props[i] != '}') ++i;
    }
  }
  return false;
}

// ---- binary sidecar fast path --------------------------------------------

struct SideFields {
  std::string_view etype, name, eid, tetype, teid, props;
  uint8_t n_props = 0;
  bool has_target = false;
};

static bool parse_sidecar(const char* p, size_t plen, SideFields* f) {
  if (plen < 15) return false;
  uint32_t bl;
  memcpy(&bl, p, 4);
  if (bl > plen || bl < 15) return false;
  f->n_props = (uint8_t)p[4];
  uint16_t l[5];
  memcpy(l, p + 5, 10);
  size_t pos = 15;
  auto take = [&](uint16_t len) {
    std::string_view v(p + pos, len);
    pos += len;
    return v;
  };
  if (15 + (size_t)l[0] + l[1] + l[2] > bl) return false;
  f->etype = take(l[0]);
  f->name = take(l[1]);
  f->eid = take(l[2]);
  f->has_target = l[3] != kNoTarget;
  if (f->has_target) {
    if (pos + l[3] + l[4] > bl) return false;
    f->tetype = take(l[3]);
    f->teid = take(l[4]);
  }
  if (pos > bl) return false;
  f->props = std::string_view(p + pos, bl - pos);
  return true;
}

static bool sidecar_prop_value(const SideFields& f, std::string_view key,
                               double* out) {
  std::string_view props = f.props;
  size_t pos = 0;
  for (uint8_t i = 0; i < f.n_props; ++i) {
    if (pos + 1 > props.size()) return false;
    const uint8_t kl = (uint8_t)props[pos];
    ++pos;
    if (pos + kl + 8 > props.size()) return false;
    std::string_view k = props.substr(pos, kl);
    pos += kl;
    if (k == key) {
      memcpy(out, props.data() + pos, 8);
      return true;
    }
    pos += 8;
  }
  return false;
}

// Per-thread partial scan: local interning, merged in submit order. Id keys
// are string_views into the mmapped file (or into `arena` for ids that
// needed JSON unescaping) — no per-record string allocations.
struct LocalScan {
  std::vector<int32_t> uidx, iidx;
  std::vector<float> vals;
  std::vector<int64_t> times;
  std::vector<std::string_view> users, items;  // local idx → id view
  std::unordered_map<std::string_view, int32_t> umap, imap;
  std::deque<std::string> arena;  // stable storage for decoded ids
};

struct ScanFilters {
  int64_t start_ms, until_ms;
  std::string_view entity_type, target_entity_type, value_prop;
  const std::vector<std::string>* names;
  std::vector<uint64_t> name_hs;
  const double* fixed_vals;
  bool have_prop;
  double default_value;
  uint64_t etype_h;
};

// One header-prefiltered entry, copied out of the in-memory index while the
// log mutex is held. The expensive payload work (mmap reads, sidecar/JSON
// parsing, interning) runs on these snapshots OUTSIDE the mutex, so
// concurrent appends — which may reallocate the entries vector — are never
// stalled by a scan and never race a reader.
struct SnapEntry {
  int64_t time_ms;
  uint64_t offset;
  uint32_t payload_len;
  uint16_t flags;
  uint16_t slot;  // matched name-hash slot (exact-checked during the scan)
};

// A span as an interning key: a view into the mmap when unescaped, else a
// decoded copy pinned in the arena.
static bool span_view(std::string_view payload, const Span& v,
                      LocalScan* out, std::string_view* view) {
  if (!v.present) return false;
  std::string_view raw = payload.substr(v.pos, v.len);
  if (!v.esc) {
    *view = raw;
    return true;
  }
  std::string decoded;
  if (!decode_escapes(raw, &decoded)) return false;
  out->arena.push_back(std::move(decoded));
  *view = out->arena.back();
  return true;
}

static void scan_snap(const char* base, const std::vector<SnapEntry>& snap,
                      int64_t lo, int64_t hi, const ScanFilters& flt,
                      LocalScan* out) {
  std::string scratch;
  std::string_view uid, iid;
  const int32_t n_names = (int32_t)flt.names->size();
  for (int64_t k = lo; k < hi; ++k) {
    const SnapEntry& e = snap[k];
    int32_t slot = (int32_t)e.slot;
    double v;
    if (e.flags & kSidecar) {
      // fast path: all fields binary, no JSON touched
      SideFields sf;
      if (!parse_sidecar(base + e.offset, e.payload_len, &sf)) continue;
      if (sf.name != (*flt.names)[slot]) {  // hash collision in name set
        slot = -1;
        for (int32_t i = 0; i < n_names; ++i)
          if (sf.name == (*flt.names)[i]) { slot = i; break; }
        if (slot < 0) continue;
      }
      if (sf.etype != flt.entity_type) continue;
      if (!sf.has_target || sf.tetype != flt.target_entity_type) continue;
      const double fv = flt.fixed_vals[slot];
      if (!std::isnan(fv)) {
        v = fv;
      } else if (flt.have_prop) {
        if (!sidecar_prop_value(sf, flt.value_prop, &v)) continue;
      } else {
        v = flt.default_value;
      }
      uid = sf.eid;
      iid = sf.teid;
    } else {
      // JSON fallback (records written before the sidecar format)
      std::string_view payload(base + e.offset, e.payload_len);
      Fields f;
      if (!extract_fields(payload, &f)) continue;
      // exact rechecks (headers are hash prefilters only)
      if (!span_equals(payload, f.event, (*flt.names)[slot], &scratch)) {
        slot = -1;
        for (int32_t i = 0; i < n_names; ++i)
          if (span_equals(payload, f.event, (*flt.names)[i], &scratch)) {
            slot = i;
            break;
          }
        if (slot < 0) continue;
      }
      if (!span_equals(payload, f.etype, flt.entity_type, &scratch))
        continue;
      if (!span_equals(payload, f.tetype, flt.target_entity_type, &scratch))
        continue;
      const double fv = flt.fixed_vals[slot];
      if (!std::isnan(fv)) {
        v = fv;
      } else if (flt.have_prop) {
        if (!f.props.present ||
            !span_property_number(
                payload.substr(f.props.pos, f.props.len), flt.value_prop,
                &v))
          continue;
      } else {
        v = flt.default_value;
      }
      if (!span_view(payload, f.eid, out, &uid)) continue;
      if (!span_view(payload, f.teid, out, &iid)) continue;
    }
    auto ur = out->umap.emplace(uid, (int32_t)out->users.size());
    if (ur.second) out->users.push_back(uid);
    auto ir = out->imap.emplace(iid, (int32_t)out->items.size());
    if (ir.second) out->items.push_back(iid);
    out->uidx.push_back(ur.first->second);
    out->iidx.push_back(ir.first->second);
    out->vals.push_back((float)v);
    out->times.push_back(e.time_ms);
  }
}

// Columnar scan. `names`/`fixed_vals` are parallel: fixed_vals[i] = NaN
// means "resolve via value_prop / default_value". value_prop may be null
// (every non-fixed event gets default_value).
//
// Locking: the log mutex is held ONLY for the snapshot — fflush, a header
// prefilter pass over the in-memory index (copying the matching entries'
// 24-byte headers out), and the mmap of the flushed extent. The payload
// scan itself runs lock-free on the snapshot + mmap, so concurrent
// appends proceed while a training scan is in flight. The time the mutex
// was held is reported via pio_scan_lock_held_ns.
//
// Entry range: [min_entry_idx, max_entry_idx) in raw entry indices;
// max_entry_idx < 0 means "through the end". A NEGATIVE max_entry_idx
// keeps the historical output order (time-ascending, ties in append
// order, via the sorted index). A bounded range emits rows in ENTRY
// order instead and never builds/resorts the time index — the sharded
// Python caller (data/storage/cpplog.py) restores global time order with
// one stable sort across shards, which reproduces the sequential order
// exactly (stable sort by time over entry order == the sorted index).
//
// n_threads: internal scan threads; <= 0 = auto (one per kMinPerThread
// candidates up to the hardware limit). Sharded Python callers pass 1 so
// parallelism is owned by exactly one layer. Per-thread id tables are
// merged in partition order so the global table keeps first-seen order.
void* pio_evlog_scan_interactions(
    void* handle, int64_t start_ms, int64_t until_ms, int64_t min_entry_idx,
    int64_t max_entry_idx, const char* entity_type,
    const char* target_entity_type, const char** names,
    const double* fixed_vals, int32_t n_names, const char* value_prop,
    double default_value, int32_t n_threads) {
  auto* log = (EventLog*)handle;
  auto* res = new ScanResult();
  // empty name list matches nothing (find() contract); slot is a u16
  if (n_names <= 0 || n_names > 0xFFFF) {
    res->uoff.push_back(0);
    res->ioff.push_back(0);
    return res;
  }

  std::vector<std::string> name_strs(names, names + n_names);
  ScanFilters flt;
  flt.start_ms = start_ms;
  flt.until_ms = until_ms;
  flt.entity_type = entity_type;
  flt.target_entity_type = target_entity_type;
  flt.value_prop = value_prop ? std::string_view(value_prop)
                              : std::string_view();
  flt.names = &name_strs;
  for (auto& s : name_strs) flt.name_hs.push_back(fnv1a64(s.data(), s.size()));
  flt.fixed_vals = fixed_vals;
  flt.have_prop = value_prop != nullptr;
  flt.default_value = default_value;
  flt.etype_h = fnv1a64(entity_type, strlen(entity_type));

  std::vector<SnapEntry> snap;
  char* base = nullptr;
  size_t map_len = 0;
  std::string heap;
  struct timespec lt0, lt1;
  {
    std::lock_guard<std::mutex> g(log->mu);
    // clock starts AFTER acquisition: lock_ns reports time HELD (what a
    // concurrent writer pays per scan), not time spent queueing behind
    // sibling shards' snapshots
    clock_gettime(CLOCK_MONOTONIC, &lt0);
    fflush(log->f);
    const int64_t n_entries = (int64_t)log->entries.size();
    const int64_t lo = std::max<int64_t>(min_entry_idx, 0);
    const int64_t hi = max_entry_idx < 0
                           ? n_entries
                           : std::min(max_entry_idx, n_entries);
    auto prefilter = [&](int64_t idx) {
      const Entry& e = log->entries[idx];
      if (e.dead) return;
      if (e.time_ms < flt.start_ms || e.time_ms >= flt.until_ms) return;
      if (e.etype_hash != flt.etype_h) return;
      int32_t slot = -1;
      for (int32_t i = 0; i < n_names; ++i)
        if (e.name_hash == flt.name_hs[i]) { slot = i; break; }
      if (slot < 0) return;
      snap.push_back({e.time_ms, e.offset, e.payload_len, (uint16_t)e.flags,
                      (uint16_t)slot});
    };
    if (max_entry_idx >= 0) {
      for (int64_t idx = lo; idx < hi; ++idx) prefilter(idx);
    } else {
      resort(log);
      for (int64_t k = 0; k < (int64_t)log->sorted.size(); ++k)
        if (log->sorted[k] >= lo) prefilter(log->sorted[k]);
    }
    // mmap the flushed extent (it covers every snapshotted payload — all
    // were flushed before the snapshot); heap fallback if mmap fails
    struct stat st;
    const int fd = fileno(log->f);
    if (!snap.empty() && fstat(fd, &st) == 0 && st.st_size > 0) {
      map_len = (size_t)st.st_size;
      void* m = mmap(nullptr, map_len, PROT_READ, MAP_SHARED, fd, 0);
      if (m != MAP_FAILED) {
        base = (char*)m;
      } else {
        heap.resize(map_len);
        fseeko(log->f, 0, SEEK_SET);
        if (fread(&heap[0], 1, map_len, log->f) != map_len)
          snap.clear();
        else
          base = &heap[0];
        fseeko(log->f, 0, SEEK_END);
      }
    }
    clock_gettime(CLOCK_MONOTONIC, &lt1);
  }
  res->lock_ns = (lt1.tv_sec - lt0.tv_sec) * 1000000000LL +
                 (lt1.tv_nsec - lt0.tv_nsec);

  const int64_t total = (int64_t)snap.size();
  if (base == nullptr || total == 0) {
    res->uoff.push_back(0);
    res->ioff.push_back(0);
    if (base && map_len && base != heap.data()) munmap(base, map_len);
    return res;
  }

  int nt = n_threads;
  if (nt <= 0) {
    constexpr int64_t kMinPerThread = 200000;
    int hw = (int)std::thread::hardware_concurrency();
    nt = (int)std::min<int64_t>(
        std::max(hw, 1), std::max<int64_t>(1, total / kMinPerThread));
  }
  nt = std::max(1, std::min(nt, 16));

  std::vector<LocalScan> locals(nt);
  if (nt == 1) {
    scan_snap(base, snap, 0, total, flt, &locals[0]);
  } else {
    std::vector<std::thread> pool;
    const int64_t step = (total + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t lo = t * step, hi = std::min<int64_t>(total, lo + step);
      pool.emplace_back(scan_snap, base, std::cref(snap), lo, hi,
                        std::cref(flt), &locals[t]);
    }
    for (auto& th : pool) th.join();
  }
  // merge in partition order: global tables keep first-seen order. Views
  // still point into the mapped file / local arenas — the file stays
  // mapped until the merge has materialized the id tables.
  std::unordered_map<std::string_view, int32_t> gu, gi;
  std::vector<std::string_view> user_order, item_order;
  size_t nnz = 0;
  for (auto& L : locals) nnz += L.uidx.size();
  res->uidx.reserve(nnz);
  res->iidx.reserve(nnz);
  res->vals.reserve(nnz);
  res->times.reserve(nnz);
  for (auto& L : locals) {
    std::vector<int32_t> uremap(L.users.size()), iremap(L.items.size());
    for (size_t j = 0; j < L.users.size(); ++j) {
      auto r = gu.emplace(L.users[j], (int32_t)gu.size());
      if (r.second) user_order.push_back(L.users[j]);
      uremap[j] = r.first->second;
    }
    for (size_t j = 0; j < L.items.size(); ++j) {
      auto r = gi.emplace(L.items[j], (int32_t)gi.size());
      if (r.second) item_order.push_back(L.items[j]);
      iremap[j] = r.first->second;
    }
    for (size_t j = 0; j < L.uidx.size(); ++j) {
      res->uidx.push_back(uremap[L.uidx[j]]);
      res->iidx.push_back(iremap[L.iidx[j]]);
      res->vals.push_back(L.vals[j]);
      res->times.push_back(L.times[j]);
    }
  }
  res->uoff.push_back(0);
  for (auto& s : user_order) {
    res->ubuf += s;
    res->uoff.push_back((int64_t)res->ubuf.size());
  }
  res->ioff.push_back(0);
  for (auto& s : item_order) {
    res->ibuf += s;
    res->ioff.push_back((int64_t)res->ibuf.size());
  }
  if (base != heap.data() && map_len) munmap(base, map_len);
  return res;
}

// Bulk append: n records whose per-record byte fields live concatenated in
// `buf` — for record k, offs[7k..7k+7] delimit (entity_type, entity_id,
// event name, event id, target_entity_type, target_entity_id+props_blob?,
// json_payload)... see below. Field layout per record (7 ranges):
//   0 entity_type   1 entity_id   2 event name   3 event id
//   4 target_entity_type   5 target_entity_id   6 props_blob ++ json
// props_blob comes pre-packed ([u8 klen][key][f64 value] per numeric
// property) followed by the JSON document; `meta` per record packs
// (u8 has_target, u8 sidecar_ok, u8 n_props, u8 pad, u32 props_blob_len).
// When sidecar_ok, the record is written as [sidecar][json] with the
// kSidecar flag; otherwise as bare JSON. Hashing and framing happen here;
// one buffered write per batch. Returns n, or -1 with the file truncated
// back to the batch start on a write failure (never a partial batch).
int64_t pio_evlog_append_bulk(void* handle, int64_t n,
                              const int64_t* time_ms, const uint8_t* buf,
                              const int64_t* offs, const uint8_t* meta) {
  auto* log = (EventLog*)handle;
  if (n <= 0) return 0;
  std::lock_guard<std::mutex> g(log->mu);
  fseeko(log->f, 0, SEEK_END);
  const off_t batch_start = ftello(log->f);
  std::string out;
  out.reserve((size_t)(offs[7 * n] - offs[0]) +
              (size_t)n * (sizeof(RecHeader) + 32));
  std::vector<Entry> new_entries;
  new_entries.reserve(n);
  off_t pos = batch_start;
  for (int64_t k = 0; k < n; ++k) {
    const int64_t* o = offs + 7 * k;
    auto flen = [&](int i) { return (size_t)(o[i + 1] - o[i]); };
    auto fptr = [&](int i) { return (const char*)buf + o[i]; };
    auto field_hash = [&](int i) { return fnv1a64(fptr(i), flen(i)); };
    const uint8_t* m = meta + 8 * k;
    const bool has_target = m[0] != 0;
    const bool sidecar_ok = m[1] != 0;
    const uint8_t n_props = m[2];
    uint32_t props_len;
    memcpy(&props_len, m + 4, 4);
    const size_t json_len = flen(6) - props_len;
    const char* json = fptr(6) + props_len;
    uint32_t plen, flags;
    uint32_t side_len = 0;
    if (sidecar_ok) {
      side_len = 4 + 1 + 10 + (uint32_t)(flen(0) + flen(2) + flen(1)) +
                 (has_target ? (uint32_t)(flen(4) + flen(5)) : 0) + props_len;
      plen = side_len + (uint32_t)json_len;
      flags = kSidecar;
    } else {
      plen = (uint32_t)json_len;
      flags = 0;
    }
    RecHeader h{time_ms[k], field_hash(0), field_hash(1), field_hash(2),
                field_hash(3), plen, flags};
    out.append((const char*)&h, sizeof(h));
    if (sidecar_ok) {
      out.append((const char*)&side_len, 4);
      out.push_back((char)n_props);
      uint16_t l[5] = {(uint16_t)flen(0), (uint16_t)flen(2),
                       (uint16_t)flen(1),
                       has_target ? (uint16_t)flen(4) : kNoTarget,
                       has_target ? (uint16_t)flen(5) : (uint16_t)0};
      out.append((const char*)l, 10);
      out.append(fptr(0), flen(0));  // etype
      out.append(fptr(2), flen(2));  // event name
      out.append(fptr(1), flen(1));  // entity id
      if (has_target) {
        out.append(fptr(4), flen(4));
        out.append(fptr(5), flen(5));
      }
      out.append(fptr(6), props_len);
    }
    out.append(json, json_len);
    new_entries.push_back({time_ms[k], h.etype_hash, h.eid_hash, h.name_hash,
                           h.id_hash, (uint64_t)(pos + sizeof(h)), plen,
                           h.flags, false});
    pos += sizeof(h) + plen;
  }
  if (fwrite(out.data(), 1, out.size(), log->f) != out.size()) {
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), batch_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  for (auto& e : new_entries) {
    if (e.time_ms >= log->last_time && !log->sorted_dirty) {
      log->sorted.push_back((int64_t)log->entries.size());
    } else {
      log->sorted_dirty = true;
    }
    log->last_time = std::max(log->last_time, e.time_ms);
    log->entries.push_back(e);
    index_new_entry(log, (int64_t)log->entries.size() - 1);
  }
  return n;
}

// ---------------------------------------------------------------------------
// Columnar bulk import — the inverse of the interaction scan.
//
// Renders `n` interaction events (JSON payload + binary sidecar + framed
// header) entirely in C++ from columnar inputs: COO index arrays plus
// arrow-style id tables (byte blob + offsets — the same layout the scan
// emits). This is the high-throughput seeding path for `pio import` and the
// benchmark: no per-event Python objects exist anywhere. Plays the role of
// the reference's bulk write (data/.../storage/PEvents.scala:184
// `write(RDD[Event])` via the HBase TableOutputFormat).
// ---------------------------------------------------------------------------

static void json_escape_append(std::string* out, std::string_view s) {
  for (char c : s) {
    switch (c) {
      case '"': out->append("\\\""); break;
      case '\\': out->append("\\\\"); break;
      case '\b': out->append("\\b"); break;
      case '\f': out->append("\\f"); break;
      case '\n': out->append("\\n"); break;
      case '\r': out->append("\\r"); break;
      case '\t': out->append("\\t"); break;
      default:
        if ((uint8_t)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", (int)(uint8_t)c);
          out->append(buf);
        } else {
          out->push_back(c);  // raw utf-8 bytes are valid JSON strings
        }
    }
  }
}

static void iso8601_append(std::string* out, int64_t ms) {
  time_t secs = (time_t)(ms >= 0 ? ms / 1000 : (ms - 999) / 1000);
  int milli = (int)(ms - (int64_t)secs * 1000);
  struct tm tmv;
  gmtime_r(&secs, &tmv);
  char buf[40];
  snprintf(buf, sizeof(buf), "%04d-%02d-%02dT%02d:%02d:%02d.%03d+00:00",
           tmv.tm_year + 1900, tmv.tm_mon + 1, tmv.tm_mday, tmv.tm_hour,
           tmv.tm_min, tmv.tm_sec, milli);
  out->append(buf);
}

static uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

static void hex32_append(std::string* out, uint64_t a, uint64_t b) {
  static const char* d = "0123456789abcdef";
  char buf[32];
  for (int i = 15; i >= 0; --i) { buf[i] = d[a & 15]; a >>= 4; }
  for (int i = 31; i >= 16; --i) { buf[i] = d[b & 15]; b >>= 4; }
  out->append(buf, 32);
}

// Render the canonical Event JSON from a compact record's sidecar — byte-
// identical to what append_interactions used to store inline (key order,
// %.9g numbers, iso8601 times), so readers cannot tell a compact record
// from a JSON-carrying one.
static void render_compact_json(const SideFields& f, std::string_view id32,
                                int64_t time_ms, std::string* out) {
  out->append("{\"eventId\":\"");
  out->append(id32);
  out->append("\",\"event\":\"");
  json_escape_append(out, f.name);
  out->append("\",\"entityType\":\"");
  json_escape_append(out, f.etype);
  out->append("\",\"entityId\":\"");
  json_escape_append(out, f.eid);
  if (f.has_target) {
    out->append("\",\"targetEntityType\":\"");
    json_escape_append(out, f.tetype);
    out->append("\",\"targetEntityId\":\"");
    json_escape_append(out, f.teid);
  }
  out->append("\",\"properties\":{");
  // f.props for a compact record also holds the trailing id32; the loop is
  // n_props-bounded so it never reads into it
  std::string_view props = f.props;
  size_t pos = 0;
  for (uint8_t i = 0; i < f.n_props; ++i) {
    if (pos + 1 > props.size()) break;
    const uint8_t kl = (uint8_t)props[pos];
    ++pos;
    if (pos + kl + 8 > props.size()) break;
    if (i) out->push_back(',');
    out->push_back('"');
    json_escape_append(out, props.substr(pos, kl));
    pos += kl;
    out->append("\":");
    double v;
    memcpy(&v, props.data() + pos, 8);
    pos += 8;
    char vbuf[40];
    snprintf(vbuf, sizeof(vbuf), "%.9g", v);
    out->append(vbuf);
  }
  out->append("},\"eventTime\":\"");
  std::string iso;
  iso8601_append(&iso, time_ms);
  out->append(iso);
  out->append("\",\"tags\":[],\"creationTime\":\"");
  out->append(iso);
  out->append("\"}");
}

// Returns n on success; -1 on write failure (file truncated back to the
// batch start — never a partial batch); -2 when an id/field exceeds the
// sidecar length limits (caller falls back to the generic Python path).
int64_t pio_evlog_append_interactions(
    void* handle, int64_t n, const int64_t* time_ms, const int32_t* uidx,
    const int32_t* iidx, const float* vals, const char* ubuf,
    const int64_t* uoffs, int64_t n_users, const char* ibuf,
    const int64_t* ioffs, int64_t n_items, const char* entity_type,
    const char* target_entity_type, const char* event_name,
    const char* value_prop, uint64_t seed) {
  auto* log = (EventLog*)handle;
  if (n <= 0) return 0;
  const std::string_view etype(entity_type), tetype(target_entity_type);
  const std::string_view name(event_name), prop(value_prop);
  if (etype.size() >= kNoTarget || tetype.size() >= kNoTarget ||
      name.size() >= kNoTarget || prop.size() > 255)
    return -2;
  for (int64_t i = 0; i < n_users; ++i)
    if (uoffs[i + 1] - uoffs[i] >= kNoTarget) return -2;
  for (int64_t i = 0; i < n_items; ++i)
    if (ioffs[i + 1] - ioffs[i] >= kNoTarget) return -2;
  for (int64_t k = 0; k < n; ++k)
    if (!std::isfinite((double)vals[k]) || uidx[k] < 0 ||
        uidx[k] >= n_users || iidx[k] < 0 || iidx[k] >= n_items)
      return -2;

  const uint64_t etype_h = fnv1a64(etype.data(), etype.size());
  const uint64_t name_h = fnv1a64(name.data(), name.size());
  // per-user id hashes, computed once
  std::vector<uint64_t> uhash(n_users);
  for (int64_t i = 0; i < n_users; ++i)
    uhash[i] = fnv1a64(ubuf + uoffs[i], (size_t)(uoffs[i + 1] - uoffs[i]));

  // Record size is a function of the two id lengths alone, so a prefix sum
  // over the batch gives every record's exact byte offset — which makes the
  // rendering embarrassingly parallel: T threads fill disjoint slices of
  // one contiguous buffer, then a single fwrite lands the super-batch.
  // Super-batches (~2M events ≈ 270 MB) bound peak memory at import scale.
  const size_t base_rec = sizeof(RecHeader) + 4 + 1 + 10 + etype.size() +
                          name.size() + tetype.size() + 1 + prop.size() + 8 +
                          32;
  // respect the cpuset/affinity mask (containers routinely pin to fewer
  // CPUs than the machine has; hardware_concurrency ignores that and
  // oversubscribing a 1-core mask just adds spawn + context-switch cost)
#if defined(__linux__)
  cpu_set_t cs;
  int nthreads = sched_getaffinity(0, sizeof(cs), &cs) == 0
                     ? CPU_COUNT(&cs)
                     : (int)std::thread::hardware_concurrency();
#else
  int nthreads = (int)std::thread::hardware_concurrency();
#endif
  if (const char* env = getenv("PIO_NATIVE_THREADS")) {
    const int v = atoi(env);
    if (v > 0) nthreads = v;
  }
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  const int64_t kSuper = 2'000'000;
  if (n < 65536) nthreads = 1;  // spawn cost dwarfs tiny batches

  std::lock_guard<std::mutex> g(log->mu);
  fseeko(log->f, 0, SEEK_END);
  const off_t batch_start = ftello(log->f);
  const size_t old_n = log->entries.size();
  const int64_t old_last_time = log->last_time;
  off_t pos = batch_start;
  if (log->entries.capacity() < old_n + (size_t)n) {
    // grow geometrically: an exact reserve() reallocates-and-copies the
    // whole entry index on EVERY small append (O(total) per call — REST
    // ingest decayed from 77k to 6k ev/s as the log grew); doubling
    // amortizes the copy to O(1) per entry
    log->entries.reserve(std::max(old_n + (size_t)n, old_n * 2));
  }
  std::string buf;
  std::vector<size_t> rec_off;
  bool failed = false;
  bool monotone = true;  // batch times in order AND not before the log tail
  int64_t prev_t = log->last_time;
  int64_t max_t = log->last_time;
  for (int64_t s0 = 0; s0 < n && !failed; s0 += kSuper) {
    const int64_t m = std::min(n - s0, kSuper);
    rec_off.assign((size_t)m + 1, 0);
    for (int64_t k = 0; k < m; ++k) {
      const int32_t u = uidx[s0 + k], it = iidx[s0 + k];
      rec_off[k + 1] = rec_off[k] + base_rec +
                       (size_t)(uoffs[u + 1] - uoffs[u]) +
                       (size_t)(ioffs[it + 1] - ioffs[it]);
      const int64_t t = time_ms[s0 + k];
      if (t < prev_t) monotone = false;
      prev_t = t;
      if (t > max_t) max_t = t;
    }
    buf.resize(rec_off[(size_t)m]);
    log->entries.resize(old_n + (size_t)(s0 + m));
    Entry* ents = log->entries.data() + old_n + s0;
    char* out = buf.data();
    const off_t sb_pos = pos;
    auto render = [&, s0, sb_pos, ents, out](int64_t a, int64_t b) {
      std::string idhex;
      for (int64_t k = a; k < b; ++k) {
        const int64_t g_k = s0 + k;
        const int32_t u = uidx[g_k], it = iidx[g_k];
        const std::string_view uid(ubuf + uoffs[u],
                                   (size_t)(uoffs[u + 1] - uoffs[u]));
        const std::string_view iid(ibuf + ioffs[it],
                                   (size_t)(ioffs[it + 1] - ioffs[it]));
        const uint64_t ida = splitmix64(seed ^ (uint64_t)g_k);
        const uint64_t idb =
            splitmix64(seed + 0x9E3779B97F4A7C15ull + (uint64_t)g_k);
        idhex.clear();
        hex32_append(&idhex, ida, idb);
        const uint64_t id_h = fnv1a64(idhex.data(), 32);
        // COMPACT record: sidecar only (with the 32-char event id appended
        // inside the block); pio_evlog_read renders the JSON on demand via
        // render_compact_json
        const uint32_t side_len = (uint32_t)(rec_off[k + 1] - rec_off[k] -
                                             sizeof(RecHeader));
        const uint32_t flags = kSidecar | kCompact;
        char* p = out + rec_off[k];
        RecHeader h{time_ms[g_k], etype_h, uhash[u], name_h, id_h, side_len,
                    flags};
        memcpy(p, &h, sizeof(h));
        p += sizeof(h);
        memcpy(p, &side_len, 4);
        p += 4;
        *p++ = (char)1;  // n_props
        uint16_t l[5] = {(uint16_t)etype.size(), (uint16_t)name.size(),
                         (uint16_t)uid.size(), (uint16_t)tetype.size(),
                         (uint16_t)iid.size()};
        memcpy(p, l, 10);
        p += 10;
        auto put = [&p](std::string_view s) {
          memcpy(p, s.data(), s.size());
          p += s.size();
        };
        put(etype);
        put(name);
        put(uid);
        put(tetype);
        put(iid);
        *p++ = (char)prop.size();
        put(prop);
        const double v64 = (double)vals[g_k];
        memcpy(p, &v64, 8);
        p += 8;
        memcpy(p, idhex.data(), 32);
        ents[k] = {time_ms[g_k], etype_h, uhash[u], name_h, id_h,
                   (uint64_t)(sb_pos + (off_t)rec_off[k] + sizeof(RecHeader)),
                   side_len, flags, false};
      }
    };
    if (nthreads == 1) {
      render(0, m);
    } else {
      std::vector<std::thread> pool;
      pool.reserve((size_t)nthreads);
      const int64_t chunk = (m + nthreads - 1) / nthreads;
      for (int t = 0; t < nthreads; ++t) {
        const int64_t a = t * chunk, b = std::min(m, a + chunk);
        if (a >= b) break;
        pool.emplace_back(render, a, b);
      }
      for (auto& th : pool) th.join();
    }
    if (fwrite(buf.data(), 1, buf.size(), log->f) != buf.size())
      failed = true;
    pos += (off_t)buf.size();
  }
  if (failed) {
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), batch_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    log->entries.resize(old_n);  // sorted/last_time were never touched
    return -1;
  }
  fflush(log->f);
  if (monotone && !log->sorted_dirty) {
    const size_t old_sorted = log->sorted.size();
    log->sorted.resize(old_sorted + (size_t)n);
    for (int64_t k = 0; k < n; ++k)
      log->sorted[old_sorted + (size_t)k] = (int64_t)(old_n + (size_t)k);
  } else {
    log->sorted_dirty = true;
  }
  log->last_time = std::max(old_last_time, max_t);
  if (log->id_index_built)
    for (int64_t k = 0; k < n; ++k)
      index_new_entry(log, (int64_t)(old_n + (size_t)k));
  return n;
}

// ---------------------------------------------------------------------------
// Record-preserving compaction: copy LIVE records into a fresh log file at
// dst_path in the CURRENT on-disk format. Records that already carry a
// sidecar (incl. compact interaction records) byte-copy unchanged; bare-JSON
// records gain a sidecar built from the span parser — conservatively: a
// record whose relevant fields carry escapes or exceed the sidecar length
// limits stays bare JSON (readers handle both forms). Order: original log
// (append) order, which preserves the cross-backend equal-time tie-break.
// Returns the live-record count, or -1 on I/O failure (dst removed).
// ---------------------------------------------------------------------------

// Pack the NUMERIC top-level entries of a JSON object span as sidecar props
// (u8 klen, key bytes, f64 value). Returns false when the object cannot be
// represented (escaped/oversize keys, >255 numeric props) — caller keeps
// the record bare.
static bool pack_numeric_props(std::string_view obj, std::string* out,
                               uint8_t* n_out) {
  size_t i = 0;
  const size_t n = obj.size();
  int count = 0;
  if (n < 2 || obj[0] != '{') return false;
  i = 1;
  while (i < n) {
    while (i < n && (obj[i] == ' ' || obj[i] == '\t' || obj[i] == ',')) ++i;
    if (i < n && obj[i] == '}') break;
    if (i >= n || obj[i] != '"') return false;
    size_t kstart = ++i;
    bool kesc = false;
    while (i < n && obj[i] != '"') {
      if (obj[i] == '\\') { kesc = true; i += 2; } else ++i;
    }
    if (i >= n) return false;
    std::string_view key = obj.substr(kstart, i - kstart);
    ++i;
    while (i < n && (obj[i] == ' ' || obj[i] == '\t')) ++i;
    if (i >= n || obj[i] != ':') return false;
    ++i;
    while (i < n && (obj[i] == ' ' || obj[i] == '\t')) ++i;
    if (i >= n) return false;
    if (obj[i] == '"') {  // string value: skip
      ++i;
      while (i < n && obj[i] != '"') i += (obj[i] == '\\') ? 2 : 1;
      ++i;
    } else if (obj[i] == '{' || obj[i] == '[') {  // nested: skip balanced
      int d = 0;
      bool instr = false;
      while (i < n) {
        char c = obj[i];
        if (instr) {
          if (c == '\\') { i += 2; continue; }
          if (c == '"') instr = false;
          ++i;
          continue;
        }
        if (c == '"') { instr = true; ++i; continue; }
        if (c == '{' || c == '[') ++d;
        else if (c == '}' || c == ']') {
          if (--d == 0) { ++i; break; }
        }
        ++i;
      }
    } else {  // bare token: numeric, true/false/null
      size_t vstart = i;
      while (i < n && obj[i] != ',' && obj[i] != '}' && obj[i] != ' ' &&
             obj[i] != '\t')
        ++i;
      std::string tok(obj.substr(vstart, i - vstart));
      if (!tok.empty() && tok != "true" && tok != "false" && tok != "null") {
        char* end = nullptr;
        double v = strtod(tok.c_str(), &end);
        if (end == tok.c_str() + tok.size() && std::isfinite(v)) {
          if (kesc || key.size() > 255) return false;
          if (++count > 255) return false;
          out->push_back((char)key.size());
          out->append(key);
          out->append((const char*)&v, 8);
        }
      }
    }
  }
  *n_out = (uint8_t)count;
  return true;
}

int64_t pio_evlog_compact_copy(void* handle, const char* dst_path) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  FILE* dst = fopen(dst_path, "wb");
  if (!dst) return -1;
  fflush(log->f);
  int64_t live = 0;
  bool failed = false;
  std::string payload;
  std::string side;
  for (size_t idx = 0; idx < log->entries.size() && !failed; ++idx) {
    const Entry& e = log->entries[idx];
    if (e.dead || (e.flags & kTombstone)) continue;
    payload.resize(e.payload_len);
    fseeko(log->f, (off_t)e.offset, SEEK_SET);
    if (e.payload_len &&
        fread(payload.data(), 1, e.payload_len, log->f) != e.payload_len) {
      failed = true;
      break;
    }
    RecHeader h{e.time_ms, e.etype_hash, e.eid_hash, e.name_hash, e.id_hash,
                e.payload_len, e.flags};
    if (!(e.flags & kSidecar)) {
      // bare JSON: try the sidecar upgrade
      Fields f;
      side.clear();
      uint8_t n_props = 0;
      std::string props_packed;
      bool ok = extract_fields(payload, &f) && f.event.present &&
                f.etype.present && f.eid.present && !f.event.esc &&
                !f.etype.esc && !f.eid.esc &&
                (!f.tetype.present || !f.tetype.esc) &&
                (!f.teid.present || !f.teid.esc) &&
                f.tetype.present == f.teid.present &&
                f.etype.len < kNoTarget && f.event.len < kNoTarget &&
                f.eid.len < kNoTarget && f.tetype.len < kNoTarget &&
                f.teid.len < kNoTarget;
      if (ok && f.props.present)
        ok = pack_numeric_props(payload.substr(f.props.pos, f.props.len),
                                &props_packed, &n_props);
      if (ok) {
        const bool has_target = f.tetype.present;
        const uint32_t side_len =
            4 + 1 + 10 +
            (uint32_t)(f.etype.len + f.event.len + f.eid.len) +
            (has_target ? (uint32_t)(f.tetype.len + f.teid.len) : 0) +
            (uint32_t)props_packed.size();
        side.append((const char*)&side_len, 4);
        side.push_back((char)n_props);
        uint16_t l[5] = {(uint16_t)f.etype.len, (uint16_t)f.event.len,
                         (uint16_t)f.eid.len,
                         has_target ? (uint16_t)f.tetype.len : kNoTarget,
                         has_target ? (uint16_t)f.teid.len : (uint16_t)0};
        side.append((const char*)l, 10);
        side.append(payload, f.etype.pos, f.etype.len);
        side.append(payload, f.event.pos, f.event.len);
        side.append(payload, f.eid.pos, f.eid.len);
        if (has_target) {
          side.append(payload, f.tetype.pos, f.tetype.len);
          side.append(payload, f.teid.pos, f.teid.len);
        }
        side.append(props_packed);
        h.payload_len = side_len + (uint32_t)payload.size();
        h.flags = kSidecar;
      }
    }
    if (fwrite(&h, sizeof(h), 1, dst) != 1 ||
        (!side.empty() &&
         fwrite(side.data(), 1, side.size(), dst) != side.size()) ||
        (!payload.empty() &&
         fwrite(payload.data(), 1, payload.size(), dst) != payload.size()))
      failed = true;
    side.clear();
    ++live;
  }
  fseeko(log->f, 0, SEEK_END);
  // fdatasync BEFORE the caller renames dst over the original: a rename
  // is durable only if the replacement's blocks are — a crash after an
  // unsynced swap would lose the whole log
#if defined(__APPLE__)
  const bool synced = !failed && fflush(dst) == 0 &&
                      fcntl(fileno(dst), F_FULLFSYNC) != -1;
#else
  const bool synced = !failed && fflush(dst) == 0 &&
                      fdatasync(fileno(dst)) == 0;
#endif
  if (!synced) {
    fclose(dst);
    remove(dst_path);
    return -1;
  }
  fclose(dst);
  return live;
}

int64_t pio_scan_nnz(void* r) { return (int64_t)((ScanResult*)r)->uidx.size(); }

// Nanoseconds the scan held the log mutex (snapshot + mmap only) — the
// bench's lock-held-wall sub-metric; the payload scan runs lock-free.
int64_t pio_scan_lock_held_ns(void* r) { return ((ScanResult*)r)->lock_ns; }

int64_t pio_scan_n_ids(void* r, int32_t which) {
  auto* res = (ScanResult*)r;
  return (int64_t)(which == 0 ? res->uoff.size() : res->ioff.size()) - 1;
}

int64_t pio_scan_ids_bytes(void* r, int32_t which) {
  auto* res = (ScanResult*)r;
  return (int64_t)(which == 0 ? res->ubuf.size() : res->ibuf.size());
}

void pio_scan_fill(void* r, int32_t* u, int32_t* i, float* v) {
  auto* res = (ScanResult*)r;
  memcpy(u, res->uidx.data(), res->uidx.size() * sizeof(int32_t));
  memcpy(i, res->iidx.data(), res->iidx.size() * sizeof(int32_t));
  memcpy(v, res->vals.data(), res->vals.size() * sizeof(float));
}

// Per-row event times, parallel to pio_scan_fill's arrays — consumed by the
// Python training-projection cache (cpplog.py) so any full scan can seed it.
void pio_scan_fill_times(void* r, int64_t* t) {
  auto* res = (ScanResult*)r;
  memcpy(t, res->times.data(), res->times.size() * sizeof(int64_t));
}

void pio_scan_copy_ids(void* r, int32_t which, char* buf, int64_t* offsets) {
  auto* res = (ScanResult*)r;
  const std::string& b = which == 0 ? res->ubuf : res->ibuf;
  const std::vector<int64_t>& o = which == 0 ? res->uoff : res->ioff;
  memcpy(buf, b.data(), b.size());
  memcpy(offsets, o.data(), o.size() * sizeof(int64_t));
}

void pio_scan_free(void* r) { delete (ScanResult*)r; }

// Returns the payload length; copies into buf only when it fits. Dead or
// out-of-range records return -1.
int32_t pio_evlog_read(void* handle, int64_t index, uint8_t* buf,
                       int32_t cap) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  if (index < 0 || (size_t)index >= log->entries.size()) return -1;
  const Entry& e = log->entries[index];
  if (e.dead) return -1;
  uint64_t off = e.offset;
  uint32_t len = e.payload_len;
  if (e.flags & kCompact) {
    // no stored JSON: read the sidecar and render the canonical document
    std::string payload(len, '\0');
    fflush(log->f);
    fseeko(log->f, (off_t)off, SEEK_SET);
    const bool ok = fread(payload.data(), 1, len, log->f) == len;
    fseeko(log->f, 0, SEEK_END);
    SideFields sf;
    if (!ok || !parse_sidecar(payload.data(), len, &sf)) return -1;
    uint32_t bl;
    memcpy(&bl, payload.data(), 4);
    if (bl < 32 || bl > len) return -1;
    const std::string_view id32(payload.data() + bl - 32, 32);
    std::string json;
    render_compact_json(sf, id32, e.time_ms, &json);
    if ((int32_t)json.size() <= cap)
      memcpy(buf, json.data(), json.size());
    return (int32_t)json.size();
  }
  if (e.flags & kSidecar) {
    // skip the binary sidecar block: callers get the JSON document only
    uint32_t bl = 0;
    fflush(log->f);
    fseeko(log->f, (off_t)off, SEEK_SET);
    if (fread(&bl, 4, 1, log->f) != 1 || bl > len) {
      fseeko(log->f, 0, SEEK_END);
      return -1;
    }
    off += bl;
    len -= bl;
  }
  if ((int32_t)len <= cap) {
    fseeko(log->f, (off_t)off, SEEK_SET);
    if (fread(buf, 1, len, log->f) != len) return -1;
    fseeko(log->f, 0, SEEK_END);
  }
  return (int32_t)len;
}

// ---------------------------------------------------------------------------
// Replication frame IO: byte-level log shipping. A follower tails the
// leader's framed byte stream — whole records only, never split — and
// appends them verbatim, so the follower's file is bit-identical to the
// leader's prefix: entry numbering, tombstone target indices, sidecars
// and hashes all carry over with no re-derivation.

int64_t pio_evlog_file_size(void* handle) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  fflush(log->f);
  fseeko(log->f, 0, SEEK_END);
  return (int64_t)ftello(log->f);
}

// Copy whole frames for entries [from_entry, ...] into buf, up to
// max_bytes. Returns bytes copied (0 = already at the tail) and sets
// *out_entries to the frame count. When even the FIRST frame exceeds
// max_bytes, returns -(needed bytes) so the caller can retry with a
// bigger buffer instead of stalling the stream forever.
int64_t pio_evlog_read_frames(void* handle, int64_t from_entry,
                              int64_t max_bytes, uint8_t* buf,
                              int64_t* out_entries) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  *out_entries = 0;
  const int64_t total = (int64_t)log->entries.size();
  if (from_entry < 0 || from_entry > total) return -1;
  if (from_entry == total) return 0;
  const off_t start = (off_t)log->entries[from_entry].offset
                      - (off_t)sizeof(RecHeader);
  int64_t end = start;
  int64_t n = 0;
  for (int64_t i = from_entry; i < total; ++i) {
    const Entry& e = log->entries[i];
    const int64_t frame_end = (int64_t)e.offset + e.payload_len;
    if (frame_end - start > max_bytes) break;
    end = frame_end;
    ++n;
  }
  if (n == 0) {  // first frame alone is larger than the caller's buffer
    const Entry& e = log->entries[from_entry];
    return -((int64_t)e.offset + e.payload_len - start);
  }
  fflush(log->f);
  fseeko(log->f, start, SEEK_SET);
  const size_t want = (size_t)(end - start);
  const bool ok = fread(buf, 1, want, log->f) == want;
  fseeko(log->f, 0, SEEK_END);
  if (!ok) return -1;
  *out_entries = n;
  return (int64_t)want;
}

// Append a validated run of whole frames (as produced by read_frames) and
// index them exactly as the reopen scan would. All-or-nothing: a malformed
// buffer is rejected before any write; a failed write truncates back.
// Returns the new entry count, or -1.
int64_t pio_evlog_append_frames(void* handle, const uint8_t* buf,
                                int64_t nbytes) {
  auto* log = (EventLog*)handle;
  std::lock_guard<std::mutex> g(log->mu);
  // validation pass: every frame extent must land exactly on nbytes
  int64_t pos = 0;
  while (pos < nbytes) {
    if (pos + (int64_t)sizeof(RecHeader) > nbytes) return -1;
    RecHeader h;
    memcpy(&h, buf + pos, sizeof(h));
    pos += (int64_t)sizeof(h) + h.payload_len;
    if (pos > nbytes) return -1;
  }
  if (pos != nbytes) return -1;
  fseeko(log->f, 0, SEEK_END);
  const off_t rec_start = ftello(log->f);
  if (nbytes &&
      fwrite(buf, 1, (size_t)nbytes, log->f) != (size_t)nbytes) {
    fflush(log->f);
    (void)!ftruncate(fileno(log->f), rec_start);
    clearerr(log->f);
    fseeko(log->f, 0, SEEK_END);
    return -1;
  }
  fflush(log->f);
  // index pass: mirrors the pio_evlog_open scan (tombstone targets are
  // indices into the stream the frames came from — identical here by
  // construction, since the follower only ever appends the leader's
  // prefix in order)
  pos = 0;
  uint64_t off_base = (uint64_t)rec_start;
  while (pos < nbytes) {
    RecHeader h;
    memcpy(&h, buf + pos, sizeof(h));
    const uint64_t off = off_base + (uint64_t)pos + sizeof(h);
    if (h.flags & kTombstone) {
      int64_t target = -1;
      if (h.payload_len == 8) {
        memcpy(&target, buf + pos + sizeof(h), 8);
        if (target >= 0 && (size_t)target < log->entries.size() &&
            !log->entries[target].dead) {
          log->entries[target].dead = true;
          ++log->dead_count;
        }
      }
      ++log->dead_count;  // the marker entry itself
      log->entries.push_back({0, 0, 0, 0, 0, off, h.payload_len, h.flags,
                              true});
    } else {
      log->last_time = std::max(log->last_time, h.time_ms);
      log->entries.push_back({h.time_ms, h.etype_hash, h.eid_hash,
                              h.name_hash, h.id_hash, off, h.payload_len,
                              h.flags, false});
      index_new_entry(log, (int64_t)log->entries.size() - 1);
    }
    pos += (int64_t)sizeof(h) + h.payload_len;
  }
  log->sorted_dirty = true;
  return (int64_t)log->entries.size();
}

int64_t pio_evlog_hash_ids(const char* blob, const int64_t* offsets,
                           int64_t n, uint64_t* out) {
  // Batched FNV-1a over an interned id table (blob + offsets, the
  // IdTable layout): one crossing for the whole table instead of a
  // per-id Python hash — the writer-shard spray's hot loop.
  if (!blob || !offsets || !out || n < 0) return -1;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t len = offsets[i + 1] - offsets[i];
    if (len < 0) return -1;
    out[i] = fnv1a64(blob + offsets[i], (size_t)len);
  }
  return n;
}

}  // extern "C"
