// Degree-bucketed padded-rows builder — the native host-side data loader.
//
// This is the hot host loop between the event store and the device: COO
// interaction triplets → the static-shape padded buckets the ALS sweep
// consumes (ops/sparse.py documents the layout; the reference's analogous
// stage is MLlib's RDD block partitioning inside ALS.train, invoked from
// examples/.../ALSAlgorithm.scala — executor-side JVM code, hence the
// native obligation here). The Python/numpy builder loops over rows in the
// interpreter; at ML-20M scale (~20M triplets, ~165k user rows) that loop
// dominates training-read time, so it moves to C++: counting sort by row +
// one linear fill pass, both O(nnz).
//
// Two-call protocol (caller allocates everything, nothing is malloc'd
// across the boundary):
//   1. pio_csr_plan   → per-bucket segment counts
//   2. pio_csr_fill   → fills caller-allocated per-bucket arrays
// Buckets: bucket b holds segments of width min_width << b; rows longer
// than max_width are split into max_width segments (same rule as
// ops/sparse.py build_padded_rows, including stable within-row order).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

// bucket index for a segment of `seg` entries
inline int bucket_of(int64_t seg, int32_t min_width, int32_t n_buckets) {
  int b = 0;
  int64_t w = min_width;
  while (w < seg && b < n_buckets - 1) { w <<= 1; ++b; }
  return b;
}

struct Plan {
  std::vector<int64_t> counts;        // per-row nnz
  std::vector<int64_t> row_start;     // prefix sums into sorted order
  std::vector<int64_t> order;         // counting-sorted triplet indices
};

int build_plan(const int32_t* rows, int64_t nnz, int64_t n_rows, Plan* p) {
  p->counts.assign(n_rows, 0);
  for (int64_t i = 0; i < nnz; ++i) {
    int32_t r = rows[i];
    if (r < 0 || r >= n_rows) return -1;
    p->counts[r]++;
  }
  p->row_start.assign(n_rows + 1, 0);
  for (int64_t r = 0; r < n_rows; ++r)
    p->row_start[r + 1] = p->row_start[r] + p->counts[r];
  p->order.resize(nnz);
  std::vector<int64_t> cursor(p->row_start.begin(), p->row_start.end() - 1);
  for (int64_t i = 0; i < nnz; ++i)
    p->order[cursor[rows[i]]++] = i;   // stable: preserves input order
  return 0;
}

}  // namespace

extern "C" {

// Writes the number of segments per bucket into bucket_counts[n_buckets].
int64_t pio_csr_plan(const int32_t* rows, int64_t nnz, int64_t n_rows,
                     int32_t min_width, int32_t max_width, int32_t n_buckets,
                     int64_t* bucket_counts) {
  std::vector<int64_t> counts(n_rows, 0);
  for (int64_t i = 0; i < nnz; ++i) {
    int32_t r = rows[i];
    if (r < 0 || r >= n_rows) return -1;
    counts[r]++;
  }
  for (int32_t b = 0; b < n_buckets; ++b) bucket_counts[b] = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    int64_t rem = counts[r];
    while (rem > 0) {
      int64_t seg = std::min<int64_t>(rem, max_width);
      bucket_counts[bucket_of(seg, min_width, n_buckets)]++;
      rem -= seg;
    }
  }
  return 0;
}

// Fills per-bucket arrays. For bucket b (width w = min_width << b) the
// caller passes row_ids[b] (int32[count_b]), out_cols[b]/out_vals[b]/
// out_mask[b] (count_b × w, zero-initialized), and bucket_counts[b] (the
// allocation sizes, normally from pio_csr_plan). Returns the total number
// of segments written, or -1 on bad input — including any bucket whose
// allocation would overflow, so a caller-precomputed plan (the pipelined
// ingest path derives bucket counts from per-shard degree histograms
// accumulated DURING the scan) can never corrupt memory: a mismatch is
// rejected, never written past the allocation. Callers must also check
// the returned segment total against their plan — an over-allocated plan
// fills fewer segments than allocated and the tail rows would be junk.
int64_t pio_csr_fill(const int32_t* rows, const int32_t* cols,
                     const float* vals, int64_t nnz, int64_t n_rows,
                     int32_t min_width, int32_t max_width, int32_t n_buckets,
                     const int64_t* bucket_counts,
                     int32_t* const* out_row_ids, int32_t* const* out_cols,
                     float* const* out_vals, float* const* out_mask) {
  Plan p;
  if (build_plan(rows, nnz, n_rows, &p) != 0) return -1;
  std::vector<int64_t> cursor(n_buckets, 0);
  int64_t segments = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    int64_t off = 0, cnt = p.counts[r];
    while (cnt - off > 0) {
      int64_t seg = std::min<int64_t>(cnt - off, max_width);
      int b = bucket_of(seg, min_width, n_buckets);
      int64_t width = (int64_t)min_width << b;
      if (bucket_counts && cursor[b] >= bucket_counts[b]) return -1;
      int64_t slot = cursor[b]++;
      ++segments;
      out_row_ids[b][slot] = (int32_t)r;
      int32_t* oc = out_cols[b] + slot * width;
      float* ov = out_vals[b] + slot * width;
      float* om = out_mask[b] + slot * width;
      for (int64_t j = 0; j < seg; ++j) {
        int64_t k = p.order[p.row_start[r] + off + j];
        oc[j] = cols[k];
        ov[j] = vals[k];
        om[j] = 1.0f;
      }
      off += seg;
    }
  }
  return segments;
}

}  // extern "C"
