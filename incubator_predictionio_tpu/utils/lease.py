"""Single-tenant accelerator lease safety helpers.

On this platform a process killed ABRUPTLY while holding the chip (its
PJRT client mid-RPC) wedges the single-tenant lease for every later
process — observed: hours-long wedges after a `timeout`-style SIGTERM,
whose default Python action is immediate death with no interpreter
shutdown (no atexit, no client destructors, sockets torn mid-frame). The
lease-safety contract (cli/main.py _ensure_accelerator docstring): any
TPU-touching process must exit via NORMAL interpreter shutdown so the
relay sees a clean disconnect.

:func:`install_sigterm_exit` converts SIGTERM into ``SystemExit`` so
`timeout`, supervisors, and Ctrl-style termination tear the process down
through the interpreter instead of around it. The handler runs between
bytecodes: a dispatch blocked inside the PJRT client returns first, then
the exit proceeds — exactly the "finish the op, then leave cleanly"
behavior the lease needs.

Install-ORDER contract: TPU entry points that dial on the main thread
(bench children, kernel-tuning scripts) install the handler AFTER
``jax.devices()`` returns — a waiter blocked inside the PJRT constructor
can only be stopped by the default OS-level kill (a Python handler never
fires inside a blocked C call), and supervisors depend on being able to
kill waiters; only a process that HOLDS the chip needs the graceful
exit. The CLI installs at entry because its dial runs on a daemon probe
thread (cli/main.py _ensure_accelerator) — the main thread stays
signal-interruptible throughout.
"""

from __future__ import annotations

import signal
import sys
import threading


def install_sigterm_exit(code: int = 143) -> bool:
    """Install a SIGTERM → ``SystemExit(code)`` handler (main thread
    only; signal handlers cannot be installed elsewhere). Returns True
    when installed. Idempotent; never raises."""
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        def _exit(_signum, _frame):
            # raising (not os._exit) unwinds through finally blocks and
            # atexit, closing the PJRT client's sockets cleanly
            raise SystemExit(code)

        signal.signal(signal.SIGTERM, _exit)
        return True
    except (ValueError, OSError):  # non-main interpreter contexts
        return False


def _selftest() -> None:  # pragma: no cover - manual aid
    install_sigterm_exit()
    signal.raise_signal(signal.SIGTERM)


if __name__ == "__main__":  # pragma: no cover
    _selftest()
    sys.exit(1)  # unreachable if the handler worked
