"""The canonical JSON ⇄ typed-params codec.

The reference needs a *dual* extractor (json4s for Scala engines, gson for
Java engines, with a ``Both`` fallback mode — reference:
core/.../workflow/JsonExtractor.scala:17-167, JsonExtractorOption.scala)
because engines can be written in either language. Here there is exactly one
engine language (Python dataclasses), so this module defines ONE canonical
codec plus an explicit, documented compatibility shim for gson-style leniency
(numeric widening, string→number parsing) instead of the ``Both`` fallback.

Supported target types for :func:`extract`:

- dataclasses (fields recursively extracted; missing fields use defaults)
- ``int`` / ``float`` / ``bool`` / ``str`` (with lenient numeric coercion)
- ``datetime`` (ISO-8601 strings)
- ``list[T]`` / ``tuple[T, ...]`` / ``set[T]`` / ``dict[K, V]``
- ``Optional[T]`` and general ``Union`` (first member that extracts wins)
- ``typing.Any`` (passed through untouched)
- ``enum.Enum`` subclasses (by value or by name)
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import json
import types
import typing
from datetime import datetime
from typing import Any, Optional, Type, TypeVar, Union, get_args, get_origin

from incubator_predictionio_tpu.utils.times import format_iso8601, parse_iso8601

T = TypeVar("T")

_MISSING = dataclasses.MISSING


class ExtractionError(ValueError):
    """Raised when a JSON value cannot be converted to the requested type."""


def extract(cls: Type[T], obj: Any, *, lenient: bool = True) -> T:
    """Convert a parsed-JSON value ``obj`` into an instance of ``cls``.

    ``lenient`` enables the gson-compatibility shim: ``"3"`` extracts to
    ``3``, ``3`` extracts to ``3.0`` for float targets, etc. With
    ``lenient=False`` the codec behaves like json4s-native (strict types,
    except int→float widening which JSON itself does not distinguish).
    """
    return _extract(cls, obj, lenient)


def extract_json(cls: Type[T], text: str, *, lenient: bool = True) -> T:
    """Parse ``text`` as JSON and extract ``cls`` from it."""
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as e:
        raise ExtractionError(f"Invalid JSON for {cls!r}: {e}") from e
    return extract(cls, obj, lenient=lenient)


def _extract(cls: Any, obj: Any, lenient: bool) -> Any:
    if cls is Any or cls is None or cls is type(None):
        if cls is type(None) and obj is not None:
            raise ExtractionError(f"Expected null, got {obj!r}")
        return obj

    origin = get_origin(cls)

    if origin is Union or origin is types.UnionType:
        return _extract_union(cls, obj, lenient)

    if dataclasses.is_dataclass(cls) and isinstance(cls, type):
        return _extract_dataclass(cls, obj, lenient)

    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return _extract_enum(cls, obj)

    if cls is datetime:
        if isinstance(obj, datetime):
            return obj
        if isinstance(obj, str):
            try:
                return parse_iso8601(obj)
            except ValueError as e:
                raise ExtractionError(str(e)) from e
        raise ExtractionError(f"Cannot convert {obj!r} to datetime")

    if cls is bool:
        if isinstance(obj, bool):
            return obj
        if lenient and isinstance(obj, str) and obj.lower() in ("true", "false"):
            return obj.lower() == "true"
        raise ExtractionError(f"Cannot convert {obj!r} to bool")

    if cls is int:
        if isinstance(obj, bool):
            raise ExtractionError(f"Cannot convert bool {obj!r} to int")
        if isinstance(obj, int):
            return obj
        if isinstance(obj, float) and obj.is_integer():
            return int(obj)
        if lenient and isinstance(obj, str):
            try:
                return int(obj)
            except ValueError:
                pass
        raise ExtractionError(f"Cannot convert {obj!r} to int")

    if cls is float:
        if isinstance(obj, bool):
            raise ExtractionError(f"Cannot convert bool {obj!r} to float")
        if isinstance(obj, (int, float)):
            return float(obj)
        if lenient and isinstance(obj, str):
            try:
                return float(obj)
            except ValueError:
                pass
        raise ExtractionError(f"Cannot convert {obj!r} to float")

    if cls is str:
        if isinstance(obj, str):
            return obj
        if lenient and isinstance(obj, (int, float, bool)):
            return json.dumps(obj)
        raise ExtractionError(f"Cannot convert {obj!r} to str")

    if origin in (list, typing.List):
        (item_t,) = get_args(cls) or (Any,)
        if not isinstance(obj, list):
            raise ExtractionError(f"Expected JSON array for {cls}, got {obj!r}")
        return [_extract(item_t, v, lenient) for v in obj]

    if origin in (set, frozenset):
        (item_t,) = get_args(cls) or (Any,)
        if not isinstance(obj, list):
            raise ExtractionError(f"Expected JSON array for {cls}, got {obj!r}")
        out = {_extract(item_t, v, lenient) for v in obj}
        return frozenset(out) if origin is frozenset else out

    if origin is tuple:
        args = get_args(cls)
        if not isinstance(obj, list):
            raise ExtractionError(f"Expected JSON array for {cls}, got {obj!r}")
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_extract(args[0], v, lenient) for v in obj)
        if len(args) != len(obj):
            raise ExtractionError(f"Expected {len(args)} elements for {cls}, got {len(obj)}")
        return tuple(_extract(t, v, lenient) for t, v in zip(args, obj))

    if origin in (dict, typing.Dict):
        key_t, val_t = get_args(cls) or (Any, Any)
        if not isinstance(obj, dict):
            raise ExtractionError(f"Expected JSON object for {cls}, got {obj!r}")
        return {
            _extract(key_t, k, lenient): _extract(val_t, v, lenient)
            for k, v in obj.items()
        }

    if cls in (dict, list, object):
        return obj

    # Classes exposing a from_jsonable hook (e.g. DataMap).
    hook = getattr(cls, "from_jsonable", None)
    if hook is not None:
        return hook(obj)

    try:
        if isinstance(obj, cls):
            return obj
    except TypeError:
        pass  # non-class target (e.g. subscripted generic) — fall through
    raise ExtractionError(f"Unsupported extraction target {cls!r} for {obj!r}")


def _extract_union(cls: Any, obj: Any, lenient: bool) -> Any:
    args = get_args(cls)
    # Optional[T]: null maps to None.
    if obj is None and type(None) in args:
        return None
    errors = []
    for arg in args:
        if arg is type(None):
            continue
        try:
            return _extract(arg, obj, lenient)
        except ExtractionError as e:
            errors.append(str(e))
    raise ExtractionError(f"No member of {cls} matched {obj!r}: {errors}")


def _extract_enum(cls: Type[enum.Enum], obj: Any) -> enum.Enum:
    try:
        return cls(obj)
    except ValueError:
        pass
    if isinstance(obj, str):
        try:
            return cls[obj]
        except KeyError:
            pass
    raise ExtractionError(f"Cannot convert {obj!r} to {cls.__name__}")


@functools.lru_cache(maxsize=4096)
def snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part.title() for part in rest)


@functools.lru_cache(maxsize=None)
def _type_hints(cls: type) -> dict:
    """Cached ``get_type_hints``: with ``from __future__ import
    annotations`` every hint is a string the typing module COMPILES and
    evaluates on each call — measured at half the serving hot path
    before this cache (one /queries.json = one Query extraction + one
    PredictedResult serialization)."""
    return typing.get_type_hints(cls)


@functools.lru_cache(maxsize=None)
def _wire_fields(cls: type):
    """Cached (field, wire_name) pairs for dataclass serialization."""
    camel = getattr(cls, "__camel_case__", False)
    return tuple(
        (f, snake_to_camel(f.name) if camel else f.name)
        for f in dataclasses.fields(cls)
    )


def _extract_dataclass(cls: type, obj: Any, lenient: bool) -> Any:
    if isinstance(obj, cls):
        return obj
    if not isinstance(obj, dict):
        raise ExtractionError(f"Expected JSON object for {cls.__name__}, got {obj!r}")
    hints = _type_hints(cls)
    # Classes with __camel_case__ speak the reference's camelCase wire format
    # (e.g. itemScores/creationYear) while staying snake_case in Python;
    # _wire_fields caches the (field, wire-name) pairs per class.
    kwargs = {}
    for f, wire in _wire_fields(cls):
        if not f.init:
            continue
        key = f.name
        if key not in obj and wire != key and wire in obj:
            key = wire
        if key in obj:
            kwargs[f.name] = _extract(hints.get(f.name, Any), obj[key], lenient)
        elif f.default is not _MISSING or f.default_factory is not _MISSING:  # type: ignore[misc]
            continue  # use the dataclass default
        else:
            raise ExtractionError(
                f"Missing required field {f.name!r} for {cls.__name__} in {obj!r}"
            )
    return cls(**kwargs)


def to_jsonable(obj: Any) -> Any:
    """Convert a value into plain JSON-serializable Python structures.

    Inverse of :func:`extract` (reference: JsonExtractor.paramToJson,
    core/.../workflow/JsonExtractor.scala:90-120).
    """
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, datetime):
        return format_iso8601(obj)
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            wire: to_jsonable(getattr(obj, f.name))
            for f, wire in _wire_fields(type(obj))
        }
    hook = getattr(obj, "to_jsonable", None)
    if hook is not None and not isinstance(obj, type):
        return hook()
    if isinstance(obj, dict):
        return {str(k): to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_jsonable(v) for v in obj]
    raise TypeError(f"Cannot convert {type(obj).__name__} to JSON: {obj!r}")


def dumps(obj: Any, **kw: Any) -> str:
    """``json.dumps`` through :func:`to_jsonable`."""
    return json.dumps(to_jsonable(obj), **kw)
