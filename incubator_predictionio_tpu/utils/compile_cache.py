"""Persistent XLA compilation cache for the CLI/server processes.

The reference pays a JVM+Spark startup cost on every ``pio train``/``pio
deploy`` (spark-submit process hop, tools/.../Runner.scala:101-213); the
TPU-native analogue of that fixed cost is XLA compilation (~15 s for the
fused ALS program on v5e). JAX ships a persistent compilation cache keyed
on the HLO; pointing it at a directory under ``$PIO_HOME`` makes every
process after the first start warm — train/deploy/eval all skip straight
to execution.

Enabled automatically by the CLI and servers; opt out with
``PIO_COMPILE_CACHE=off`` or redirect with ``PIO_COMPILE_CACHE=/path``.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_enabled = False


def enable() -> None:
    """Idempotently enable the persistent compilation cache."""
    global _enabled
    if _enabled:
        return
    setting = os.environ.get("PIO_COMPILE_CACHE", "")
    if setting.lower() in ("off", "0", "false", "disable"):
        return
    if setting and setting.lower() not in ("on", "1", "true"):
        cache_dir = setting
    else:
        from incubator_predictionio_tpu.data.storage import pio_home

        cache_dir = os.path.join(pio_home(), "xla_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # env vars, NOT jax.config: jax reads these at import time, so
        # commands that never touch jax (app new, status, export) stay
        # fast while train/deploy still get the cache when they import it
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        # cache every program that takes noticeable time to compile
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        import sys
        if "jax" in sys.modules:  # already imported: apply directly
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
        _enabled = True
    except Exception as exc:  # pragma: no cover - cache is best-effort
        logger.warning("compilation cache unavailable: %s", exc)
