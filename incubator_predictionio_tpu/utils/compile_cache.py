"""Persistent XLA compilation cache for the CLI/server processes.

The reference pays a JVM+Spark startup cost on every ``pio train``/``pio
deploy`` (spark-submit process hop, tools/.../Runner.scala:101-213); the
TPU-native analogue of that fixed cost is XLA compilation (~15 s for the
fused ALS program on v5e). JAX ships a persistent compilation cache keyed
on the HLO; pointing it at a directory under ``$PIO_HOME`` makes every
process after the first start warm — train/deploy/eval all skip straight
to execution.

Enabled automatically by the CLI and servers; opt out with
``PIO_COMPILE_CACHE=off`` or redirect with ``PIO_COMPILE_CACHE=/path``.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_enabled = False
_listener_installed = False


def _install_metrics_listener() -> None:
    """Bridge JAX's compilation-cache monitoring events into the obs
    registry: ``pio_compile_cache_hits_total`` / ``_requests_total``
    counters (misses = requests − hits, derived as a gauge at scrape
    time). Counters exist from the moment the cache is enabled, so a
    scrape always sees the series even before the first compile. The
    jax.monitoring event names are version-dependent — the whole bridge
    is best-effort and a missing API degrades to zero counters, never
    an error."""
    global _listener_installed
    if _listener_installed:
        return
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    hits = obs_metrics.REGISTRY.counter(
        "pio_compile_cache_hits_total",
        "XLA persistent-cache hits (compile skipped)")
    requests = obs_metrics.REGISTRY.counter(
        "pio_compile_cache_requests_total",
        "compile requests eligible for the persistent cache")
    misses = obs_metrics.REGISTRY.gauge(
        "pio_compile_cache_misses",
        "cache-eligible compiles that missed (requests - hits)")
    obs_metrics.REGISTRY.register_collector(
        "compile_cache_misses",
        lambda: misses.set(max(requests.value - hits.value, 0)))
    try:
        from jax._src import monitoring

        def on_event(event: str, **_kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                hits.inc()
            elif event == "/jax/compilation_cache/compile_requests_use_cache":
                requests.inc()

        monitoring.register_event_listener(on_event)
        _listener_installed = True
    except Exception:  # pragma: no cover - monitoring API drift
        logger.debug("jax monitoring unavailable; compile-cache "
                     "counters stay at zero", exc_info=True)
        _listener_installed = True  # don't retry (and re-register) forever


def enable(cache_dir: str | None = None) -> None:
    """Idempotently enable the persistent compilation cache.

    ``cache_dir`` overrides the resolution below (used by the bench to
    point at a fresh directory for an honestly-cold measurement).

    On platforms whose site customization pre-imports jax at interpreter
    startup (the tunneled TPU image does), setting the JAX_* env vars is
    ALWAYS too late — jax.config has already read its defaults — so when
    jax is in sys.modules the settings are applied via jax.config.update
    directly. The env vars are still set for child processes and for
    platforms where jax genuinely hasn't been imported yet (there they
    keep `pio app new`-style commands from paying the jax import)."""
    global _enabled
    if _enabled and cache_dir is None:
        return
    # an explicit cache_dir re-points the cache even when already enabled
    # (the bench directs different measurement phases at fresh dirs)
    setting = os.environ.get("PIO_COMPILE_CACHE", "")
    if setting.lower() in ("off", "0", "false", "disable"):
        return
    explicit = cache_dir is not None or (
        setting and setting.lower() not in ("on", "1", "true"))
    if cache_dir is None:
        if setting and setting.lower() not in ("on", "1", "true"):
            cache_dir = setting
        else:
            from incubator_predictionio_tpu.data.storage import pio_home

            cache_dir = os.path.join(pio_home(), "xla_cache")
    try:
        # a user-set JAX_COMPILATION_CACHE_DIR still wins over the implicit
        # PIO_HOME default; explicit PIO_COMPILE_CACHE=/path or a direct
        # cache_dir argument wins over everything
        if explicit:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        else:
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
        os.makedirs(cache_dir, exist_ok=True)
        # cache every program that takes noticeable time to compile
        # (setdefault: a user-tuned threshold wins here too)
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        min_compile_s = float(
            os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"])
        import sys
        if "jax" in sys.modules:  # pre-imported: env vars are too late
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", min_compile_s)
            if _enabled:
                # jax lazily opens its file-cache handle once per process;
                # re-pointing an already-active cache needs a reset or the
                # old directory keeps serving
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.reset_cache()
        _enabled = True
        _install_metrics_listener()
    except Exception as exc:  # pragma: no cover - cache is best-effort
        logger.warning("compilation cache unavailable: %s", exc)
