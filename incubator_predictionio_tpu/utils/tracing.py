"""Per-phase wall-clock tracing + optional device profiling.

The reference's nearest mechanisms are per-query latency bookkeeping in the
prediction server (CreateServer.scala:426-428,611-618), `WorkflowParams.
verbose` with `debugString` RDD dumps (WorkflowUtils.scala:217-239), and the
implicit Spark UI. The TPU build replaces them with an explicit tracer: the
workflow runner times every pipeline phase (read/prepare/train/checkpoint),
records the timings on the EngineInstance, and can capture a device-level
``jax.profiler`` trace for TensorBoard when a profile dir is configured.

Usage::

    tracer = Tracer()
    with tracer.activate():
        with phase("read"):
            ...
    tracer.timings  # {"read": 0.123}
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import time
from typing import Any, Dict, Iterator, Optional

logger = logging.getLogger(__name__)

_current: contextvars.ContextVar[Optional["Tracer"]] = contextvars.ContextVar(
    "pio_tpu_tracer", default=None
)


class Tracer:
    """Accumulates named phase durations (seconds) for one workflow run."""

    def __init__(self, profile_dir: Optional[str] = None):
        self.timings: Dict[str, float] = {}
        self.profile_dir = profile_dir
        self._profiling = False

    # -- activation --------------------------------------------------------
    @contextlib.contextmanager
    def activate(self, profile: bool = True) -> Iterator["Tracer"]:
        """Install as the ambient tracer; starts/stops the jax profiler
        when ``profile_dir`` is set. Pass ``profile=False`` when the
        traced region is a re-activation around already-computed work
        (the pod training path activates twice) — a second profiler
        start would drop a spurious near-empty trace next to the real
        one in ``profile_dir``."""
        token = _current.set(self)
        if profile:
            self._start_profiler()
        try:
            yield self
        finally:
            if profile:
                self._stop_profiler()
            _current.reset(token)

    def _start_profiler(self) -> None:
        if not self.profile_dir:
            return
        try:
            import jax

            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            logger.info("tracing: jax profiler capturing to %s",
                        self.profile_dir)
        except Exception:
            logger.warning("tracing: could not start jax profiler",
                           exc_info=True)

    def _stop_profiler(self) -> None:
        if not self._profiling:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            logger.warning("tracing: could not stop jax profiler",
                           exc_info=True)
        self._profiling = False

    # -- recording ---------------------------------------------------------
    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.timings[name] = self.timings.get(name, 0.0) + dt
            logger.info("tracing: phase %s took %.3fs", name, dt)

    def summary(self) -> str:
        total = sum(self.timings.values())
        parts = ", ".join(
            f"{k}={v:.3f}s" for k, v in self.timings.items()
        )
        return f"total={total:.3f}s ({parts})"

    def to_conf(self) -> Dict[str, str]:
        """Phase timings as string values for EngineInstance.runtime_conf."""
        return {
            f"phase.{name}_s": f"{secs:.6f}"
            for name, secs in self.timings.items()
        }

    def export_metrics(self) -> None:
        """Publish this run's phase walls into the process-wide metrics
        registry (``pio_workflow_phase_seconds{phase=...}`` gauges +
        a run counter), so a /metrics scrape on any server co-hosted
        with training sees the last run's read/prepare/train/checkpoint
        breakdown next to the serving metrics. Gauges, not counters:
        each workflow run REPLACES the previous run's wall per phase
        (phase names are a bounded label set — pipeline stages, not
        user data). Called by CoreWorkflow after each run; never from
        inside traced code."""
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        phase_g = obs_metrics.REGISTRY.gauge(
            "pio_workflow_phase_seconds",
            "wall seconds per workflow phase, last run", labels=("phase",))
        runs = obs_metrics.REGISTRY.counter(
            "pio_workflow_runs_total", "workflow runs that exported timings")
        for name, secs in self.timings.items():
            phase_g.labels(phase=name).set(secs)
        runs.inc()


def current() -> Optional[Tracer]:
    return _current.get()


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Time a phase on the ambient tracer; no-op when none is active."""
    tracer = _current.get()
    if tracer is None:
        yield
        return
    with tracer.phase(name):
        yield


def debug_string(obj: Any, max_items: int = 10) -> str:
    """Human dump of a pipeline intermediate (WorkflowUtils.debugString
    parity — there it collects an RDD; here it summarizes arrays/sequences
    without forcing a device transfer of the full buffer)."""
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return f"<array shape={tuple(obj.shape)} dtype={obj.dtype}>"
    if isinstance(obj, dict):
        items = list(obj.items())[:max_items]
        body = ", ".join(f"{k!r}: {debug_string(v)}" for k, v in items)
        more = "" if len(obj) <= max_items else f", … +{len(obj)-max_items}"
        return "{" + body + more + "}"
    if isinstance(obj, (list, tuple)):
        items = [debug_string(x) for x in obj[:max_items]]
        more = [] if len(obj) <= max_items else [f"… +{len(obj)-max_items}"]
        return "[" + ", ".join(items + more) + "]"
    out = repr(obj)
    return out if len(out) <= 200 else out[:200] + "…"
