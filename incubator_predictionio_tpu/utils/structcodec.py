"""Shared structural value codec — one tagged encoding, two consumers.

Both durable formats in the framework encode the same structural core —
numpy/jax arrays as (dtype, shape, bytes), datetimes, tuples, sets,
non-string-keyed maps, DataMap/BiMap — under a reserved tag key:

- model checkpoints (workflow/checkpoint.py, tag ``~pio~``), which add an
  open-but-guarded dataclass tag resolved only from imported modules;
- the remote-storage wire protocol (data/storage/wire.py, tag ``~t~``),
  which adds a CLOSED table of storage record types plus Event/Interactions
  forms.

This module is the single implementation of the shared core so the two
formats cannot drift (they had already diverged once: numpy scalars
round-tripped through checkpoints but raised at the RPC boundary).
Decoding constructs only fixed structural types here; anything
type-resolving (dataclasses, records) lives in the consumers' extension
hooks with their own security posture.
"""

from __future__ import annotations

from datetime import date, datetime
from typing import Any, Callable, Optional

#: extension hook signatures — return NotImplemented to fall through
EncodeExt = Callable[[Any, "StructCodec"], Any]
DecodeExt = Callable[[str, dict, "StructCodec"], Any]


def _is_jax_array(obj: Any) -> bool:
    try:
        import jax

        return isinstance(obj, jax.Array)
    except Exception:  # pragma: no cover - jax always present
        return False


class StructCodec:
    """Structural encoder/decoder parameterized by tag key + extensions.

    ``encode_ext`` runs before the structural rules (so a consumer can
    claim its own types — e.g. PropertyMap before the DataMap rule);
    ``decode_ext`` runs for any tag the structural rules don't own.
    """

    def __init__(
        self,
        tag_key: str,
        error_cls: type = ValueError,
        encode_ext: Optional[EncodeExt] = None,
        decode_ext: Optional[DecodeExt] = None,
    ):
        self.tag = tag_key
        self.error_cls = error_cls
        self.encode_ext = encode_ext
        self.decode_ext = decode_ext

    # -- encode ------------------------------------------------------------
    def encode(self, obj: Any) -> Any:
        import numpy as np

        if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
            return obj
        if self.encode_ext is not None:
            out = self.encode_ext(obj, self)
            if out is not NotImplemented:
                return out
        tag = self.tag
        if _is_jax_array(obj):
            obj = np.asarray(obj)
        if isinstance(obj, np.ndarray):
            a = np.ascontiguousarray(obj)
            return {tag: "nd", "d": a.dtype.str, "s": list(a.shape),
                    "b": a.tobytes()}
        if isinstance(obj, np.generic):  # numpy scalar
            return {tag: "npv", "d": obj.dtype.str, "b": obj.tobytes()}
        if isinstance(obj, tuple):
            return {tag: "tu", "v": [self.encode(x) for x in obj]}
        if isinstance(obj, list):
            return [self.encode(x) for x in obj]
        if isinstance(obj, (set, frozenset)):
            return {tag: "set", "f": isinstance(obj, frozenset),
                    "v": [self.encode(x) for x in obj]}
        if isinstance(obj, datetime):
            return {tag: "dt", "v": obj.isoformat()}
        if isinstance(obj, date):  # AFTER datetime: datetime is a date
            return {tag: "date", "v": obj.isoformat()}
        if isinstance(obj, dict):
            if all(isinstance(k, str) for k in obj) and tag not in obj:
                return {k: self.encode(v) for k, v in obj.items()}
            # non-string (or reserved) keys: encode as a pair list
            return {tag: "map",
                    "v": [[self.encode(k), self.encode(v)]
                          for k, v in obj.items()]}
        from incubator_predictionio_tpu.data.bimap import BiMap

        if isinstance(obj, BiMap):
            return {tag: "bimap", "v": self.encode(dict(obj.items()))}
        from incubator_predictionio_tpu.data.datamap import DataMap

        if isinstance(obj, DataMap) and type(obj) is DataMap:
            return {tag: "dmap", "v": self.encode(obj.to_jsonable())}
        raise self.error_cls(
            f"cannot encode {type(obj).__module__}.{type(obj).__qualname__}"
        )

    # -- decode ------------------------------------------------------------
    def decode(self, obj: Any) -> Any:
        import numpy as np

        if isinstance(obj, list):
            return [self.decode(x) for x in obj]
        if not isinstance(obj, dict):
            return obj
        tag = obj.get(self.tag)
        if tag is None:
            return {k: self.decode(v) for k, v in obj.items()}
        if tag == "nd":
            arr = np.frombuffer(obj["b"], dtype=np.dtype(obj["d"]))
            return arr.reshape(obj["s"]).copy()  # writable, owned
        if tag == "npv":
            return np.frombuffer(obj["b"], dtype=np.dtype(obj["d"]))[0]
        if tag == "tu":
            return tuple(self.decode(x) for x in obj["v"])
        if tag == "set":
            vals = (self.decode(x) for x in obj["v"])
            return frozenset(vals) if obj["f"] else set(vals)
        if tag == "dt":
            return datetime.fromisoformat(obj["v"])
        if tag == "date":
            return date.fromisoformat(obj["v"])
        if tag == "map":
            return {self.decode(k): self.decode(v) for k, v in obj["v"]}
        if tag == "bimap":
            from incubator_predictionio_tpu.data.bimap import BiMap

            return BiMap(self.decode(obj["v"]))
        if tag == "dmap":
            from incubator_predictionio_tpu.data.datamap import DataMap

            return DataMap(self.decode(obj["v"]))
        if self.decode_ext is not None:
            out = self.decode_ext(tag, obj, self)
            if out is not NotImplemented:
                return out
        raise self.error_cls(f"unknown structural tag {tag!r}")
