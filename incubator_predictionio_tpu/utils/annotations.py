"""API-stability markers — @developer_api and @experimental.

The reference tags JVM classes with ``@DeveloperApi`` / ``@Experimental``
(common/.../annotation/{DeveloperApi,Experimental}.java) so users know
which surfaces are low-level or may change without deprecation. Python has
no annotation retention, so these decorators do the equivalent two things:
stamp the object (``__pio_api__``) for programmatic discovery, and prepend
the marker to the docstring so it shows in ``help()`` and rendered docs.
"""

from __future__ import annotations

from typing import Any, TypeVar

T = TypeVar("T")

DEVELOPER_API = "DeveloperApi"
EXPERIMENTAL = "Experimental"


def _mark(obj: T, kind: str, note: str) -> T:
    try:
        obj.__pio_api__ = kind  # type: ignore[attr-defined]
    except (AttributeError, TypeError):  # pragma: no cover - builtins
        pass
    doc = obj.__doc__ or ""
    try:
        obj.__doc__ = f":: {kind} ::\n{note}\n\n{doc}" if doc \
            else f":: {kind} ::\n{note}"
    except (AttributeError, TypeError):  # pragma: no cover
        pass
    return obj


def developer_api(obj: T) -> T:
    """A lower-level, unstable API intended for framework developers
    (DeveloperApi.java:25-33)."""
    return _mark(
        obj, DEVELOPER_API,
        "Intended for framework developers; may change across minor "
        "releases.")


def experimental(obj: T) -> T:
    """An experimental API that may change or be removed without
    deprecation (Experimental.java:25-33)."""
    return _mark(
        obj, EXPERIMENTAL,
        "Experimental; may change or be removed in minor releases.")


def api_stability(obj: Any) -> str:
    """The marker applied to ``obj`` (``\"DeveloperApi\"`` /
    ``\"Experimental\"``), or ``\"stable\"``."""
    return getattr(obj, "__pio_api__", "stable")
