"""Server TLS configuration + server-key authentication config.

Parity: common/.../configuration/SSLConfiguration.scala:32-70 (SSLContext
from a ``server.conf``-named keystore) and common/.../authentication/
KeyAuthentication.scala:34-72 (``ServerKey`` loaded from the same file, the
``accessKey`` query-param check for /stop,/reload).

Design delta: the JVM reference loads a JKS keystore via typesafe-config;
the Python-native equivalent is a PEM cert/key pair fed to
``ssl.SSLContext``. ``server.conf`` stays a flat ``key = value`` file (the
subset of HOCON the reference actually uses) under ``$PIO_CONF_DIR`` (or
``$PIO_HOME/conf``), with the same dotted key names re-rooted at
``pio.server.``:

    pio.server.ssl-certfile = /path/to/server.crt
    pio.server.ssl-keyfile  = /path/to/server.key
    pio.server.ssl-keyfile-pass = secret        # optional
    pio.server.key-auth-enforced = true
    pio.server.accessKey = my-server-key
"""

from __future__ import annotations

import dataclasses
import logging
import os
import ssl
from pathlib import Path
from typing import Dict, Optional

logger = logging.getLogger(__name__)


def conf_dir() -> Path:
    explicit = os.environ.get("PIO_CONF_DIR")
    if explicit:
        return Path(explicit)
    home = os.environ.get("PIO_HOME", os.path.expanduser("~/.pio_tpu"))
    return Path(home) / "conf"


def parse_server_conf(text: str) -> Dict[str, str]:
    """Flat ``key = value`` parser (the HOCON subset server.conf uses)."""
    out: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("//"):
            continue
        key, sep, value = line.partition("=")
        if not sep:
            continue
        value = value.strip()
        # inline comments: only when preceded by whitespace, so values may
        # still contain '#'/'//' (e.g. passwords, URLs)
        for marker in (" #", "\t#", " //", "\t//"):
            idx = value.find(marker)
            if idx != -1:
                value = value[:idx].rstrip()
        out[key.strip()] = value.strip().strip('"')
    return out


def load_server_conf(path: Optional[Path] = None) -> Dict[str, str]:
    path = path or (conf_dir() / "server.conf")
    if not path.exists():
        return {}
    return parse_server_conf(path.read_text())


@dataclasses.dataclass(frozen=True)
class SSLConfig:
    """The TLS material (SSLConfiguration.scala keystore fields)."""
    certfile: Optional[str] = None
    keyfile: Optional[str] = None
    password: Optional[str] = None

    @property
    def enabled(self) -> bool:
        return bool(self.certfile)

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        """Build the server SSLContext (SSLConfiguration.sslContext:53-61)."""
        if not self.enabled:
            return None
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(
            certfile=self.certfile,
            keyfile=self.keyfile,
            password=self.password,
        )
        return context


@dataclasses.dataclass(frozen=True)
class ServerKeyConfig:
    """KeyAuthentication.ServerKey (KeyAuthentication.scala:36-43)."""
    auth_enforced: bool = False
    key: Optional[str] = None

    PARAM = "accessKey"

    def check(self, provided: Optional[str]) -> bool:
        """withAccessKeyFromFile semantics: pass unless enforcement is on
        and the ``accessKey`` query param mismatches."""
        if not self.auth_enforced:
            return True
        return provided is not None and provided == self.key


def load_ssl_config(conf: Optional[Dict[str, str]] = None) -> SSLConfig:
    conf = load_server_conf() if conf is None else conf
    return SSLConfig(
        certfile=conf.get("pio.server.ssl-certfile"),
        keyfile=conf.get("pio.server.ssl-keyfile"),
        password=conf.get("pio.server.ssl-keyfile-pass"),
    )


def load_server_key(conf: Optional[Dict[str, str]] = None) -> ServerKeyConfig:
    conf = load_server_conf() if conf is None else conf
    return ServerKeyConfig(
        auth_enforced=(
            conf.get("pio.server.key-auth-enforced", "false").lower() == "true"
        ),
        key=conf.get("pio.server.accessKey"),
    )
