"""UTC time helpers.

The reference uses joda-time ``DateTime`` with a default zone of UTC
(reference: data/.../storage/Event.scala:70 ``defaultTimeZone = DateTimeZone.UTC``)
and ISO-8601 wire format for ``eventTime`` in the REST API. Here the canonical
in-memory representation is a timezone-aware ``datetime.datetime``.
"""

from __future__ import annotations

import time as _time
from datetime import datetime, timezone
from typing import Callable


def now_utc() -> datetime:
    """Current time as a timezone-aware UTC datetime."""
    return datetime.now(timezone.utc)


# ---------------------------------------------------------------------------
# Clock seam — TTL/staleness decisions route through here so tests can
# inject a fake clock instead of sleeping (speed-layer overlay TTLs, the
# serving micro-caches, /status staleness). Production code calls
# :func:`monotonic`; tests swap the source with :func:`set_monotonic`
# (restoring the previous source in a finally block) or use
# :class:`FakeClock` directly.
# ---------------------------------------------------------------------------

_monotonic_source: Callable[[], float] = _time.monotonic


def monotonic() -> float:
    """Seconds from an arbitrary epoch, never going backwards — the ONE
    clock every TTL/staleness decision reads (time.monotonic by default).
    """
    return _monotonic_source()


def set_monotonic(source: Callable[[], float]) -> Callable[[], float]:
    """Swap the monotonic source (tests inject a FakeClock); returns the
    previous source so callers can restore it in a finally block."""
    global _monotonic_source
    prev = _monotonic_source
    _monotonic_source = source
    return prev


class FakeClock:
    """Deterministic clock for TTL tests: ``advance`` instead of sleep.

    Install with ``prev = set_monotonic(clock)`` and restore with
    ``set_monotonic(prev)``; or pass the instance directly to components
    that take a ``clock=`` callable.
    """

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError("a monotonic clock cannot go backwards")
        self._now += float(seconds)


# ---------------------------------------------------------------------------
# Wall-clock seam — epoch-millisecond reads that cross process boundaries
# (event append stamps, freshness spans) route through here so tests can
# plant deterministic append times instead of sleeping. Unlike the
# monotonic seam this clock is comparable across processes: an event
# appended by the event server and served by the prediction server share
# the same epoch.
# ---------------------------------------------------------------------------

_wall_millis_source: Callable[[], int] = lambda: int(_time.time() * 1000)


def wall_millis() -> int:
    """Current wall time in epoch milliseconds — the ONE clock append
    stamps and freshness measurements read (time.time by default)."""
    return _wall_millis_source()


def set_wall_millis(source: Callable[[], int]) -> Callable[[], int]:
    """Swap the wall-millis source (tests plant append times); returns
    the previous source so callers can restore it in a finally block."""
    global _wall_millis_source
    prev = _wall_millis_source
    _wall_millis_source = source
    return prev


def ensure_aware(dt: datetime) -> datetime:
    """Interpret naive datetimes as UTC (the reference's default zone)."""
    if dt.tzinfo is None:
        return dt.replace(tzinfo=timezone.utc)
    return dt


def parse_iso8601(s: str) -> datetime:
    """Parse an ISO-8601 timestamp, accepting the trailing-``Z`` form.

    joda's ISO8601 parser (used by the reference event API) accepts
    ``2004-12-13T21:39:45.618-07:00`` and ``...Z`` forms; ``fromisoformat``
    in Python >= 3.11 covers both once ``Z`` is normalized.
    """
    if not isinstance(s, str):
        raise ValueError(f"Cannot convert {s!r} to a datetime.")
    dt = datetime.fromisoformat(s.replace("Z", "+00:00"))
    return ensure_aware(dt)


def format_iso8601(dt: datetime) -> str:
    """Format with milliseconds, matching the reference's wire format."""
    dt = ensure_aware(dt)
    return dt.isoformat(timespec="milliseconds")


def to_millis(dt: datetime) -> int:
    """Epoch milliseconds (joda ``DateTime.getMillis`` equivalent)."""
    return int(ensure_aware(dt).timestamp() * 1000)


def from_millis(ms: int) -> datetime:
    return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
