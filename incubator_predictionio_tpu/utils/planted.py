"""Seeded planted-factor catalogue generator — the shared fixture of the
two-stage MIPS serving path (tests AND bench legs import it).

ML-20M tops out at ~27k items, far too small to measure an
approximate-MIPS win; real embedding catalogues are 10-100× larger. This
module PLANTS a factor table with the geometry trained factor tables
actually have — cluster structure (genres/categories), bounded relative
within-cluster noise, and a log-normal popularity (norm) profile — at
any item count, so the candidate-stage recall and the exhaustive-vs-
two-stage device walls are measurable without new data.

The geometry matters: an isotropic-noise table (per-dim noise comparable
to the cluster radius) is ~75% noise at rank 64 and NO index structure
can beat a linear scan on it — which is a statement about the fixture,
not about serving. Here ``noise`` is the RELATIVE within-cluster radius
(noise vector norm over center norm), matching the spectral decay of
trained MF factors, and the recall gate (tests/test_mips.py,
``bench_mips``) is honest because the exhaustive oracle runs on the
same table.

Everything is a pure function of the seed — the determinism tests and
the bench compare runs byte-for-byte.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def planted_item_factors(
    n_items: int,
    rank: int,
    seed: int = 0,
    n_genres: int = 64,
    noise: float = 0.6,
    pop_sigma: float = 0.35,
) -> np.ndarray:
    """[n_items, rank] f32 planted item factor table.

    item = (unit genre center + relative-noise) × log-normal popularity.
    ``noise`` is the within-cluster radius relative to the unit center
    (per-dim sigma = noise/sqrt(rank)); ``pop_sigma`` the log-normal
    sigma of the row norms (the MIPS-relevant norm spread — top-k by
    inner product is popularity-weighted, so the coarse stage must
    survive it)."""
    rng = np.random.default_rng(seed)
    genres = rng.normal(0.0, 1.0, (n_genres, rank))
    genres /= np.maximum(
        np.linalg.norm(genres, axis=1, keepdims=True), 1e-9)
    which = rng.integers(0, n_genres, n_items)
    v = genres[which] + rng.normal(
        0.0, noise / np.sqrt(rank), (n_items, rank))
    v *= rng.lognormal(0.0, pop_sigma, n_items)[:, None]
    return np.ascontiguousarray(v, dtype=np.float32)


def planted_queries(
    item_factors: np.ndarray,
    n_queries: int,
    seed: int = 1,
    mix: int = 3,
) -> np.ndarray:
    """[n_queries, rank] f32 user-like query vectors: each the mean of
    ``mix`` random item rows — the blended-interest shape ALS user
    vectors converge to, and the harder case for a bucketed coarse
    stage than single-item queries."""
    rng = np.random.default_rng(seed)
    picks = rng.integers(0, item_factors.shape[0], (n_queries, mix))
    return np.ascontiguousarray(
        item_factors[picks].mean(axis=1), dtype=np.float32)


def exhaustive_top_k(
    item_factors: np.ndarray,
    queries: np.ndarray,
    k: int,
) -> np.ndarray:
    """[n_queries, k] exact oracle ids (descending score) — the recall
    gate's ground truth, computed on the host so it cannot share a bug
    with the device path under test."""
    scores = queries @ item_factors.T
    part = np.argpartition(scores, -k, axis=1)[:, -k:]
    ps = np.take_along_axis(scores, part, axis=1)
    order = np.argsort(-ps, axis=1, kind="stable")
    return np.take_along_axis(part, order, axis=1)


def recall_against_oracle(
    approx_ids: np.ndarray,   # [Q, >=k] approximate ids (any order)
    oracle_ids: np.ndarray,   # [Q, k] exact ids
    k: int,
) -> Tuple[float, float]:
    """(mean recall@k, min per-query recall@k)."""
    recalls = []
    for row in range(oracle_ids.shape[0]):
        got = set(int(i) for i in approx_ids[row] if i >= 0)
        want = set(int(i) for i in oracle_ids[row][:k])
        recalls.append(len(got & want) / max(len(want), 1))
    return float(np.mean(recalls)), float(np.min(recalls))
