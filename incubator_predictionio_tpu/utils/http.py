"""Asyncio HTTP/1.1 micro-framework — the spray/akka replacement.

The reference runs four spray-can servers (EventServer :7070, PredictionServer
:8000, Dashboard :9000, AdminAPI :7071) on akka actors. Here one small
dependency-free asyncio server underlies all of them: routed handlers, JSON
helpers, keep-alive, and a thread-pool bridge for the synchronous storage
DAOs (the moral equivalent of the reference's ``Future { ... }`` blocks
around blocking storage calls, e.g. EventServer.scala:97).

Deliberately minimal: Content-Length bodies (no chunked uploads), HTTP/1.1
keep-alive. TLS termination is available by passing an ``ssl_context``
(built from server.conf by utils/ssl_config.py — the reference's
SSLConfiguration keystore equivalent); otherwise run behind a terminating
proxy.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import errno
import inspect
import json
import logging
import random
import re
import socket
import ssl
import threading
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, unquote, urlsplit

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace

logger = logging.getLogger(__name__)

#: request telemetry every server shares (docs/observability.md). The
#: route label is the ROUTE PATTERN (bounded set), never the raw path —
#: `/events/{event_id}.json` stays one series no matter how many ids
#: pass through it; unrouted paths collapse into one `<unmatched>`.
_HTTP_REQUESTS = obs_metrics.REGISTRY.counter(
    "pio_http_requests_total",
    "HTTP requests served, by server/method/route pattern/status",
    labels=("server", "method", "route", "status"))
_HTTP_LATENCY = obs_metrics.REGISTRY.histogram(
    "pio_http_request_seconds",
    "HTTP request wall (dispatch to response), by server/route pattern",
    labels=("server", "route"))
_UNMATCHED_ROUTE = "<unmatched>"

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024

STATUS_TEXT = {
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    301: "Moved Permanently", 302: "Found", 400: "Bad Request",
    401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    500: "Internal Server Error", 502: "Bad Gateway",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class HttpError(Exception):
    """Raise from a handler to produce a JSON error response.

    ``headers`` (an attribute, default empty) ride the error response —
    the scheduler's 503 shed carries its ``Retry-After`` contract this
    way (serving/scheduler.py ShedError)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message
        # per-instance, never a class-level dict: an in-place mutation
        # must not leak the header onto every other error response
        self.headers: Dict[str, str] = {}


class RetryableError(Exception):
    """Wraps a failure that is safe to retry under a :class:`RetryPolicy`.

    The CALLER decides retryability (it knows whether the request body
    ever reached the wire, whether the verb is idempotent, whether a 503
    shed said come back later) and wraps only those failures; everything
    else propagates immediately. ``retry_after_s`` carries a
    server-directed minimum delay (the ``Retry-After`` contract the
    scheduler's shed responses ride)."""

    def __init__(self, cause: BaseException,
                 retry_after_s: Optional[float] = None):
        super().__init__(str(cause))
        self.cause = cause
        self.retry_after_s = retry_after_s


def parse_retry_after(value: Optional[str]) -> Optional[float]:
    """``Retry-After`` header → seconds (delta-seconds form only; the
    HTTP-date form is ignored — nothing in this repo emits it)."""
    if not value:
        return None
    try:
        return max(float(value.strip()), 0.0)
    except ValueError:
        return None


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """THE one copy of HTTP-client retry choreography: jittered
    exponential backoff under an overall deadline, honoring a
    server-directed ``Retry-After``, idempotent-only by default.

    Before this existed every client grew its own loop (the remote
    storage RPC channel, the GCS driver, the prediction server's
    feedback POSTs) and they drifted — fixed delays, no deadline, no
    Retry-After. The ``unbounded-retry`` pio-lint rule now flags new
    ad-hoc loops outside this module; adopters call :meth:`call` with a
    closure that wraps retry-SAFE failures in :class:`RetryableError`
    (see data/storage/remote.py for the sent/idempotent discipline).
    """

    #: total tries (1 = no retry)
    attempts: int = 3
    base_delay_s: float = 0.2
    multiplier: float = 2.0
    max_delay_s: float = 5.0
    #: overall budget across every attempt AND backoff sleep — a retry
    #: that cannot finish before the deadline is not attempted
    deadline_s: float = 30.0
    #: fraction of each delay randomized away (decorrelates a thundering
    #: herd of clients retrying the same outage in lockstep)
    jitter_frac: float = 0.5

    def backoff_s(self, attempt: int,
                  retry_after_s: Optional[float] = None,
                  rand: Callable[[], float] = random.random) -> float:
        """Delay before retry number ``attempt+1`` (attempt is 0-based).
        A server-directed ``Retry-After`` sets the floor — backing off
        LESS than the server asked would re-offer load it just shed."""
        delay = min(self.base_delay_s * (self.multiplier ** attempt),
                    self.max_delay_s)
        delay *= 1.0 - self.jitter_frac * rand()
        if retry_after_s is not None:
            delay = max(delay, retry_after_s)
        return delay

    def call(self, fn: Callable[[], Any], *, idempotent: bool = True,
             clock: Callable[[], float] = time.monotonic,
             sleep: Callable[[float], None] = time.sleep) -> Any:
        """Run ``fn()`` under this policy.

        ``fn`` raises :class:`RetryableError` around failures it judged
        safe to re-send; any other exception propagates unretried. With
        ``idempotent=False`` nothing retries (the wrap is ignored) —
        the policy is idempotent-only by default, because a lost
        RESPONSE never proves the request was not applied. On
        exhaustion the ORIGINAL cause is re-raised, so callers keep
        their typed errors."""
        deadline = clock() + self.deadline_s
        attempt = 0
        while True:
            try:
                return fn()
            except RetryableError as e:
                delay = self.backoff_s(attempt, e.retry_after_s)
                attempt += 1
                if (not idempotent or attempt >= self.attempts
                        or clock() + delay > deadline):
                    raise e.cause
                sleep(delay)


class Request:
    def __init__(
        self,
        method: str,
        path: str,
        query: Dict[str, str],
        headers: Dict[str, str],
        body: bytes,
        path_params: Optional[Dict[str, str]] = None,
    ):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.path_params = path_params or {}

    def json(self) -> Any:
        if not self.body:
            raise ValueError("Empty request body")
        try:
            return json.loads(self.body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"Invalid JSON body: {e}") from e

    def form(self) -> Dict[str, str]:
        return dict(parse_qsl(self.body.decode("utf-8", "replace")))


class Response:
    def __init__(
        self,
        status: int = 200,
        json_body: Any = None,
        body: Optional[bytes] = None,
        content_type: str = "application/json; charset=UTF-8",
        headers: Optional[Dict[str, str]] = None,
    ):
        self.status = status
        if json_body is not None:
            body = json.dumps(json_body).encode("utf-8")
        self.body = body or b""
        self.content_type = content_type
        self.headers = headers or {}

    def encode(self, keep_alive: bool) -> bytes:
        reason = STATUS_TEXT.get(self.status, "Unknown")
        lines = [
            f"HTTP/1.1 {self.status} {reason}",
            f"Content-Type: {self.content_type}",
            f"Content-Length: {len(self.body)}",
            "Connection: " + ("keep-alive" if keep_alive else "close"),
            "Server: pio-tpu",
        ]
        for k, v in self.headers.items():
            lines.append(f"{k}: {v}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + self.body


Handler = Callable[[Request], "Response | Awaitable[Response]"]


#: headers a CORS-enabled router grants on OPTIONS preflight
#: (CorsSupport.scala:34-45 — AllOrigins + the standard request headers)
CORS_ALLOW_HEADERS = (
    "Origin, X-Requested-With, Content-Type, Accept, Accept-Encoding, "
    "Accept-Language, Host, Referer, User-Agent"
)


class Router:
    """Method + path routing with ``{param}`` segments and a catch-all
    ``{tail...}`` form. ``cors=True`` adds ``Access-Control-Allow-Origin: *``
    to every response and answers OPTIONS preflights with the allowed
    methods (the dashboard's CorsSupport trait,
    tools/.../dashboard/CorsSupport.scala:30-66)."""

    def __init__(self, cors: bool = False) -> None:
        self._routes: List[Tuple[str, re.Pattern, Handler, str]] = []
        self.cors = cors

    def allowed_methods(self, path: str) -> List[str]:
        return sorted({
            m for m, pattern, _h, _p in self._routes if pattern.match(path)
        })

    _PARAM_RE = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)(\.\.\.)?\}")

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        regex = ["^"]
        for part in pattern.split("/"):
            if not part:
                continue
            regex.append("/")
            # a segment may embed params: "{event_id}.json", "{name}.form"
            pos = 0
            for m in self._PARAM_RE.finditer(part):
                regex.append(re.escape(part[pos:m.start()]))
                if m.group(2):  # {tail...} catch-all
                    regex.append(f"(?P<{m.group(1)}>.*)")
                else:
                    regex.append(f"(?P<{m.group(1)}>[^/]+?)")
                pos = m.end()
            regex.append(re.escape(part[pos:]))
        if pattern.endswith("/") or pattern == "/":
            regex.append("/?")
        regex.append("$")
        self._routes.append(
            (method.upper(), re.compile("".join(regex)), handler, pattern))

    def get(self, pattern: str):
        return lambda h: (self.add("GET", pattern, h), h)[1]

    def post(self, pattern: str):
        return lambda h: (self.add("POST", pattern, h), h)[1]

    def delete(self, pattern: str):
        return lambda h: (self.add("DELETE", pattern, h), h)[1]

    def resolve(
        self, method: str, path: str
    ) -> Tuple[Optional[Handler], Dict[str, str], bool, Optional[str]]:
        """(handler, params, path_exists, route_pattern). The pattern
        comes back even on a method mismatch, so 405s and CORS
        preflights book under the real route label — `<unmatched>` is
        reserved for paths no route knows at all."""
        path_matched = False
        matched_route: Optional[str] = None
        for m, pattern, handler, route in self._routes:
            match = pattern.match(path)
            if match:
                path_matched = True
                if matched_route is None:
                    matched_route = route
                if m == method:
                    return handler, {
                        k: unquote(v) for k, v in match.groupdict().items()
                    }, True, route
        return None, {}, path_matched, matched_route


class ClientConnectionPool:
    """Thread-local keep-alive HTTP(S) connections to one host.

    The single copy of client connection lifecycle shared by the
    remote-storage RPC channel (data/storage/remote.py) and the GCS
    driver (data/storage/gcs.py) — retry choreography layers on top via
    :class:`RetryPolicy` (the callers still own retryABILITY: only they
    know whether a given failure left the request unsent).
    ``get()`` returns this thread's connection (created on first
    use; ``http.client`` transparently reconnects a closed one on the
    next request), ``drop()`` discards this thread's connection so the
    next ``get()`` builds a fresh object, ``close_all()`` closes every
    connection the pool ever handed out."""

    def __init__(self, host: str, port: int, timeout: float,
                 tls: bool = False):
        import http.client as _hc

        self._cls = _hc.HTTPSConnection if tls else _hc.HTTPConnection
        self.host = host
        self.port = port
        self.timeout = timeout
        self._local = threading.local()
        self._lock = threading.Lock()
        self._conns: list = []

    def get(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._cls(self.host, self.port, timeout=self.timeout)
            self._local.conn = conn
            with self._lock:
                self._conns.append(conn)
        return conn

    def drop(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def close_all(self) -> None:
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()
        self._local = threading.local()


class HttpServer:
    """One listening socket + a router. Synchronous handlers and the
    ``sync()`` helper run on the default thread pool so blocking DAO work
    never stalls the event loop."""

    def __init__(self, router: Router, host: str = "0.0.0.0", port: int = 0,
                 ssl_context: Optional["ssl.SSLContext"] = None,
                 bind_retries: int = 0, bind_retry_delay: float = 1.0,
                 name: str = "http"):
        self.router = router
        self.host = host
        # written once by the loop thread (the bound port) before the
        # `_started` Event publishes it to waiters; verified by
        # pio-lint's unguarded-shared-state pass (docs/lint.md)
        self.port = port  # pio-lint: publish-only
        #: `server` label on the shared request metrics + span logs
        self.name = name
        self.ssl_context = ssl_context
        #: extra bind attempts after a failed bind (occupied port), each
        #: after ``bind_retry_delay`` seconds — MasterActor retries 3×/1 s
        #: (CreateServer.scala:371-381)
        self.bind_retries = bind_retries
        self.bind_retry_delay = bind_retry_delay
        # single-writer (the loop thread), `_started`-Event-sequenced
        self._server: Optional[asyncio.AbstractServer] = None  # pio-lint: publish-only
        self._loop: Optional[asyncio.AbstractEventLoop] = None  # pio-lint: publish-only
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    @classmethod
    def from_conf(cls, router: Router, host: str = "0.0.0.0",
                  port: int = 0, bind_retries: int = 0,
                  name: str = "http") -> "HttpServer":
        """Server with TLS material from server.conf when configured
        (the reference mixes SSLConfiguration into every server)."""
        from incubator_predictionio_tpu.utils.ssl_config import load_ssl_config

        return cls(router, host, port,
                   ssl_context=load_ssl_config().ssl_context(),
                   bind_retries=bind_retries, name=name)

    # -- request cycle -----------------------------------------------------
    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                except asyncio.LimitOverrunError:
                    writer.write(Response(413, {"message": "headers too large"})
                                 .encode(False))
                    await writer.drain()
                    return
                if len(head) > MAX_HEADER_BYTES:
                    writer.write(Response(413, {"message": "headers too large"})
                                 .encode(False))
                    await writer.drain()
                    return
                request, keep_alive = await self._read_request(reader, head)
                if request is None:
                    writer.write(Response(400, {"message": "bad request"})
                                 .encode(False))
                    await writer.drain()
                    return
                response = await self._dispatch(request)
                writer.write(response.encode(keep_alive))
                await writer.drain()
                if not keep_alive:
                    return
        except Exception:
            logger.exception("connection handler error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(
        self, reader: asyncio.StreamReader, head: bytes
    ) -> Tuple[Optional[Request], bool]:
        try:
            text = head.decode("latin-1")
            lines = text.split("\r\n")
            method, target, _version = lines[0].split(" ", 2)
            headers: Dict[str, str] = {}
            for line in lines[1:]:
                if not line:
                    continue
                name, _, value = line.partition(":")
                headers[name.strip().lower()] = value.strip()
            length = int(headers.get("content-length", "0") or "0")
            if length < 0 or length > MAX_BODY_BYTES:
                return None, False
            body = await reader.readexactly(length) if length else b""
            parts = urlsplit(target)
            query = dict(parse_qsl(parts.query, keep_blank_values=True))
            keep_alive = headers.get("connection", "keep-alive").lower() != "close"
            return (
                Request(method.upper(), parts.path or "/", query, headers, body),
                keep_alive,
            )
        except (ValueError, asyncio.IncompleteReadError):
            return None, False

    async def _dispatch(self, request: Request) -> Response:
        """Route + run the handler, wrapped in the shared request
        telemetry (docs/observability.md): trace-ID stamping, the
        per-route counter + latency histogram, and the JSON span log.
        All of it is host-side bookkeeping on the event loop — one
        counter add, one histogram add, one header — never a device
        touch."""
        t0 = time.perf_counter()
        trace_id = obs_trace.accept_trace_id(
            request.headers.get("x-pio-trace-id"))
        # cross-process parenting: an in-repo client hop stamps its own
        # span ID in X-PIO-Parent-Span (obs_trace.client_headers), so
        # this request's span line links under the upstream span
        parent_span = obs_trace.accept_parent_span(
            request.headers.get("x-pio-parent-span"))
        span_id = obs_trace.new_span_id()
        token = obs_trace.set_current(trace_id)
        span_token = obs_trace.set_current_span(span_id)
        try:
            response, route = await self._dispatch_routed(request)
        finally:
            obs_trace.reset_current_span(span_token)
            obs_trace.reset_current(token)
        dt = time.perf_counter() - t0
        route_label = route or _UNMATCHED_ROUTE
        _HTTP_REQUESTS.labels(
            server=self.name, method=request.method, route=route_label,
            status=str(response.status)).inc()
        _HTTP_LATENCY.labels(server=self.name, route=route_label).observe(dt)
        # the propagation contract is unconditional and status-blind:
        # error responses (4xx/5xx) echo the trace ID and emit their
        # span line exactly like the happy path — a failing hop is the
        # one an operator most needs to find in the tree
        response.headers.setdefault(obs_trace.TRACE_HEADER, trace_id)
        response.headers.setdefault(obs_trace.SPAN_HEADER, span_id)
        # span sampling (PIO_TRACE_SAMPLE): the JSON line is the one
        # per-request cost that scales with QPS; sampled-out requests
        # still got their trace ID stamped and echoed above
        if obs_trace.span_sampled():
            obs_trace.log_span(self.name, request.method, route_label,
                               response.status, dt, trace_id,
                               span_id=span_id,
                               parent_span_id=parent_span)
        return response

    async def _dispatch_routed(
        self, request: Request
    ) -> Tuple[Response, Optional[str]]:
        """(response, matched route pattern or None)."""
        handler, params, path_exists, route = self.router.resolve(
            request.method, request.path
        )
        if handler is None:
            if self.router.cors and path_exists \
                    and request.method == "OPTIONS":
                # CORS preflight for a resource that answers other methods
                # (CorsSupport.scala:49-62)
                methods = self.router.allowed_methods(request.path)
                return self._with_cors(Response(200, headers={
                    "Access-Control-Allow-Methods":
                        ", ".join(["OPTIONS"] + methods),
                    "Access-Control-Allow-Headers": CORS_ALLOW_HEADERS,
                    "Access-Control-Max-Age": "1728000",
                })), route
            if path_exists:
                return self._with_cors(
                    Response(405, {"message": "Method Not Allowed"})), route
            return self._with_cors(
                Response(404, {"message": "Not Found"})), route
        request.path_params = params
        try:
            if inspect.iscoroutinefunction(handler):
                result = await handler(request)
            else:
                loop = asyncio.get_running_loop()
                # copy_context: run_in_executor does not propagate
                # contextvars by itself, and sync handlers must see the
                # ambient trace ID (obs_trace.current_trace_id)
                ctx = contextvars.copy_context()
                result = await loop.run_in_executor(
                    None, ctx.run, handler, request)
                if inspect.isawaitable(result):
                    result = await result
            return self._with_cors(result), route
        except HttpError as e:
            return self._with_cors(
                Response(e.status, {"message": e.message},
                         headers=dict(e.headers))), route
        except Exception as e:
            logger.exception("handler error for %s %s", request.method,
                             request.path)
            return self._with_cors(
                Response(500, {"message": str(e)})), route

    def _with_cors(self, response: Response) -> Response:
        if self.router.cors:
            response.headers.setdefault("Access-Control-Allow-Origin", "*")
        return response

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        attempt = self.bind_retries
        while True:
            try:
                self._server = await asyncio.start_server(
                    self._handle_conn, self.host, self.port,
                    limit=MAX_HEADER_BYTES, ssl=self.ssl_context,
                )
                break
            except OSError as e:
                # only an occupied port is transient; EACCES, gaierror
                # etc. can never clear, so fail fast on those
                if attempt <= 0 or e.errno != errno.EADDRINUSE:
                    raise
                attempt -= 1
                logger.error(
                    "Bind to %s:%d failed (%s). Retrying... "
                    "(%d more trial(s))", self.host, self.port, e, attempt + 1)
                await asyncio.sleep(self.bind_retry_delay)
        self.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        logger.info("http%s server listening on %s:%d",
                    "s" if self.ssl_context else "", self.host, self.port)

    async def serve_forever(
        self, on_started: Optional[Callable[[int], None]] = None
    ) -> None:
        """Bind, then serve until cancelled. ``on_started`` (if given)
        runs once with the KERNEL-assigned port after the bind — the
        ephemeral-bind (`port=0`) announcement hook: a parent that
        pre-picks a "free" port instead is racing every other process
        on the box for it."""
        await self.start()
        assert self._server is not None
        if on_started is not None:
            on_started(self.port)
        async with self._server:
            await self._server.serve_forever()

    def wait_started(self, timeout: Optional[float] = None) -> bool:
        """True once the server has bound (or False on timeout / when the
        startup errored — callers gating work on a live listener should
        treat False as "not serving")."""
        if not self._started.wait(timeout):
            return False
        return getattr(self, "_start_error", None) is None

    def start_background(self) -> int:
        """Run the server on a daemon thread; returns the bound port."""
        # loop-thread writes sequenced by the `_started` Event
        self._start_error: Optional[BaseException] = None  # pio-lint: publish-only

        def _run() -> None:
            try:
                asyncio.run(self.serve_forever())
            except asyncio.CancelledError:
                pass  # normal stop() path
            except BaseException as e:
                if self._started.is_set():
                    # post-startup crash: the waiter is long gone — make
                    # the dead listener loud instead of vanishing silently
                    logger.exception("http server died after startup")
                self._start_error = e
                self._started.set()  # unblock the waiter; error checked there

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        timeout = 10 + self.bind_retries * self.bind_retry_delay
        if not self._started.wait(timeout):
            raise RuntimeError("http server failed to start")
        if self._start_error is not None:
            raise RuntimeError(
                f"http server failed to start: {self._start_error}")
        return self.port

    def stop(self) -> None:
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            try:
                loop.call_soon_threadsafe(server.close)
            except RuntimeError:
                pass  # loop already closed (server stopped itself)


async def sync(fn: Callable[..., Any], *args: Any) -> Any:
    """Run a blocking callable on the thread pool (spray's detach())."""
    return await asyncio.get_running_loop().run_in_executor(None, fn, *args)
