"""Shared utilities: time handling, the canonical JSON codec, logging."""

from incubator_predictionio_tpu.utils.times import (
    now_utc,
    parse_iso8601,
    format_iso8601,
    to_millis,
    from_millis,
)
from incubator_predictionio_tpu.utils.json_codec import extract, to_jsonable

__all__ = [
    "now_utc",
    "parse_iso8601",
    "format_iso8601",
    "to_millis",
    "from_millis",
    "extract",
    "to_jsonable",
]
