"""e2 — standalone engine-building library (reference: e2/ module).

Parity: CategoricalNaiveBayes (e2/.../engine/CategoricalNaiveBayes.scala),
MarkovChain (e2/.../engine/MarkovChain.scala), BinaryVectorizer
(e2/.../engine/BinaryVectorizer.scala), CrossValidation
(e2/.../evaluation/CrossValidation.scala).
"""

from incubator_predictionio_tpu.e2.engine import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    CategoricalNaiveBayesModel,
    LabeledPoint,
    MarkovChain,
    MarkovChainModel,
)
from incubator_predictionio_tpu.e2.evaluation import split_data

__all__ = [
    "BinaryVectorizer", "CategoricalNaiveBayes", "CategoricalNaiveBayesModel",
    "LabeledPoint", "MarkovChain", "MarkovChainModel", "split_data",
]
