"""e2 engine-building blocks.

- :class:`CategoricalNaiveBayes` — NB over string-feature LabeledPoints
  (e2/.../engine/CategoricalNaiveBayes.scala:30-160: ``train`` → model with
  ``log_score`` (optional default for unseen feature values) and ``predict``).
- :class:`MarkovChain` — top-N transition model on a sparse count matrix
  (e2/.../engine/MarkovChain.scala:33-90).
- :class:`BinaryVectorizer` — (field, value) pairs → binary feature vectors
  (e2/.../engine/BinaryVectorizer.scala:37-60) feeding the jax classifiers.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """e2 LabeledPoint: a string label + string feature values."""

    label: str
    features: Tuple[str, ...]

    def __post_init__(self) -> None:
        if isinstance(self.features, list):
            object.__setattr__(self, "features", tuple(self.features))


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """priors: label → log P(label); likelihoods: label → per-position
    {value → log P(value | label, position)} (CategoricalNaiveBayes.scala:88)."""

    priors: Dict[str, float]
    likelihoods: Dict[str, List[Dict[str, float]]]

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda _: float("-inf"),
    ) -> Optional[float]:
        """Joint log-score of a point under its label
        (CategoricalNaiveBayes.scala logScore:102-138). Unseen feature values
        go through ``default_likelihood`` (given the position's seen
        log-likelihoods); the default −inf matches the reference."""
        if point.label not in self.priors:
            return None
        like = self.likelihoods[point.label]
        if len(point.features) != len(like):
            raise ValueError(
                f"point has {len(point.features)} features, model expects {len(like)}"
            )
        score = self.priors[point.label]
        for position, value in enumerate(point.features):
            table = like[position]
            if value in table:
                score += table[value]
            else:
                score += default_likelihood(list(table.values()))
        return score

    def predict(self, features: Sequence[str]) -> str:
        """Most-likely label (CategoricalNaiveBayes.scala predict:141-158).

        When every label scores −inf (all feature values unseen), the first
        label still wins — the reference sorts and takes the head."""
        scored = [
            (label, self.log_score(LabeledPoint(label, tuple(features))))
            for label in self.priors
        ]
        return max(scored, key=lambda t: t[1])[0]


class CategoricalNaiveBayes:
    @staticmethod
    def train(points: Iterable[LabeledPoint]) -> CategoricalNaiveBayesModel:
        """CategoricalNaiveBayes.train:30-86."""
        points = list(points)
        if not points:
            raise ValueError("No training points")
        n_features = len(points[0].features)
        label_counts: Dict[str, int] = {}
        value_counts: Dict[str, List[Dict[str, int]]] = {}
        for p in points:
            if len(p.features) != n_features:
                raise ValueError("Inconsistent feature arity")
            label_counts[p.label] = label_counts.get(p.label, 0) + 1
            tables = value_counts.setdefault(
                p.label, [dict() for _ in range(n_features)]
            )
            for position, value in enumerate(p.features):
                tables[position][value] = tables[position].get(value, 0) + 1
        total = len(points)
        priors = {
            label: math.log(count / total)
            for label, count in label_counts.items()
        }
        likelihoods = {
            label: [
                {v: math.log(c / label_counts[label]) for v, c in table.items()}
                for table in tables
            ]
            for label, tables in value_counts.items()
        }
        return CategoricalNaiveBayesModel(priors, likelihoods)


@dataclasses.dataclass
class MarkovChainModel:
    """Per-state top-N transitions (MarkovChain.scala MarkovChainModel:60-90)."""

    transitions: Dict[int, List[Tuple[int, float]]]
    n: int

    def predict(self, current_states: Sequence[int]) -> List[int]:
        """Most probable next state for each current state
        (MarkovChain.scala predict:71)."""
        out = []
        for s in current_states:
            candidates = self.transitions.get(s, [])
            out.append(candidates[0][0] if candidates else -1)
        return out

    def top_n(self, state: int) -> List[Tuple[int, float]]:
        return self.transitions.get(state, [])


class MarkovChain:
    @staticmethod
    def train(
        rows: Sequence[int],
        cols: Sequence[int],
        counts: Sequence[float],
        top_n: int,
    ) -> MarkovChainModel:
        """Row-normalize a sparse transition-count matrix and keep the top-N
        next states per state (MarkovChain.train:33-58)."""
        sums: Dict[int, float] = {}
        for r, c in zip(rows, counts):
            sums[int(r)] = sums.get(int(r), 0.0) + float(c)
        per_state: Dict[int, List[Tuple[int, float]]] = {}
        for r, c, n in zip(rows, cols, counts):
            r = int(r)
            per_state.setdefault(r, []).append((int(c), float(n) / sums[r]))
        transitions = {
            r: sorted(lst, key=lambda t: -t[1])[:top_n]
            for r, lst in per_state.items()
        }
        return MarkovChainModel(transitions, top_n)


class BinaryVectorizer:
    """(field, value) → one-hot index map (BinaryVectorizer.scala:37-60)."""

    def __init__(self, index: Dict[Tuple[str, str], int]):
        self.index = dict(index)
        self.n = len(self.index)

    @classmethod
    def fit(cls, pairs: Iterable[Tuple[str, str]]) -> "BinaryVectorizer":
        distinct = dict.fromkeys(tuple(p) for p in pairs)
        return cls({p: i for i, p in enumerate(distinct)})

    def transform(self, properties: Dict[str, str]) -> np.ndarray:
        """BinaryVectorizer.toBinary: set 1.0 at each known (field, value)."""
        out = np.zeros(self.n, np.float32)
        for field, value in properties.items():
            idx = self.index.get((field, str(value)))
            if idx is not None:
                out[idx] = 1.0
        return out

    def transform_batch(
        self, rows: Sequence[Dict[str, str]]
    ) -> np.ndarray:
        return np.stack([self.transform(r) for r in rows]) if rows else \
            np.zeros((0, self.n), np.float32)
