"""k-fold cross-validation splitter.

Parity: e2/.../evaluation/CrossValidation.scala:36-60 — splits data into k
folds, yielding (training set, eval info, (query, actual) pairs) tuples in
the shape ``DataSource.read_eval`` expects.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    k: int,
    data: Sequence[D],
    make_qa: Callable[[D], Tuple[Q, A]],
) -> List[Tuple[List[D], int, List[Tuple[Q, A]]]]:
    """Returns k tuples (train_fold, fold_index, [(query, actual)]).

    Fold membership is ``index % k`` (the reference uses zipWithIndex % k,
    CrossValidation.scala:44) so splits are deterministic.
    """
    if k <= 1:
        raise ValueError("k must be >= 2")
    out = []
    for fold in range(k):
        train = [d for i, d in enumerate(data) if i % k != fold]
        test = [d for i, d in enumerate(data) if i % k == fold]
        out.append((train, fold, [make_qa(d) for d in test]))
    return out
