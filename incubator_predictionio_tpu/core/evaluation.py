"""Evaluation DSL + MetricEvaluator.

Parity: controller/{Evaluation,Deployment}.scala and MetricEvaluator.scala.
An ``Evaluation`` binds an engine with a metric set; ``MetricEvaluator``
scores every candidate ``EngineParams``, tracks the best by the primary
metric's ordering, and writes ``best.json`` (MetricEvaluator.saveEngineJson:
193). HTML/one-liner renderings feed the dashboard like the reference's
Twirl template output.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, List, Optional, Sequence, Tuple

from incubator_predictionio_tpu.core.base import Evaluator
from incubator_predictionio_tpu.core.metrics import Metric, ZeroMetric
from incubator_predictionio_tpu.core.params import EngineParams
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.utils import json_codec

logger = logging.getLogger(__name__)


class Deployment:
    """controller/Deployment.scala:29-56 — holds the engine singleton."""

    def __init__(self) -> None:
        self._engine: Any = None

    @property
    def engine(self) -> Any:
        if self._engine is None:
            raise RuntimeError("Engine not assigned")
        return self._engine

    @engine.setter
    def engine(self, value: Any) -> None:
        if self._engine is not None:
            raise RuntimeError("Engine can be assigned only once")
        self._engine = value


class Evaluation(Deployment):
    """controller/Evaluation.scala:34-125.

    Assign either ``engine_metric = (engine, metric)`` or
    ``engine_metrics = (engine, primary_metric, [other_metrics])`` or a fully
    custom ``engine_evaluator = (engine, evaluator)``.
    """

    def __init__(self) -> None:
        super().__init__()
        self._evaluator: Optional[Evaluator] = None

    @property
    def evaluator(self) -> Evaluator:
        if self._evaluator is None:
            raise RuntimeError(
                "Evaluator not assigned — set engine_metric/engine_metrics first"
            )
        return self._evaluator

    @property
    def engine_evaluator(self) -> Tuple[Any, Evaluator]:
        return (self.engine, self.evaluator)

    @engine_evaluator.setter
    def engine_evaluator(self, value: Tuple[Any, Evaluator]) -> None:
        self.engine, self._evaluator = value[0], value[1]

    @property
    def engine_metric(self) -> Tuple[Any, Metric]:
        raise NotImplementedError("write-only (Evaluation.scala:98)")

    @engine_metric.setter
    def engine_metric(self, value: Tuple[Any, Metric]) -> None:
        self.engine, self._evaluator = value[0], MetricEvaluator(value[1])

    @property
    def engine_metrics(self) -> Tuple[Any, Metric, List[Metric]]:
        raise NotImplementedError("write-only (Evaluation.scala:110)")

    @engine_metrics.setter
    def engine_metrics(self, value: Tuple[Any, Metric, List[Metric]]) -> None:
        self.engine, self._evaluator = (
            value[0],
            MetricEvaluator(value[1], list(value[2])),
        )


@dataclasses.dataclass
class MetricScores:
    """MetricEvaluator.scala:48 — primary + other scores for one candidate."""

    score: Any
    other_scores: List[Any]


@dataclasses.dataclass
class MetricEvaluatorResult:
    """MetricEvaluator.scala:55-130."""

    best_score: MetricScores
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[Tuple[EngineParams, MetricScores]]

    def to_one_liner(self) -> str:
        return f"[{self.best_score.score}] {json.dumps(self.best_engine_params.to_jsonable())[:120]}"

    def to_jsonable(self) -> dict:
        return {
            "bestScore": json_codec.to_jsonable(self.best_score),
            "bestEngineParams": self.best_engine_params.to_jsonable(),
            "bestIdx": self.best_idx,
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "engineParamsScores": [
                {"engineParams": ep.to_jsonable(),
                 "score": json_codec.to_jsonable(ms)}
                for ep, ms in self.engine_params_scores
            ],
        }

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{ms.score}</td><td>{ms.other_scores}</td>"
            f"<td><pre>{json.dumps(ep.to_jsonable(), indent=2)}</pre></td></tr>"
            for ep, ms in self.engine_params_scores
        )
        return (
            f"<h3>Metric: {self.metric_header}</h3>"
            f"<p>Best score: {self.best_score.score} (candidate #{self.best_idx})</p>"
            f"<table border=1><tr><th>{self.metric_header}</th>"
            f"<th>{self.other_metric_headers}</th><th>Engine params</th></tr>"
            f"{rows}</table>"
        )


class MetricEvaluator(Evaluator):
    """Scores every EngineParams candidate (MetricEvaluator.scala:185-263)."""

    def __init__(
        self,
        metric: Optional[Metric] = None,
        other_metrics: Optional[Sequence[Metric]] = None,
        output_path: Optional[str] = None,
    ):
        super().__init__()
        self.metric = metric or ZeroMetric()
        self.other_metrics = list(other_metrics or [])
        self.output_path = output_path

    def evaluate(
        self,
        ctx: RuntimeContext,
        evaluation: Any,
        engine_eval_data_set: Sequence[Tuple[EngineParams, Any]],
        params: Any = None,
    ) -> MetricEvaluatorResult:
        if not engine_eval_data_set:
            raise ValueError(
                "MetricEvaluator needs at least one EngineParams candidate "
                "(engine_eval_data_set is empty)"
            )
        scores: List[Tuple[EngineParams, MetricScores]] = []
        for engine_params, eval_data in engine_eval_data_set:
            ms = MetricScores(
                score=self.metric.calculate(ctx, eval_data),
                other_scores=[
                    m.calculate(ctx, eval_data) for m in self.other_metrics
                ],
            )
            logger.info("MetricEvaluator: %s -> %s", engine_params, ms.score)
            scores.append((engine_params, ms))

        best_idx = 0
        for i in range(1, len(scores)):
            if self.metric.compare(scores[i][1].score, scores[best_idx][1].score) > 0:
                best_idx = i
        best_params, best_score = scores[best_idx]

        result = MetricEvaluatorResult(
            best_score=best_score,
            best_engine_params=best_params,
            best_idx=best_idx,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            # best.json (MetricEvaluator.saveEngineJson:193)
            with open(self.output_path, "w") as f:
                json.dump(best_params.to_jsonable(), f, indent=2)
            logger.info("Writing best variant params to disk (%s)...", self.output_path)
        return result
