"""FastEvalEngine — batch evaluation with pipeline-prefix memoization.

Parity: controller/FastEvalEngine.scala:46-346. When scoring many
EngineParams candidates, pipeline prefixes shared between candidates
(data source read → preparation → algorithm training → serving) are computed
once: a candidate differing only in serving params reuses the trained models;
one differing only in algorithm params reuses the prepared data, etc. Caches
are keyed on the serialized params prefix exactly like the reference's
``DataSourcePrefix`` / ``PreparatorPrefix`` / ``AlgorithmsPrefix`` /
``ServingPrefix`` case-class keys (FastEvalEngine.scala:60-130).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

from incubator_predictionio_tpu.core.base import EmptyParams, doer
from incubator_predictionio_tpu.core.engine import Engine, _select
from incubator_predictionio_tpu.core.params import EngineParams, WorkflowParams
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.utils import json_codec
from incubator_predictionio_tpu.utils.annotations import experimental

logger = logging.getLogger(__name__)


def _key(*parts: Any) -> str:
    return json.dumps([json_codec.to_jsonable(p) for p in parts], sort_keys=True)


@experimental
class FastEvalEngineWorkflow:
    """Holds the prefix caches for one batch_eval run
    (FastEvalEngine.scala:215-264)."""

    def __init__(self, engine: "FastEvalEngine", ctx: RuntimeContext,
                 params: Optional[WorkflowParams] = None):
        self.engine = engine
        self.ctx = ctx
        self.params = params or WorkflowParams()
        self.data_source_cache: Dict[str, Any] = {}
        self.preparator_cache: Dict[str, Any] = {}
        self.algorithms_cache: Dict[str, Any] = {}
        self.serving_cache: Dict[str, Any] = {}

    # each get_* returns per-eval-set lists, caching on the params prefix
    def get_eval_sets(self, ds_pair: Tuple[str, Any]) -> Any:
        k = _key(ds_pair)
        if k not in self.data_source_cache:
            name, p = ds_pair
            ds = doer(_select(self.engine.data_source_class_map, name, "dataSource"), p)
            self.data_source_cache[k] = ds.read_eval(self.ctx)
        return self.data_source_cache[k]

    def get_prepared(self, ds_pair, prep_pair) -> Any:
        k = _key(ds_pair, prep_pair)
        if k not in self.preparator_cache:
            name, p = prep_pair
            prep = doer(_select(self.engine.preparator_class_map, name, "preparator"), p)
            self.preparator_cache[k] = [
                (prep.prepare(self.ctx, td), info, qas)
                for td, info, qas in self.get_eval_sets(ds_pair)
            ]
        return self.preparator_cache[k]

    def get_models(self, ds_pair, prep_pair, algo_list) -> Any:
        k = _key(ds_pair, prep_pair, algo_list)
        if k not in self.algorithms_cache:
            algos = [
                doer(_select(self.engine.algorithm_class_map, name, "algorithm"), p)
                for name, p in algo_list
            ]
            self.algorithms_cache[k] = [
                ([a.train(self.ctx, pd) for a in algos], algos)
                for pd, _info, _qas in self.get_prepared(ds_pair, prep_pair)
            ]
        return self.algorithms_cache[k]

    def get_result(self, engine_params: EngineParams) -> Any:
        ds_pair = engine_params.data_source_params
        prep_pair = engine_params.preparator_params
        algo_list = engine_params.algorithm_params_list or [("", EmptyParams())]
        serv_pair = engine_params.serving_params
        k = _key(ds_pair, prep_pair, algo_list, serv_pair)
        if k not in self.serving_cache:
            name, p = serv_pair
            serving = doer(_select(self.engine.serving_class_map, name, "serving"), p)
            prepared = self.get_prepared(ds_pair, prep_pair)
            models_per_set = self.get_models(ds_pair, prep_pair, algo_list)
            out = []
            for (pd, info, qas), (models, algos) in zip(prepared, models_per_set):
                qa_indexed = list(enumerate(qas))
                supplemented = [(qx, serving.supplement(q)) for qx, (q, _a) in qa_indexed]
                by_qx: Dict[int, List[Any]] = {qx: [] for qx, _ in supplemented}
                for algo, model in zip(algos, models):
                    for qx, pred in algo.batch_predict(model, supplemented):
                        by_qx[qx].append(pred)
                qpa = [
                    (q, serving.serve(q, by_qx[qx]), a)
                    for qx, (q, a) in qa_indexed
                ]
                out.append((info, qpa))
            self.serving_cache[k] = out
        return self.serving_cache[k]


@experimental
class FastEvalEngine(Engine):
    """Engine whose batch_eval memoizes pipeline prefixes.

    Only for evaluation — ``train`` behaves exactly like Engine
    (FastEvalEngine.scala:292-310 throws on train; we allow it since the
    implementation is shared and correct).
    """

    def batch_eval(
        self,
        ctx: RuntimeContext,
        engine_params_list: Sequence[EngineParams],
        params: Optional[WorkflowParams] = None,
    ) -> List[Tuple[EngineParams, Any]]:
        workflow = FastEvalEngineWorkflow(self, ctx, params)
        return [(ep, workflow.get_result(ep)) for ep in engine_params_list]
