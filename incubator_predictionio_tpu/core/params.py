"""EngineParams and WorkflowParams.

Parity: controller/EngineParams.scala:35-152 (named per-component params
bundle) and workflow/WorkflowParams.scala (run controls). ``sparkEnv`` is
replaced by ``runtime_conf`` (mesh/XLA settings).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.core.base import EmptyParams, Params
from incubator_predictionio_tpu.utils import json_codec


@dataclasses.dataclass
class EngineParams:
    """Named (component-name, params) for every DASE slot.

    Component names select entries of the Engine's class maps; ``""`` selects
    the single registered component (EngineParams.scala:55-83 uses the same
    convention).
    """

    data_source_params: Tuple[str, Params] = ("", EmptyParams())
    preparator_params: Tuple[str, Params] = ("", EmptyParams())
    algorithm_params_list: List[Tuple[str, Params]] = dataclasses.field(
        default_factory=list
    )
    serving_params: Tuple[str, Params] = ("", EmptyParams())

    # -- builder API (EngineParams.Builder, EngineParams.scala:104-152) ----
    def with_data_source(self, params: Params, name: str = "") -> "EngineParams":
        return dataclasses.replace(self, data_source_params=(name, params))

    def with_preparator(self, params: Params, name: str = "") -> "EngineParams":
        return dataclasses.replace(self, preparator_params=(name, params))

    def with_algorithms(
        self, *named: Tuple[str, Params]
    ) -> "EngineParams":
        return dataclasses.replace(self, algorithm_params_list=list(named))

    def with_serving(self, params: Params, name: str = "") -> "EngineParams":
        return dataclasses.replace(self, serving_params=(name, params))

    def to_jsonable(self) -> Dict[str, Any]:
        def comp(pair: Tuple[str, Params]) -> Dict[str, Any]:
            return {"name": pair[0], "params": json_codec.to_jsonable(pair[1])}

        return {
            "dataSourceParams": comp(self.data_source_params),
            "preparatorParams": comp(self.preparator_params),
            "algorithmParamsList": [comp(ap) for ap in self.algorithm_params_list],
            "servingParams": comp(self.serving_params),
        }

    def key(self) -> str:
        """Stable serialization, used by FastEvalEngine prefix caches."""
        import json

        return json.dumps(self.to_jsonable(), sort_keys=True)


class EngineParamsGenerator:
    """Holder of candidate EngineParams lists for tuning
    (controller/EngineParamsGenerator.scala). Subclass and set
    ``engine_params_list``."""

    engine_params_list: List[EngineParams] = []


@dataclasses.dataclass
class WorkflowParams:
    """workflow/WorkflowParams.scala — training run controls."""

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    runtime_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
