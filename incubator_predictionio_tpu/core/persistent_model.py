"""PersistentModel — opt-in custom model persistence.

Parity: controller/PersistentModel.scala:17-115 (``save(id, params, sc)`` +
companion loader) and LocalFileSystemPersistentModel.scala:17-77. The
workflow checkpoints a :class:`PersistentModelManifest` in place of the model
blob and ``Engine.prepare_deploy`` calls ``load`` at deploy, exactly like the
reference resolves the manifest reflectively
(WorkflowUtils.SparkWorkflowUtils.getPersistentModel:347-386).

``RetrainMarker`` is the explicit replacement for the reference's "Unit
model" class: a parallel model that cannot be serialized is stored as Unit
and silently retrained at deploy (Engine.scala:211-233, CoreWorkflow
stores ``()``). On TPU every model is a checkpointable pytree, so this path
exists only for engines that *choose* train-at-deploy semantics.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import pickle
from pathlib import Path
from typing import Any, Optional

from incubator_predictionio_tpu.core.base import Params
from incubator_predictionio_tpu.parallel.context import RuntimeContext


class PersistentModel:
    """Models implementing this manage their own persistence."""

    def save(self, instance_id: str, params: Params, ctx: RuntimeContext) -> bool:
        """Persist; return False to fall back to default checkpointing
        (PersistentModel.scala:84-90)."""
        raise NotImplementedError

    @classmethod
    def load(cls, instance_id: str, params: Params, ctx: RuntimeContext) -> Any:
        """Companion loader (PersistentModelLoader.apply)."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Stored in the model blob in place of a PersistentModel
    (workflow/PersistentModelManifest in CoreWorkflow.scala)."""

    class_path: str
    instance_id: str

    def load(self, params: Params, ctx: RuntimeContext) -> Any:
        module_name, _, cls_name = self.class_path.rpartition(".")
        cls = getattr(importlib.import_module(module_name), cls_name)
        return cls.load(self.instance_id, params, ctx)


@dataclasses.dataclass(frozen=True)
class RetrainMarker:
    """Explicit train-at-deploy marker (reference: the silent Unit model)."""


def model_store_path(instance_id: str, name: str = "model") -> Path:
    base = Path(os.environ.get("PIO_HOME", "~/.pio_tpu")).expanduser() / "pmodels"
    base.mkdir(parents=True, exist_ok=True)
    return base / f"{name}-{instance_id}.pkl"


class LocalFileSystemPersistentModel(PersistentModel):
    """Ready-made local-FS persistence via pickle
    (LocalFileSystemPersistentModel.scala:17-77 uses Spark saveAsObjectFile;
    same contract, local file)."""

    def save(self, instance_id: str, params: Params, ctx: RuntimeContext) -> bool:
        with open(model_store_path(instance_id, type(self).__name__), "wb") as f:
            pickle.dump(self, f)
        return True

    @classmethod
    def load(cls, instance_id: str, params: Params, ctx: RuntimeContext) -> Any:
        with open(model_store_path(instance_id, cls.__name__), "rb") as f:
            return pickle.load(f)
