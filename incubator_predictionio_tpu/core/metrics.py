"""The Metric family for evaluation scoring.

Parity: controller/Metric.scala:39-269. A metric scores the full evaluation
output ``[(eval_info, [(query, prediction, actual)])]``; the statistical
bases mirror AverageMetric:99, OptionAverageMetric:124, StdevMetric:151,
OptionStdevMetric:179, SumMetric:205, ZeroMetric:234, QPAMetric:259.
"""

from __future__ import annotations

import math
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

from incubator_predictionio_tpu.core.base import EI, A, P, Q
from incubator_predictionio_tpu.parallel.context import RuntimeContext

R = TypeVar("R")

EvalDataSet = Sequence[Tuple[Any, Sequence[Tuple[Any, Any, Any]]]]


class Metric(Generic[EI, Q, P, A, R]):
    """Base metric (Metric.scala:39). Higher ``compare`` wins."""

    def header(self) -> str:
        return type(self).__name__

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> R:
        raise NotImplementedError

    def compare(self, r0: R, r1: R) -> int:
        """Ordering (Metric.scala:52): >0 if r0 better than r1."""
        if r0 == r1:
            return 0
        return 1 if r0 > r1 else -1  # type: ignore[operator]


class QPAMetric(Metric[EI, Q, P, A, R]):
    """Per-(Q,P,A) scoring hook (Metric.scala:259)."""

    def calculate_qpa(self, q: Q, p: P, a: A) -> R:
        raise NotImplementedError


def _all_scores(
    metric: "QPAMetric", eval_data_set: EvalDataSet
) -> List[Any]:
    return [
        metric.calculate_qpa(q, p, a)
        for _info, qpas in eval_data_set
        for q, p, a in qpas
    ]


def _present(scores: List[Optional[float]]) -> List[float]:
    return [s for s in scores if s is not None]


class AverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean of per-tuple scores across all eval sets (Metric.scala:99)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        scores = _all_scores(self, eval_data_set)
        return sum(scores) / len(scores) if scores else float("nan")


class OptionAverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean ignoring None scores (Metric.scala:124)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        scores = _present(_all_scores(self, eval_data_set))
        return sum(scores) / len(scores) if scores else float("nan")


def _stdev(scores: List[float]) -> float:
    if not scores:
        return float("nan")
    mean = sum(scores) / len(scores)
    return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class StdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Population stdev of scores (Metric.scala:151)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        return _stdev(_all_scores(self, eval_data_set))


class OptionStdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Stdev ignoring None (Metric.scala:179)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        return _stdev(_present(_all_scores(self, eval_data_set)))


class SumMetric(QPAMetric[EI, Q, P, A, float]):
    """Sum of scores (Metric.scala:205)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        return sum(_all_scores(self, eval_data_set))


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 — placeholder (Metric.scala:234)."""

    def calculate(self, ctx: RuntimeContext, eval_data_set: EvalDataSet) -> float:
        return 0.0
