"""The DASE abstraction: typed base contracts + the Engine controller.

Reference layers L3 (core/src/main/scala/.../core/) and L4
(core/src/main/scala/.../controller/) of SURVEY.md §1.
"""

from incubator_predictionio_tpu.core.base import (
    Algorithm,
    DataSource,
    EmptyParams,
    Params,
    Preparator,
    IdentityPreparator,
    SanityCheck,
    Serving,
    FirstServing,
    AverageServing,
    StopAfterReadInterruption,
    StopAfterPrepareInterruption,
    doer,
    params_class_of,
)
from incubator_predictionio_tpu.core.params import EngineParams, WorkflowParams
from incubator_predictionio_tpu.core.engine import Engine, EngineFactory
from incubator_predictionio_tpu.core.metrics import (
    Metric,
    AverageMetric,
    OptionAverageMetric,
    StdevMetric,
    OptionStdevMetric,
    SumMetric,
    ZeroMetric,
)
from incubator_predictionio_tpu.core.evaluation import (
    Evaluation,
    MetricEvaluator,
    MetricScores,
)
from incubator_predictionio_tpu.core.persistent_model import (
    PersistentModel,
    LocalFileSystemPersistentModel,
)

__all__ = [
    "Algorithm", "DataSource", "EmptyParams", "Params", "Preparator",
    "IdentityPreparator", "SanityCheck", "Serving", "FirstServing",
    "AverageServing", "StopAfterReadInterruption",
    "StopAfterPrepareInterruption", "doer", "params_class_of",
    "EngineParams", "WorkflowParams", "Engine", "EngineFactory",
    "Metric", "AverageMetric", "OptionAverageMetric", "StdevMetric",
    "OptionStdevMetric", "SumMetric", "ZeroMetric",
    "Evaluation", "MetricEvaluator", "MetricScores",
    "PersistentModel", "LocalFileSystemPersistentModel",
]
