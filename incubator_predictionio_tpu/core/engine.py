"""Engine — the DASE composition and train/eval orchestration.

Parity: controller/Engine.scala:83-832. The Engine holds *class maps* for
each DASE slot (multiple named implementations; params select by name),
instantiates components through :func:`doer`, and orchestrates:

- ``train``  (Engine.scala:625-712): read → sanity → prepare → sanity →
  per-algorithm train → sanity.
- ``eval``   (Engine.scala:730-820): per eval-set train + per-algorithm
  batch predict + serve join, with the *original* (unsupplemented) query
  passed to ``serve``.
- ``jvalue_to_engine_params`` (Engine.scala:357-420): engine.json variant →
  typed EngineParams.
- ``prepare_deploy`` (Engine.scala:199-269): restore checkpointed models into
  servable (device-resident) form.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from incubator_predictionio_tpu.core import base
from incubator_predictionio_tpu.core.base import (
    Algorithm,
    DataSource,
    EmptyParams,
    Params,
    Preparator,
    SanityCheck,
    Serving,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    doer,
    params_class_of,
)
from incubator_predictionio_tpu.core.params import EngineParams, WorkflowParams
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.utils import json_codec, tracing

logger = logging.getLogger(__name__)


def _as_class_map(spec: Any) -> Dict[str, type]:
    """Accept a single class or a name→class dict (Engine.scala:500-560
    companion constructors do the same normalization)."""
    if isinstance(spec, dict):
        return dict(spec)
    return {"": spec}


def _select(class_map: Dict[str, type], name: str, slot: str) -> type:
    if name in class_map:
        return class_map[name]
    if name == "" and len(class_map) == 1:
        return next(iter(class_map.values()))
    raise ValueError(
        f"{slot} has no component named {name!r} (registered: {sorted(class_map)})"
    )


def _sanity(obj: Any, skip: bool) -> None:
    if skip:
        return
    if isinstance(obj, SanityCheck):
        logger.info("%s supports data sanity check. Performing check.",
                    type(obj).__name__)
        obj.sanity_check()


class Engine:
    """The DASE engine (controller/Engine.scala:83)."""

    def __init__(
        self,
        data_source_class_map: Any,
        preparator_class_map: Any,
        algorithm_class_map: Any,
        serving_class_map: Any,
    ):
        self.data_source_class_map = _as_class_map(data_source_class_map)
        self.preparator_class_map = _as_class_map(preparator_class_map)
        self.algorithm_class_map = _as_class_map(algorithm_class_map)
        self.serving_class_map = _as_class_map(serving_class_map)

    # -- component instantiation ------------------------------------------
    def _components(
        self, engine_params: EngineParams
    ) -> Tuple[DataSource, Preparator, List[Algorithm], Serving]:
        ds_name, ds_params = engine_params.data_source_params
        prep_name, prep_params = engine_params.preparator_params
        serv_name, serv_params = engine_params.serving_params
        data_source = doer(
            _select(self.data_source_class_map, ds_name, "dataSource"), ds_params
        )
        preparator = doer(
            _select(self.preparator_class_map, prep_name, "preparator"), prep_params
        )
        algo_list = [
            doer(_select(self.algorithm_class_map, name, "algorithm"), params)
            for name, params in (engine_params.algorithm_params_list or [("", EmptyParams())])
        ]
        serving = doer(
            _select(self.serving_class_map, serv_name, "serving"), serv_params
        )
        return data_source, preparator, algo_list, serving

    def components(
        self, engine_params: EngineParams
    ) -> Tuple[DataSource, Preparator, List[Algorithm], Serving]:
        """Instantiate all DASE components once (deploy paths should call
        this instead of algorithms()+serving() to avoid rebuilding)."""
        return self._components(engine_params)

    def algorithms(self, engine_params: EngineParams) -> List[Algorithm]:
        return self._components(engine_params)[2]

    def serving(self, engine_params: EngineParams) -> Serving:
        return self._components(engine_params)[3]

    # -- training (Engine.scala:625-712) ----------------------------------
    def train(
        self,
        ctx: RuntimeContext,
        engine_params: EngineParams,
        params: Optional[WorkflowParams] = None,
        prev_models: Optional[List[Any]] = None,
    ) -> List[Any]:
        """``prev_models`` (aligned with the algorithm list) enables the
        continuation-retrain path: each algorithm receives its previous
        model through ``Algorithm.train_with_previous`` and decides
        itself whether it can seed from it (CoreWorkflow.run_train loads
        them from the last COMPLETED instance behind the
        ``PIO_RETRAIN_CONTINUE`` knob)."""
        params = params or WorkflowParams()
        data_source, preparator, algo_list, _ = self._components(engine_params)
        logger.info("Engine.train: ds=%s prep=%s algos=%s",
                    type(data_source).__name__, type(preparator).__name__,
                    [type(a).__name__ for a in algo_list])

        with tracing.phase("read"):
            td = data_source.read_training(ctx)
        _sanity(td, params.skip_sanity_check)
        if params.verbose >= 3:
            logger.info("Training data: %s", tracing.debug_string(td))
        if params.stop_after_read:
            raise StopAfterReadInterruption()

        with tracing.phase("prepare"):
            pd = preparator.prepare(ctx, td)
        _sanity(pd, params.skip_sanity_check)
        if params.verbose >= 3:
            logger.info("Prepared data: %s", tracing.debug_string(pd))
        if params.stop_after_prepare:
            raise StopAfterPrepareInterruption()

        models = []
        for i, algo in enumerate(algo_list):
            prev = (prev_models[i]
                    if prev_models is not None and i < len(prev_models)
                    else None)
            with tracing.phase(f"train.algo{i}"):
                models.append(
                    algo.train_with_previous(ctx, pd, prev)
                    if prev is not None else algo.train(ctx, pd))
        for model in models:
            _sanity(model, params.skip_sanity_check)
        return models

    # -- evaluation (Engine.scala:730-820) --------------------------------
    def eval(
        self,
        ctx: RuntimeContext,
        engine_params: EngineParams,
        params: Optional[WorkflowParams] = None,
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Returns [(eval_info, [(query, served_prediction, actual)])]."""
        params = params or WorkflowParams()
        data_source, preparator, algo_list, serving = self._components(engine_params)

        eval_sets = data_source.read_eval(ctx)
        out: List[Tuple[Any, List[Tuple[Any, Any, Any]]]] = []
        for td, eval_info, qa_pairs in eval_sets:
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algo_list]

            qa_indexed = list(enumerate(qa_pairs))
            supplemented = [
                (qx, serving.supplement(q)) for qx, (q, a) in qa_indexed
            ]
            # per-algorithm batch predict over the supplemented queries,
            # joined back by query index, ordered by algorithm index
            predictions_by_qx: Dict[int, List[Any]] = {
                qx: [] for qx, _ in supplemented
            }
            for algo, model in zip(algo_list, models):
                for qx, p in algo.batch_predict(model, supplemented):
                    predictions_by_qx[qx].append(p)
            qpa: List[Tuple[Any, Any, Any]] = []
            for qx, (q, a) in qa_indexed:
                ps = predictions_by_qx[qx]
                assert len(ps) == len(algo_list), (
                    "Must have one prediction per algorithm"
                )
                # serve sees the ORIGINAL query (Engine.scala:805-808)
                qpa.append((q, serving.serve(q, ps), a))
            out.append((eval_info, qpa))
        return out

    def batch_eval(
        self,
        ctx: RuntimeContext,
        engine_params_list: Sequence[EngineParams],
        params: Optional[WorkflowParams] = None,
    ) -> List[Tuple[EngineParams, List[Tuple[Any, List[Tuple[Any, Any, Any]]]]]]:
        """BaseEngine.batchEval:82 — evaluate every candidate EngineParams."""
        return [
            (ep, self.eval(ctx, ep, params)) for ep in engine_params_list
        ]

    # -- deploy-time model restoration (Engine.scala:199-269) --------------
    def prepare_deploy(
        self,
        ctx: RuntimeContext,
        engine_params: EngineParams,
        engine_instance_id: str,
        models: List[Any],
        params: Optional[WorkflowParams] = None,
    ) -> List[Any]:
        """Turn checkpointed models into servable models.

        Reference semantics: Unit models (non-serializable RDD models) are
        silently *retrained* at deploy (Engine.scala:211-233); PersistentModel
        manifests are loaded via their companion loader (:241-255). Here every
        directly-checkpointable model passes through unchanged; PersistentModel
        manifests load through ``PersistentModel.load``; and a ``RetrainMarker``
        (the explicit replacement for the silent-Unit behavior) triggers
        retraining.
        """
        from incubator_predictionio_tpu.core.persistent_model import (
            PersistentModelManifest,
            RetrainMarker,
        )

        algo_list = self.algorithms(engine_params)
        if len(models) != len(algo_list):
            raise ValueError(
                f"{len(models)} models for {len(algo_list)} algorithms"
            )
        if any(isinstance(m, RetrainMarker) for m in models):
            logger.info("Some models are retrain markers; retraining at deploy.")
            trained = self.train(ctx, engine_params, params)
        else:
            trained = models
        out: List[Any] = []
        for algo, model in zip(algo_list, trained):
            if isinstance(model, PersistentModelManifest):
                model = model.load(algo.params, ctx)
            out.append(algo.prepare_model(ctx, model))
        return out

    # -- engine.json params extraction (Engine.scala:357-420) ---------------
    def jvalue_to_engine_params(
        self, variant: Dict[str, Any], lenient: bool = True
    ) -> EngineParams:
        def one(slot: str, class_map: Dict[str, type], obj: Any) -> Tuple[str, Params]:
            if obj is None:
                return ("", EmptyParams())
            name = obj.get("name", "") if isinstance(obj, dict) else ""
            raw = obj.get("params", {}) if isinstance(obj, dict) else {}
            cls = _select(class_map, name, slot)
            pcls = params_class_of(cls)
            if pcls is None:
                return (name, EmptyParams() if not raw else raw)
            return (name, json_codec.extract(pcls, raw, lenient=lenient))

        algorithms = variant.get("algorithms")
        algo_params: List[Tuple[str, Params]] = []
        if algorithms:
            for spec in algorithms:
                algo_params.append(one("algorithm", self.algorithm_class_map, spec))
        return EngineParams(
            data_source_params=one(
                "dataSource", self.data_source_class_map, variant.get("datasource")
            ),
            preparator_params=one(
                "preparator", self.preparator_class_map, variant.get("preparator")
            ),
            algorithm_params_list=algo_params,
            serving_params=one(
                "serving", self.serving_class_map, variant.get("serving")
            ),
        )


    def engine_params_from_instance(self, instance: Any) -> EngineParams:
        """Reconstruct typed EngineParams from a stored EngineInstance
        (Engine.engineInstanceToEngineParams, Engine.scala:422-470)."""
        import json

        def one(slot: str, class_map: Dict[str, type], raw: str) -> Tuple[str, Params]:
            if not raw:
                return ("", EmptyParams())
            name, params_obj = json.loads(raw)
            cls = _select(class_map, name, slot)
            pcls = params_class_of(cls)
            if pcls is None or not params_obj:
                return (name, EmptyParams())
            return (name, json_codec.extract(pcls, params_obj))

        algo_list: List[Tuple[str, Params]] = []
        if instance.algorithms_params:
            for name, params_obj in json.loads(instance.algorithms_params):
                cls = _select(self.algorithm_class_map, name, "algorithm")
                pcls = params_class_of(cls)
                algo_list.append(
                    (name, json_codec.extract(pcls, params_obj))
                    if pcls is not None and params_obj
                    else (name, EmptyParams())
                )
        return EngineParams(
            data_source_params=one(
                "dataSource", self.data_source_class_map,
                instance.data_source_params,
            ),
            preparator_params=one(
                "preparator", self.preparator_class_map,
                instance.preparator_params,
            ),
            algorithm_params_list=algo_list,
            serving_params=one(
                "serving", self.serving_class_map, instance.serving_params
            ),
        )


class EngineFactory:
    """controller/EngineFactory.scala — subclass and implement ``apply``."""

    def apply(self) -> Engine:
        raise NotImplementedError

    def engine_params(self, variant: Dict[str, Any]) -> EngineParams:
        return self.apply().jvalue_to_engine_params(variant)
