"""SelfCleaningDataSource — sliding-window event-store compaction.

Parity: core/.../core/SelfCleaningDataSource.scala:76-325. A DataSource mixes
this in to keep its app's event data bounded: events older than
``EventWindow.duration`` are dropped, ``$set``/``$unset`` chains per entity
are compressed into single events, and exact duplicates are removed; the
cleaned set then *replaces* the persisted events (``wipe``, :209). The
reference implements L and P variants over LEvents/PEvents; here one
host-side pass covers both (see data.storage.base.Events).
"""

from __future__ import annotations

import dataclasses
import logging
import re
from datetime import datetime, timedelta
from typing import Iterable, List, Optional, Tuple

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.utils.times import now_utc

logger = logging.getLogger(__name__)

_DURATION_RE = re.compile(
    r"^\s*(\d+)\s*(s|sec|second|seconds|m|min|minute|minutes|h|hour|hours|"
    r"d|day|days|w|week|weeks)?\s*$"
)
_UNIT_SECONDS = {
    None: 1, "s": 1, "sec": 1, "second": 1, "seconds": 1,
    "m": 60, "min": 60, "minute": 60, "minutes": 60,
    "h": 3600, "hour": 3600, "hours": 3600,
    "d": 86400, "day": 86400, "days": 86400,
    "w": 604800, "week": 604800, "weeks": 604800,
}


def parse_duration(spec: "str | int | float | timedelta") -> timedelta:
    """Parse ``"30 days"`` / ``"3600s"`` / seconds (scala Duration parity)."""
    if isinstance(spec, timedelta):
        return spec
    if isinstance(spec, (int, float)):
        return timedelta(seconds=spec)
    m = _DURATION_RE.match(spec)
    if not m:
        raise ValueError(f"Cannot parse duration {spec!r}")
    return timedelta(seconds=int(m.group(1)) * _UNIT_SECONDS[m.group(2)])


@dataclasses.dataclass(frozen=True)
class EventWindow:
    """SelfCleaningDataSource.scala:321 EventWindow."""

    duration: Optional[str] = None
    remove_duplicates: bool = False
    compress_properties: bool = False


def _dedup_key(e: Event) -> Tuple:
    # identity minus eventId/eventTime/creationTime — the reference's
    # removeDuplicates keys on the event recreated with times zeroed
    # (SelfCleaningDataSource.scala:128-152 recreateEvent) and keeps the
    # first occurrence's id and eventTime.
    return (
        e.event, e.entity_type, e.entity_id, e.target_entity_type,
        e.target_entity_id, e.properties, e.pr_id, e.tags,
    )


def compress_properties(events: Iterable[Event]) -> List[Event]:
    """Compress per-entity ``$set`` chains (compressPProperties:107-117):
    all ``$set`` events of one entity merge right-biased-by-time into a
    single ``$set`` carrying the chain's final property state, stamped with
    the latest event time. Everything else (incl. ``$unset``) passes through,
    matching the reference's ``isSetEvent`` filter."""
    set_chains: dict[Tuple[str, str], List[Event]] = {}
    out: List[Event] = []
    for e in sorted(events, key=lambda e: e.event_time):
        if e.event == "$set":
            set_chains.setdefault((e.entity_type, e.entity_id), []).append(e)
        else:
            out.append(e)
    for chain in set_chains.values():
        merged = DataMap()
        for e in chain:
            merged = merged + e.properties
        out.append(
            dataclasses.replace(chain[-1], properties=merged, event_id=None)
        )
    return sorted(out, key=lambda e: e.event_time)


class SelfCleaningDataSource:
    """Mixin for DataSources. Set ``app_name`` and ``event_window``; call
    :meth:`clean_persisted_events` at the start of ``read_training``
    (the reference calls it from readTraining/readEval wrappers,
    SelfCleaningDataSource.scala:269-301)."""

    app_name: str
    #: optional channel the DataSource reads — cleaning targets the same one
    channel_name: Optional[str] = None
    event_window: Optional[EventWindow] = None

    def _app_id(self) -> int:
        app = Storage.get_meta_data_apps().get_by_name(self.app_name)
        if app is None:
            raise ValueError(f"Invalid app name {self.app_name}")
        return app.id

    def _channel_id(self) -> Optional[int]:
        name = getattr(self, "channel_name", None)
        if not name:
            return None
        for c in Storage.get_meta_data_channels().get_by_appid(self._app_id()):
            if c.name == name:
                return c.id
        raise ValueError(
            f"Invalid channel name {name} for app {self.app_name}"
        )

    def get_cleaned_events(self, events: Iterable[Event]) -> List[Event]:
        """Pure transformation (cleanPEvents/compress/dedup)."""
        window = self.event_window
        rows = list(events)
        if window is None:
            return sorted(rows, key=lambda e: e.event_time)
        if window.duration is not None:
            cutoff = now_utc() - parse_duration(window.duration)
            rows = [e for e in rows if e.event_time >= cutoff]
        if window.compress_properties:
            rows = compress_properties(rows)
        if window.remove_duplicates:
            seen = set()
            unique = []
            for e in sorted(rows, key=lambda e: e.event_time):
                k = _dedup_key(e)
                if k not in seen:
                    seen.add(k)
                    unique.append(e)
            rows = unique
        return sorted(rows, key=lambda e: e.event_time)

    def clean_persisted_events(self, channel_id: Optional[int] = "__from_name__") -> int:
        """Clean + rewrite the persisted events (cleanPersistedPEvents:161,
        wipe:209) of the channel this DataSource reads (``channel_name``,
        default channel when unset). Returns the cleaned event count."""
        if self.event_window is None:
            return 0
        if channel_id == "__from_name__":
            channel_id = self._channel_id()
        app_id = self._app_id()
        dao = Storage.get_events()
        before = list(dao.find(app_id=app_id, channel_id=channel_id))
        cleaned = self.get_cleaned_events(before)
        logger.info(
            "SelfCleaningDataSource: %d events -> %d after cleaning",
            len(before), len(cleaned),
        )
        dao.remove(app_id, channel_id)
        dao.init(app_id, channel_id)
        for e in cleaned:
            dao.insert(e, app_id, channel_id)
        return len(cleaned)
