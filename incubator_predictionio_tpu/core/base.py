"""Base SPI — the typed contracts every DASE component implements.

Parity with reference L3 (core/.../core/Base{DataSource,Preparator,Algorithm,
Serving,Evaluator}.scala, AbstractDoer.scala) and the user-facing L4
controller bases (core/.../controller/{L,P,P2L}Algorithm.scala,
{L,P}DataSource.scala, {L,P}Preparator.scala, LServing.scala).

**The L/P split collapses by design.** The reference needs three algorithm
flavors because a model is either driver-local (L), RDD-distributed (P), or
trained-distributed-then-localized (P2L). On TPU every model is a pytree
whose arrays live on the mesh; "local vs distributed" is a sharding
annotation, not a class hierarchy. One ``Algorithm`` base therefore covers
LAlgorithm:45 / PAlgorithm:47 / P2LAlgorithm:46, and one ``DataSource`` /
``Preparator`` covers both flavors. This behavioral delta is intentional and
documented (SURVEY.md §7 hard part (f)).
"""

from __future__ import annotations

import abc
import dataclasses
import inspect
import typing
from typing import Any, Generic, List, Optional, Sequence, Tuple, Type, TypeVar

from incubator_predictionio_tpu.parallel.context import RuntimeContext

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
Q = TypeVar("Q")    # query
P = TypeVar("P")    # predicted result
A = TypeVar("A")    # actual result
M = TypeVar("M")    # model
R = TypeVar("R")    # metric result


class Params:
    """Marker base for component parameter dataclasses
    (controller/Params.scala:32). Subclasses should be ``@dataclass``es."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """controller/Params.scala EmptyParams."""


class SanityCheck(abc.ABC):
    """Data classes may implement this to participate in the train-time
    sanity check (core/.../core/SanityCheck.scala; called from
    Engine.scala:652-708)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise if the data is invalid."""


class StopAfterReadInterruption(Exception):
    """Engine.scala:668 — raised when WorkflowParams.stop_after_read."""


class StopAfterPrepareInterruption(Exception):
    """Engine.scala:689 — raised when WorkflowParams.stop_after_prepare."""


# ---------------------------------------------------------------------------
# Doer — component instantiation from Params (AbstractDoer.scala:33-60)
# ---------------------------------------------------------------------------

def doer(cls: Type[Any], params: Params) -> Any:
    """Instantiate a component: try ctor(params), else no-arg ctor.

    The reference does this reflectively over JVM constructors
    (AbstractDoer.scala:40-59); here we inspect the Python signature once.
    """
    sig = inspect.signature(cls.__init__)
    positional = [
        p
        for name, p in list(sig.parameters.items())[1:]  # skip self
        if p.kind
        in (inspect.Parameter.POSITIONAL_ONLY, inspect.Parameter.POSITIONAL_OR_KEYWORD)
    ]
    if positional:
        return cls(params)
    return cls()


def params_class_of(cls: Type[Any]) -> Optional[Type[Params]]:
    """The Params dataclass a component's constructor expects, if any.

    Resolution order: explicit ``params_class`` attribute, then the type
    annotation of the first constructor argument. Used by
    ``Engine.jvalue_to_engine_params`` to type engine.json params the way the
    reference recovers them from manifest class info
    (WorkflowUtils.extractParams, core/.../workflow/WorkflowUtils.scala:134).
    """
    explicit = getattr(cls, "params_class", None)
    if explicit is not None:
        return explicit
    try:
        hints = typing.get_type_hints(cls.__init__)
    except Exception:
        hints = {}
    sig = inspect.signature(cls.__init__)
    for name, p in list(sig.parameters.items())[1:]:
        if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        ):
            hint = hints.get(name)
            if isinstance(hint, type) and issubclass(hint, Params):
                return hint
            return None
    return None


# ---------------------------------------------------------------------------
# DASE bases
# ---------------------------------------------------------------------------

class _Component:
    """Common base: stores params like the reference's ctor convention."""

    def __init__(self, params: Params = EmptyParams()):
        self.params = params


class DataSource(_Component, Generic[TD, EI, Q, A]):
    """Reads training and evaluation data from the event store.

    Parity: core/BaseDataSource.scala:43-54 + controller/{P,L}DataSource.scala.
    """

    def read_training(self, ctx: RuntimeContext) -> TD:
        raise NotImplementedError

    def read_eval(
        self, ctx: RuntimeContext
    ) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """Evaluation data: (training set, eval info, (query, actual) pairs)
        per fold (PDataSource.readEval:55). Default: no eval data."""
        return []


class Preparator(_Component, Generic[TD, PD]):
    """Transforms training data into algorithm input
    (core/BasePreparator.scala:44, controller/{P,L}Preparator.scala)."""

    def prepare(self, ctx: RuntimeContext, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(Preparator[TD, TD]):
    """Pass-through (controller/IdentityPreparator.scala:34,59)."""

    def prepare(self, ctx: RuntimeContext, training_data: TD) -> TD:
        return training_data


class Algorithm(_Component, Generic[PD, M, Q, P]):
    """Trains a model and answers queries.

    Parity: core/BaseAlgorithm.scala:69-111 and all three controller
    algorithm flavors (see module docstring). Models should be pytrees of
    device arrays (+ host-side index maps such as BiMap); ``predict`` should
    be wrapped in ``jax.jit`` by the implementation with the model donated /
    device-resident so serving never re-stages weights.
    """

    def train(self, ctx: RuntimeContext, prepared_data: PD) -> M:
        raise NotImplementedError

    def train_with_previous(
        self, ctx: RuntimeContext, prepared_data: PD, prev_model: Any
    ) -> M:
        """Continuation-retrain hook: train with the previous run's model
        available as a warm start (the steady-state O(delta) path —
        ops/retrain.py). The DEFAULT ignores ``prev_model`` and trains
        fresh, so algorithms without a continuation story are untouched.
        Implementations MUST validate compatibility themselves (rank /
        index-space prefix / hyperparameters) and fall back to
        ``self.train`` when the prior model cannot seed this one — a
        wrong warm start silently corrupts the model, while a refused
        one only costs a cold train."""
        return self.train(ctx, prepared_data)

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(
        self, model: M, queries: Sequence[Tuple[int, Q]]
    ) -> List[Tuple[int, P]]:
        """Batch prediction for evaluation (BaseAlgorithm.batchPredictBase:81).

        Default loops ``predict``; TPU implementations should override with a
        single jitted batched call (the MXU wants one big matmul, not Q small
        ones).
        """
        return [(qx, self.predict(model, q)) for qx, q in queries]

    def batch_serve_json(self, model: M, docs: Sequence[Any]
                         ) -> Optional[List[Optional[bytes]]]:
        """Optional serving fast path: raw parsed query docs → fully
        rendered response-body bytes, skipping Query/Prediction object
        construction and the jsonable tree walk entirely (the serving
        analogue of the event store's columnar ingest path).

        Return None when the algorithm has no such path; otherwise a list
        aligned with ``docs`` where each slot is the response bytes —
        BYTE-IDENTICAL to ``json.dumps(to_jsonable(serve-result))`` for a
        first-prediction serving — or None for docs the fast path cannot
        take (filtered/custom queries fall back to the object path). The
        PredictionServer only consults this when serving is declared
        first-prediction-only and no feedback/output plugins are active
        (prediction_server._handle_batch)."""
        return None

    def prepare_model(self, ctx: RuntimeContext, model: M) -> M:
        """Deploy-time hook: make a checkpoint-restored model servable.

        Checkpoints hold host numpy arrays (workflow/checkpoint.py); without
        this hook every predict would re-transfer weights host→device. TPU
        implementations should ``jax.device_put`` their arrays here so
        serving runs against device-resident state. Called by
        ``Engine.prepare_deploy`` (the reference's equivalent moment is
        CreateServer's model localization, CreateServer.scala:216-266).
        """
        return model

    def warmup(self, model: M, max_batch: int = 1) -> None:
        """Deploy-time pre-compilation hook (optional, default no-op).

        The first query against a freshly deployed engine otherwise pays
        XLA compilation of the scoring dispatch (seconds to tens of
        seconds on TPU). Implementations should run their jitted serving
        paths once per compiled shape — e.g. the singleton path plus the
        power-of-two micro-batch sizes up to ``max_batch``. Called by the
        PredictionServer on a background thread AFTER the server binds,
        so deploy latency is unchanged and only pre-warm queries compile.
        The reference has no counterpart (its JVM serving needs no
        compilation step); errors must not escape — the server logs and
        serves anyway.
        """

    def make_speed_overlay(self, model: M, app_name: Optional[str],
                           channel_name: Optional[str],
                           data_source_params: Any = None):
        """Speed-layer hook (incubator_predictionio_tpu/speed/): return a
        configured ``SpeedOverlay`` over this model's frozen factors, or
        None (the default) when the algorithm has no fold-in story.

        Called by the PredictionServer at deploy/reload time with the
        app/channel resolved from the engine's data-source params (and
        those params themselves, for event-weight knobs that live there).
        Implementations MUST build the overlay with the SAME event shape
        and regularization their training used — the fold-in solve is
        only "exact model quality" when it solves the training objective.
        The server owns the overlay lifecycle (start/stop/invalidate on
        hot swap) and attaches it via :meth:`attach_speed_overlay`."""
        return None

    def attach_speed_overlay(self, overlay) -> None:
        """Bind (or clear, with None) the serving-time overlay consulted
        before the base model. Engines read ``self._speed_overlay`` in
        their predict paths."""
        self._speed_overlay = overlay

    @property
    def speed_overlay(self):
        return getattr(self, "_speed_overlay", None)

    @property
    def query_class(self) -> Optional[type]:
        """Query dataclass for JSON extraction at the server edge
        (BaseAlgorithm.queryClass via TypeToken, BaseAlgorithm.scala:117).

        Resolution: explicit ``query_class_`` attribute, else the type
        annotation of ``predict``'s query argument.
        """
        explicit = getattr(self, "query_class_", None)
        if explicit is not None:
            return explicit
        try:
            hints = typing.get_type_hints(self.predict)
        except Exception:
            return None
        sig = inspect.signature(self.predict)
        names = [n for n in sig.parameters if n != "self"]
        if len(names) >= 2:
            hint = hints.get(names[1])
            if isinstance(hint, type):
                return hint
        return None


class Serving(_Component, Generic[Q, P]):
    """Combines per-algorithm predictions into the served result
    (core/BaseServing.scala:41-53, controller/LServing.scala:30-54)."""

    #: declared capability: ``serve`` returns predictions[0] unchanged and
    #: ``supplement`` is the identity — the conditions under which the
    #: PredictionServer may route plain queries through an algorithm's
    #: ``batch_serve_json`` fast path (rendered bytes never see serve()).
    #: Subclasses that override either method must leave this False.
    FIRST_PREDICTION_ONLY = False

    def supplement(self, query: Q) -> Q:
        """Pre-process the query before algorithms see it (LServing.supplement:41)."""
        return query

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError


class FirstServing(Serving[Q, P]):
    """Serve the first algorithm's prediction (controller/LFirstServing.scala)."""

    FIRST_PREDICTION_ONLY = True

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Average numeric predictions (controller/LAverageServing.scala)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)


class Evaluator(_Component, Generic[EI, Q, P, A, R]):
    """Scores evaluation output (core/BaseEvaluator.scala:52)."""

    def evaluate(
        self,
        ctx: RuntimeContext,
        evaluation: Any,
        engine_eval_data_set: Sequence[
            Tuple[Any, Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]]
        ],
        params: Any,
    ) -> R:
        raise NotImplementedError
