"""Recommended-user engine: user→user recommendations from follow events.

The similarproduct family's ``recommended-user`` template variant
(examples/scala-parallel-similarproduct/recommended-user/): instead of
item-to-item similarity it learns user-to-user affinity from ``follow``
events and answers "who should these users follow next".

Reference parity:

- ``Query(users, num, whiteList?, blackList?)`` /
  ``PredictedResult(similarUserScores)`` (Engine.scala:22-36).
- DataSource reads ``user follow user`` events (DataSource.scala:52-60).
- ALSAlgorithm trains implicit ALS on the follower×followed matrix with
  ONE shared user index for both sides (ALSAlgorithm.scala:74-76 builds a
  single BiMap); the model keeps the followed-side factors
  (``m.productFeatures``, :120).
- Predict scores every user by the SUM of cosine similarities to the
  query users' vectors, drops the query users themselves, applies
  white/blacklists, keeps positive scores, top N
  (ALSAlgorithm.scala:127-185).

TPU shape: factors are L2-normalized once at train time, so the serve-time
cosine sum collapses to one matvec ``normed @ Σ normed[query]`` against
the whole user catalog (host copy for small models, fused device
score+top-k otherwise).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    users: Tuple[str, ...]
    num: int
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class SimilarUserScore:
    __camel_case__ = True

    user: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True

    similar_user_scores: Tuple[SimilarUserScore, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None


@dataclasses.dataclass
class TrainingData:
    #: columnar follower→followed scan (both sides are users)
    follows: Interactions

    def __len__(self) -> int:
        return len(self.follows)

    def sanity_check(self) -> None:
        if not len(self):
            raise ValueError("TrainingData has no follow events")


class RecommendedUserDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        follows = EventStore.interactions(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="user",
            event_names=("follow",),
            event_values={"follow": 1.0},
        )
        return TrainingData(follows=follows)


@dataclasses.dataclass
class PreparedData:
    followers: np.ndarray     # [nnz] int32, shared user index
    followed: np.ndarray      # [nnz] int32, shared user index
    weights: np.ndarray       # [nnz] f32
    user_bimap: BiMap         # ONE id space for both matrix sides


class RecommendedUserPreparator(Preparator):
    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        """Merge the scan's follower/followed id tables into the single
        shared user index the reference uses for both ALS sides
        (ALSAlgorithm.scala:74-76)."""
        inter = td.follows
        mapping: Dict[str, int] = {}
        for uid in inter.user_ids:
            mapping.setdefault(uid, len(mapping))
        for uid in inter.item_ids:
            mapping.setdefault(uid, len(mapping))
        bimap = BiMap(mapping)
        follower_remap = np.asarray(
            [mapping[u] for u in inter.user_ids], np.int32)
        followed_remap = np.asarray(
            [mapping[u] for u in inter.item_ids], np.int32)
        return PreparedData(
            followers=follower_remap[inter.user_idx],
            followed=followed_remap[inter.item_idx],
            weights=inter.values.astype(np.float32),
            user_bimap=bimap,
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    __camel_case__ = True

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None


@dataclasses.dataclass
class RecommendedUserModel:
    #: followed-side factors, L2-normalized rows (cosine = dot)
    user_features: Any
    user_bimap: BiMap


class RecommendedUserAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams
    query_class_ = Query

    def __init__(self, params: ALSAlgorithmParams):
        super().__init__(params)

    def train(self, ctx: RuntimeContext,
              pd: PreparedData) -> RecommendedUserModel:
        from incubator_predictionio_tpu.ops.als import als_train_implicit

        n = len(pd.user_bimap)
        seed = self.params.seed if self.params.seed is not None else ctx.seed
        state = als_train_implicit(
            pd.followers, pd.followed, pd.weights,
            n_users=n, n_items=n,
            rank=self.params.rank, iterations=self.params.num_iterations,
            l2=self.params.lambda_, seed=seed,
        )
        # the reference serves from the followed-side ("product") factors
        # (ALSAlgorithm.scala:120-123); normalize once so serve-time cosine
        # sums are a single matvec
        feats = np.asarray(state.item_factors, np.float32)
        norms = np.linalg.norm(feats, axis=1, keepdims=True)
        feats = np.where(norms > 0, feats / np.maximum(norms, 1e-30), 0.0)
        return RecommendedUserModel(
            user_features=feats, user_bimap=pd.user_bimap)

    def prepare_model(self, ctx, model: RecommendedUserModel
                      ) -> RecommendedUserModel:
        import jax

        return dataclasses.replace(
            model, user_features=jax.device_put(
                np.asarray(model.user_features)))

    def predict(self, model: RecommendedUserModel,
                query: Query) -> PredictedResult:
        query_idx = [
            model.user_bimap[u] for u in query.users
            if u in model.user_bimap
        ]
        if not query_idx:
            logger.info("no feature vectors for query users %s", query.users)
            return PredictedResult(similar_user_scores=())
        n = len(model.user_bimap)
        # candidate mask: never recommend the query users back; then
        # white/blacklist (ALSAlgorithm.scala isCandidateSimilarUser)
        mask = np.ones(n, bool)
        mask[np.asarray(query_idx, np.int64)] = False
        if query.white_list is not None:
            allowed = np.zeros(n, bool)
            for u in query.white_list:
                idx = model.user_bimap.get(u)
                if idx is not None:
                    allowed[idx] = True
            mask &= allowed
        if query.black_list:
            for u in query.black_list:
                idx = model.user_bimap.get(u)
                if idx is not None:
                    mask[idx] = False
        k = min(query.num, n)

        from incubator_predictionio_tpu.ops.host_serving import (
            host_arrays,
            host_top_k,
        )
        host = host_arrays(model, "user_features")
        rows = np.asarray(query_idx, np.int32)
        if host is not None:
            feats = host[0]
            qvec = feats[rows].sum(axis=0)
            top_s, top_i = host_top_k(feats @ qvec, k, allowed_mask=mask)
        else:
            import jax.numpy as jnp

            from incubator_predictionio_tpu.ops.topk import score_and_top_k

            feats = jnp.asarray(model.user_features)
            qvec = feats[jnp.asarray(rows)].sum(axis=0)
            packed = np.asarray(score_and_top_k(
                qvec, feats, k, allowed_mask=jnp.asarray(mask)))
            top_s, top_i = packed[0], packed[1].astype(np.int64)
        inv = model.user_bimap.inverse
        out = []
        for s, i in zip(np.asarray(top_s), np.asarray(top_i)):
            if s <= 0:  # reference keeps strictly positive scores only
                continue
            out.append(SimilarUserScore(user=inv[int(i)], score=float(s)))
        return PredictedResult(similar_user_scores=tuple(out))


class RecommendedUserEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            RecommendedUserDataSource,
            RecommendedUserPreparator,
            {"als": RecommendedUserAlgorithm},
            FirstServing,
        )
