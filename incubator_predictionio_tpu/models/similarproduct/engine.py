"""Similar-product engine: implicit ALS factors, item-to-item cosine ranking.

Reference parity (examples/scala-parallel-similarproduct/multi/):

- ``Query(items, num, categories?, whiteList?, blackList?)`` /
  ``PredictedResult(itemScores)`` (Engine.scala:23-38).
- DataSource reads ``view`` (and the multi variant's ``like``/``dislike``)
  events user→item plus ``$set`` item properties with categories
  (DataSource.scala).
- ALSAlgorithm trains ``ALS.trainImplicit`` on view counts
  (ALSAlgorithm.scala:147) — here ops.als_train_implicit; similarity is
  cosine between item factors, query = average of the query items' vectors
  (ALSAlgorithm.scala predict), ranked on-device, query items excluded.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator,
    Serving,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    items: Tuple[str, ...]
    num: int
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    __camel_case__ = True

    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True

    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str
    weight: float


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None
    #: event name -> implicit weight (the multi variant weighs likes > views)
    event_weights: Tuple[Tuple[str, float], ...] = (("view", 1.0), ("like", 3.0))


@dataclasses.dataclass
class TrainingData:
    views: Optional[List[ViewEvent]] = None   # fixture/legacy form
    item_categories: Dict[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    interactions: Optional[Interactions] = None  # columnar ingest form

    def __len__(self) -> int:
        if self.interactions is not None:
            return len(self.interactions)
        return len(self.views or [])

    def sanity_check(self) -> None:
        if not len(self):
            raise ValueError("TrainingData has no view events")


class SimilarProductDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        weights = dict(self.params.event_weights)
        inter = EventStore.interactions(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=tuple(weights),
            event_values={k: float(v) for k, v in weights.items()},
        )
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="item",
        )
        cats = {
            item: tuple(str(c) for c in (pm.opt("categories", list) or ()))
            for item, pm in props.items()
        }
        return TrainingData(interactions=inter, item_categories=cats)


@dataclasses.dataclass
class PreparedData:
    users: np.ndarray
    items: np.ndarray
    weights: np.ndarray
    user_bimap: BiMap
    item_bimap: BiMap
    item_categories: Dict[str, Tuple[str, ...]]


class SimilarProductPreparator(Preparator):
    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        if td.interactions is not None:
            return self._prepare_columnar(td)
        user_bimap = BiMap.string_int(v.user for v in td.views)
        item_bimap = BiMap.string_int(v.item for v in td.views)
        # sum repeated (user, item) weights — repeated views add confidence
        agg: Dict[Tuple[int, int], float] = {}
        for v in td.views:
            key = (user_bimap[v.user], item_bimap[v.item])
            agg[key] = agg.get(key, 0.0) + v.weight
        coo = np.array([(u, i, w) for (u, i), w in agg.items()],
                       np.float64).reshape(-1, 3)
        return PreparedData(
            users=coo[:, 0].astype(np.int32),
            items=coo[:, 1].astype(np.int32),
            weights=coo[:, 2].astype(np.float32),
            user_bimap=user_bimap,
            item_bimap=item_bimap,
            item_categories=td.item_categories,
        )

    def _prepare_columnar(self, td: TrainingData) -> PreparedData:
        """Vectorized weight summation: np.unique over packed (user, item)
        keys + np.add.at accumulation — repeated views add confidence with
        no Python loop over triples."""
        inter = td.interactions
        n_items = max(len(inter.item_ids), 1)
        keys = inter.user_idx.astype(np.int64) * n_items \
            + inter.item_idx.astype(np.int64)
        uniq, inverse = np.unique(keys, return_inverse=True)
        sums = np.zeros(len(uniq), np.float64)
        np.add.at(sums, inverse, inter.values.astype(np.float64))
        return PreparedData(
            users=(uniq // n_items).astype(np.int32),
            items=(uniq % n_items).astype(np.int32),
            weights=sums.astype(np.float32),
            user_bimap=BiMap({u: i for i, u in enumerate(inter.user_ids)}),
            item_bimap=BiMap({t: i for i, t in enumerate(inter.item_ids)}),
            item_categories=td.item_categories,
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    __camel_case__ = True

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None


@dataclasses.dataclass
class SimilarProductModel:
    #: unit-normalized item factors [I, K] — cosine becomes a dot product
    item_factors_norm: Any
    item_bimap: BiMap
    item_categories: Dict[str, Tuple[str, ...]]
    #: frozen USER factors + index (speed layer): a brand-new item's
    #: factor row is one regularized solve of its view events against
    #: these — the item-side fold-in. None on pre-speed checkpoints
    #: (restored models degrade to no overlay, never to an error).
    user_factors: Any = None
    user_bimap: Optional[BiMap] = None


class SimilarProductAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams
    query_class_ = Query

    def __init__(self, params: ALSAlgorithmParams = ALSAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> SimilarProductModel:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.als import als_train_implicit
        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        seed = self.params.seed if self.params.seed is not None else ctx.seed
        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        placement = placement_for_ctx(ctx, n_users, n_items)
        if placement is not None:
            # mesh-sharded implicit training (ALX layout): both tables
            # row-sharded, each device solves its own rows (ops/als.py
            # als_train_placed); model factors are unplaced for storage
            from incubator_predictionio_tpu.ops.als import als_train_placed

            state = placement.unplace_state(als_train_placed(
                pd.users, pd.items, pd.weights,
                n_users=n_users, n_items=n_items, placement=placement,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_, alpha=self.params.alpha,
                seed=seed, implicit=True))
        else:
            state = als_train_implicit(
                pd.users, pd.items, pd.weights,
                n_users=n_users, n_items=n_items,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_, alpha=self.params.alpha,
                seed=seed,
            )
        factors = state.item_factors
        norm = jnp.linalg.norm(factors, axis=1, keepdims=True)
        factors_norm = factors / jnp.maximum(norm, 1e-9)
        model = SimilarProductModel(
            item_factors_norm=factors_norm,
            item_bimap=pd.item_bimap,
            item_categories=pd.item_categories,
            user_factors=np.asarray(state.user_factors),
            user_bimap=pd.user_bimap,
        )
        self._refresh_mips_index(model)
        return model

    def _refresh_mips_index(self, model: SimilarProductModel) -> None:
        """Two-stage MIPS index over the UNIT-NORMALIZED serving table
        (ops/mips.py) — cosine ranking is inner product on this table,
        so the same coarse-scan + exact-rerank path serves it. Always a
        full rebuild: normalization rescales every row each retrain, so
        there is no O(delta) splice to keep honest here. Gated by
        PIO_SERVE_MIPS; never fatal."""
        from incubator_predictionio_tpu.ops import mips

        n_items = len(model.item_bimap)
        if not mips.build_enabled(n_items):
            return
        try:
            mips.build_index(model.item_factors_norm, n_items,
                             seed=self.params.seed or 0,
                             probe_recall=True,
                             engine="similarproduct")
        except Exception:  # index is an optimization, never a failure
            logger.exception("MIPS index build failed; similarproduct "
                             "serving stays exhaustive")

    def train_with_previous(
        self, ctx: RuntimeContext, pd: PreparedData, prev_model: Any
    ) -> SimilarProductModel:
        """Continuation retrain: the stored model only keeps the
        unit-normalized item factors, so the warm start seeds the ITEM
        side from them (scale is recovered within the first sweep — the
        user half-sweep solves against whatever item factors exist) and
        the user side starts fresh. Incompatible priors (rank change,
        rebuilt item id space) fall back to a cold train."""
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.als import ALSState

        prev_items = (np.asarray(prev_model.item_factors_norm)
                      if isinstance(prev_model, SimilarProductModel)
                      else None)
        if (prev_items is None or prev_items.ndim != 2
                or prev_items.shape[1] != self.params.rank
                or not prev_model.item_bimap.is_index_prefix_of(
                    pd.item_bimap)):
            return self.train(ctx, pd)
        from incubator_predictionio_tpu.ops.retrain import als_retrain

        from incubator_predictionio_tpu.models.recommendation.engine import (
            _plan_key,
        )

        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        seed = self.params.seed if self.params.seed is not None else ctx.seed
        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        placement = placement_for_ctx(ctx, n_users, n_items)
        stats: Dict[str, Any] = {}
        state = als_retrain(
            pd.users, pd.items, pd.weights,
            n_users=n_users, n_items=n_items,
            rank=self.params.rank, iterations=self.params.num_iterations,
            l2=self.params.lambda_, alpha=self.params.alpha, seed=seed,
            implicit=True, plan_key=_plan_key("simprod", pd),
            prev_state=ALSState(
                user_factors=np.zeros((0, self.params.rank), np.float32),
                item_factors=prev_items),
            stats=stats, placement=placement)
        if placement is not None:
            state = placement.unplace_state(state)
        logger.info("similarproduct continuation retrain: %s sweeps "
                    "(mode=%s)", stats.get("sweeps_used"),
                    stats.get("mode"))
        factors = state.item_factors
        norm = jnp.linalg.norm(factors, axis=1, keepdims=True)
        model = SimilarProductModel(
            item_factors_norm=factors / jnp.maximum(norm, 1e-9),
            item_bimap=pd.item_bimap,
            item_categories=pd.item_categories,
            user_factors=np.asarray(state.user_factors),
            user_bimap=pd.user_bimap,
        )
        self._refresh_mips_index(model)
        return model

    def prepare_model(self, ctx, model: SimilarProductModel) -> SimilarProductModel:
        import jax

        from incubator_predictionio_tpu.ops import mips

        prev_table = model.item_factors_norm
        model = dataclasses.replace(
            model,
            item_factors_norm=jax.device_put(
                np.asarray(model.item_factors_norm)
            ),
        )
        # deploy-time index: adopt a just-trained one onto the
        # re-device_put table (same values, new object); restored
        # models build fresh
        if mips.adopt_index(prev_table,
                            model.item_factors_norm) is None:
            self._refresh_mips_index(model)
        return model

    def make_speed_overlay(self, model: SimilarProductModel, app_name,
                           channel_name, data_source_params=None):
        """ITEM-side fold-in: a brand-new (or dirty) item's factor row is
        solved from its view/like events against the FROZEN user factors
        — the symmetric orientation of the same ALX row solve — then
        unit-normalized so cosine ranking works unchanged. Models restored
        from pre-speed checkpoints (no stored user factors) get no
        overlay."""
        user_factors = getattr(model, "user_factors", None)
        user_bimap = getattr(model, "user_bimap", None)
        if app_name is None or user_factors is None or user_bimap is None:
            return None
        from incubator_predictionio_tpu.speed.overlay import (
            SpeedOverlay,
            SpeedOverlayConfig,
        )

        weights = dict(getattr(data_source_params, "event_weights", ())
                       or (("view", 1.0), ("like", 3.0)))

        def normalize(vec: np.ndarray) -> np.ndarray:
            n = float(np.linalg.norm(vec))
            return vec / max(n, 1e-9)

        item_bimap = model.item_bimap
        serving_table = getattr(model, "item_factors_norm", None)
        #: virtual tail id <-> item key, for results the base bimap has
        #: never heard of (brand-new items published by the overlay);
        #: the by-key direction excludes a query item's own tail entry
        virtual_ids = self._mips_virtual_ids = {}
        virtual_by_key = self._mips_virtual_by_key = {}

        def index_sink(keys, vecs):
            # two-stage MIPS seam: item-side fold-ins enter the serving
            # index the moment they publish — known rows re-quantize in
            # place + override exactly via the tail, unknown (brand-new)
            # items ride the tail under virtual ids until the next
            # rebuild folds them in (predict resolves them through
            # _mips_virtual_ids). No-op unless an index is registered
            # for the serving table.
            from incubator_predictionio_tpu.ops import mips

            if (serving_table is None
                    or mips.index_for(serving_table) is None):
                return
            rows = [item_bimap.get(k, -1) for k in keys]
            gids = mips.publish_rows(serving_table, np.stack(vecs),
                                     rows=rows)
            if gids is not None:
                for key, row, gid in zip(keys, rows, gids):
                    if row < 0:
                        virtual_ids[int(gid)] = key
                        virtual_by_key[key] = int(gid)

        return SpeedOverlay(
            SpeedOverlayConfig(
                app_name=app_name, channel_name=channel_name,
                engine="similarproduct",
                entity_type="user", target_entity_type="item",
                event_names=tuple(weights),
                event_values={k: float(v) for k, v in weights.items()},
                key_side="target",
                l2=self.params.lambda_, implicit=True,
                alpha=self.params.alpha,
                transform=normalize,
            ),
            other_factors=np.asarray(user_factors),
            other_index=user_bimap,
            key_index=model.item_bimap,
            index_sink=index_sink,
        )

    def _allowed_mask(self, model: SimilarProductModel,
                      query: Query) -> np.ndarray:
        # always materialized: the query items themselves are always excluded
        # (ALSAlgorithm.scala), so there is no "no filter" case
        n = len(model.item_bimap)
        mask = np.ones(n, bool)
        if query.categories:
            wanted = set(query.categories)
            for item, idx in model.item_bimap.items():
                if not wanted.intersection(model.item_categories.get(item, ())):
                    mask[idx] = False
        if query.white_list:
            allowed = {
                model.item_bimap[i] for i in query.white_list
                if i in model.item_bimap
            }
            for idx in range(n):
                if idx not in allowed:
                    mask[idx] = False
        if query.black_list:
            for item in query.black_list:
                idx = model.item_bimap.get(item)
                if idx is not None:
                    mask[idx] = False
        for item in query.items:
            idx = model.item_bimap.get(item)
            if idx is not None:
                mask[idx] = False
        return mask

    def warmup(self, model: SimilarProductModel, max_batch: int = 1) -> None:
        """Pre-compile the serving path (core/base.py Algorithm.warmup):
        one real predict compiles whichever path this model size uses
        (host mirror = free, device top-k = the XLA compile to pre-pay)."""
        first = next(iter(model.item_bimap), None)
        if first is not None:
            self.predict(model, Query(items=(str(first),), num=10))

    def predict(self, model: SimilarProductModel, query: Query) -> PredictedResult:
        from incubator_predictionio_tpu.ops.host_serving import (
            host_arrays,
            host_top_k,
        )

        # speed layer: query items the model never trained on (or whose
        # events are newer than the deployed instance) contribute their
        # FOLDED-IN unit vectors to the query average — a just-listed
        # product gets similar-product results from its first views
        ov = self.speed_overlay
        indices: list = []
        extra_vecs: list = []
        for item in query.items:
            vec = ov.lookup(item) if ov is not None else None
            if vec is not None:
                extra_vecs.append(np.asarray(vec, np.float32))
            elif item in model.item_bimap:
                indices.append(model.item_bimap[item])
        if not indices and not extra_vecs:
            return PredictedResult(item_scores=())
        mask = self._allowed_mask(model, query)
        k = min(query.num, len(model.item_bimap))
        host = host_arrays(model, "item_factors_norm")
        if host is not None:
            (factors,) = host
            parts = ([factors[np.asarray(indices, np.int32)]]
                     if indices else []) + (
                [np.stack(extra_vecs)] if extra_vecs else [])
            query_vec = np.concatenate(parts).mean(axis=0)
            query_vec = query_vec / max(float(np.linalg.norm(query_vec)),
                                        1e-9)
            top_s, top_i = host_top_k(factors @ query_vec, k,
                                      allowed_mask=mask)
        else:
            import jax.numpy as jnp

            from incubator_predictionio_tpu.ops.topk import (
                pad_exclude,
                score_and_top_k,
            )

            factors = jnp.asarray(model.item_factors_norm)
            if indices:
                query_vec = factors[
                    jnp.asarray(indices, jnp.int32)].sum(axis=0)
            else:
                query_vec = jnp.zeros(factors.shape[1], jnp.float32)
            if extra_vecs:
                query_vec = query_vec + jnp.asarray(
                    np.sum(extra_vecs, axis=0, dtype=np.float32))
            query_vec = query_vec / (len(indices) + len(extra_vecs))
            qnorm = jnp.linalg.norm(query_vec)
            query_vec = query_vec / jnp.maximum(qnorm, 1e-9)
            # cosine ranking through the top-k AUTO-ROUTER (the
            # pre-normalized table makes it an inner product): plain
            # queries express the query-item exclusion as a pow2-padded
            # id list so a registered two-stage MIPS index can serve
            # them; filtered queries keep the mask (→ exhaustive, the
            # router's designed fallback)
            if (query.categories or query.white_list
                    or query.black_list):
                packed = np.asarray(score_and_top_k(
                    query_vec, factors, k=k,
                    allowed_mask=jnp.asarray(mask)))
            else:
                virtual_by_key = getattr(self, "_mips_virtual_by_key",
                                         None) or {}
                # query items exclude by id — base rows AND the virtual
                # tail ids of overlay-published query items (else a
                # just-folded item comes back as its own best match)
                seen = [model.item_bimap[i] for i in query.items
                        if i in model.item_bimap]
                seen += [virtual_by_key[i] for i in query.items
                         if i in virtual_by_key]
                packed = np.asarray(score_and_top_k(
                    query_vec, factors, k=k, exclude=pad_exclude(seen)))
            top_s, top_i = packed[0], packed[1]
        inv = model.item_bimap.inverse
        n_known = len(model.item_bimap)
        virtual_ids = getattr(self, "_mips_virtual_ids", None) or {}
        out = []
        for s, i in zip(np.asarray(top_s), np.asarray(top_i)):
            if s <= -1e37:
                continue
            # ids past the base bimap are overlay-published brand-new
            # items served from the index's exact tail
            item = (inv[int(i)] if int(i) < n_known
                    else virtual_ids.get(int(i)))
            if item is None:
                continue
            out.append(ItemScore(item=item, score=float(s)))
        return PredictedResult(item_scores=tuple(out))


@dataclasses.dataclass(frozen=True)
class DIMSUMAlgorithmParams(Params):
    __camel_case__ = True

    #: minimum cosine similarity kept (columnSimilarities(threshold))
    threshold: float = 0.1
    #: neighbors stored per item — the model is [I, topN], not [I, I]
    top_n: int = 100


@dataclasses.dataclass
class DIMSUMModel:
    sim_scores: np.ndarray    # [I, T] f32, 0 where absent
    sim_indices: np.ndarray   # [I, T] int32
    item_bimap: BiMap
    item_categories: Dict[str, Tuple[str, ...]]


class DIMSUMAlgorithm(Algorithm):
    """Exact item-item cosine similarity (the similarproduct-dimsum
    variant, examples/experimental/scala-parallel-similarproduct-dimsum/
    DIMSUMAlgorithm.scala:118-145 — its Spark columnSimilarities sampling
    replaced by the exact MXU Gram, ops/dimsum.py). Prediction sums
    similarity over the query items (indexScores groupBy-sum, :168)."""

    params_class = DIMSUMAlgorithmParams
    query_class_ = Query

    def __init__(self,
                 params: DIMSUMAlgorithmParams = DIMSUMAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> DIMSUMModel:
        from incubator_predictionio_tpu.ops.dimsum import column_cosine_topk

        scores, indices = column_cosine_topk(
            pd.users, pd.items, pd.weights,
            n_items=len(pd.item_bimap),
            threshold=self.params.threshold,
            top_n=self.params.top_n,
        )
        return DIMSUMModel(
            sim_scores=np.asarray(scores),
            sim_indices=np.asarray(indices),
            item_bimap=pd.item_bimap,
            item_categories=pd.item_categories,
        )

    # filters are identical to the ALS variant's (same Query contract)
    _allowed_mask = SimilarProductAlgorithm._allowed_mask

    def predict(self, model: DIMSUMModel, query: Query) -> PredictedResult:
        indices = [
            model.item_bimap[i] for i in query.items if i in model.item_bimap
        ]
        if not indices:
            return PredictedResult(item_scores=())
        n = len(model.item_bimap)
        acc = np.zeros(n, np.float32)
        for qi in indices:
            np.add.at(acc, model.sim_indices[qi], model.sim_scores[qi])
        mask = self._allowed_mask(model, query)
        acc[~mask] = 0.0
        k = min(query.num, n)
        top = np.argsort(-acc, kind="stable")[:k]
        inv = model.item_bimap.inverse
        return PredictedResult(item_scores=tuple(
            ItemScore(item=inv[int(i)], score=float(acc[i]))
            for i in top if acc[i] > 0.0
        ))


class SimilarProductEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            SimilarProductDataSource,
            SimilarProductPreparator,
            {"als": SimilarProductAlgorithm, "dimsum": DIMSUMAlgorithm},
            FirstServing,
        )
