"""Similar-product template (implicit-feedback ALS, item-to-item queries).

Parity: examples/scala-parallel-similarproduct/ (multi variant capabilities:
view + like events, category/white/blacklist filters).
"""

from incubator_predictionio_tpu.models.similarproduct.engine import (
    ALSAlgorithmParams,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    SimilarProductEngine,
)

__all__ = [
    "ALSAlgorithmParams", "DataSourceParams", "ItemScore", "PredictedResult",
    "Query", "SimilarProductEngine",
]
