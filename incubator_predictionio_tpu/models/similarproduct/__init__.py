"""Similar-product template (implicit-feedback ALS, item-to-item queries).

Parity: examples/scala-parallel-similarproduct/ (multi variant capabilities:
view + like events, category/white/blacklist filters; the recommended-user
variant lives in .recommended_user).
"""

from incubator_predictionio_tpu.models.similarproduct.engine import (
    ALSAlgorithmParams,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    SimilarProductEngine,
)
from incubator_predictionio_tpu.models.similarproduct.recommended_user import (
    RecommendedUserEngine,
)

__all__ = [
    "ALSAlgorithmParams", "DataSourceParams", "ItemScore", "PredictedResult",
    "Query", "SimilarProductEngine", "RecommendedUserEngine",
]
