"""Classification template (NaiveBayes + LogisticRegression).

Parity: examples/scala-parallel-classification/ (add-algorithm and
custom-attributes variants).
"""

from incubator_predictionio_tpu.models.classification.engine import (
    ClassificationDataSource,
    ClassificationEngine,
    ClassificationPreparator,
    DataSourceParams,
    FirstServing,
    LabeledPoint,
    LogRegAlgorithm,
    LogRegAlgorithmParams,
    NaiveBayesAlgorithm,
    NaiveBayesAlgorithmParams,
    PredictedResult,
    Query,
    TrainingData,
)

__all__ = [
    "ClassificationDataSource", "ClassificationEngine",
    "ClassificationPreparator", "DataSourceParams", "FirstServing",
    "LabeledPoint", "LogRegAlgorithm", "LogRegAlgorithmParams",
    "NaiveBayesAlgorithm", "NaiveBayesAlgorithmParams", "PredictedResult",
    "Query", "TrainingData",
]
