"""Classification engine: entity attributes → NB / LogReg on device.

Reference parity (examples/scala-parallel-classification/add-algorithm/):

- DataSource aggregates ``user`` entity properties requiring
  ``plan, attr0, attr1, attr2`` (DataSource.scala:46-71) into LabeledPoints;
  attribute names are configurable (custom-attributes variant reads
  ``featureA..D`` — DataSourceParams.attrs covers both).
- ``Query(attr0, attr1, attr2)`` / ``PredictedResult(label)``
  (Engine.scala:23-31).
- Two algorithms registered under one engine ("naive" + "logreg"), the
  add-algorithm variant's multi-algo engine.json shape (its
  algorithms list pairs NaiveBayes with a second model).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator,
    Serving,
)
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    features: Tuple[float, ...]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True

    label: float


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    label: float
    features: Tuple[float, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    entity_type: str = "user"
    label_attr: str = "plan"
    attrs: Tuple[str, ...] = ("attr0", "attr1", "attr2")
    eval_k: int = 0


@dataclasses.dataclass
class TrainingData:
    labeled_points: List[LabeledPoint]

    def sanity_check(self) -> None:
        if not self.labeled_points:
            raise ValueError("TrainingData has no labeled points")


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    fold: int


class ClassificationDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_points(self) -> List[LabeledPoint]:
        required = [self.params.label_attr, *self.params.attrs]
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            required=required,
        )
        points = []
        for _entity, pm in sorted(props.items()):
            points.append(LabeledPoint(
                label=pm.get(self.params.label_attr, float),
                features=tuple(pm.get(a, float) for a in self.params.attrs),
            ))
        return points

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        return TrainingData(self._read_points())

    def read_eval(self, ctx: RuntimeContext):
        from incubator_predictionio_tpu.e2 import split_data

        if self.params.eval_k <= 0:
            return []
        points = self._read_points()
        return [
            (TrainingData(train), EvalInfo(fold), qa)
            for train, fold, qa in split_data(
                self.params.eval_k, points,
                lambda p: (Query(features=p.features), p.label),
            )
        ]


@dataclasses.dataclass
class PreparedData:
    features: np.ndarray       # [N, D] f32
    labels: np.ndarray         # [N] int32 class ids
    label_values: Tuple[float, ...]  # class id -> original label value


class ClassificationPreparator(Preparator):
    """Labels (arbitrary doubles in the reference) index to dense class ids
    for the device; the map rides in the model to translate back."""

    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        values = sorted({p.label for p in td.labeled_points})
        index = {v: i for i, v in enumerate(values)}
        return PreparedData(
            features=np.array([p.features for p in td.labeled_points],
                              np.float32),
            labels=np.array([index[p.label] for p in td.labeled_points],
                            np.int32),
            label_values=tuple(values),
        )


@dataclasses.dataclass(frozen=True)
class NaiveBayesAlgorithmParams(Params):
    __camel_case__ = True  # accepts {"lambda": ...}

    lambda_: float = 1.0


@dataclasses.dataclass
class NBModel:
    nb: Any
    label_values: Tuple[float, ...]


class NaiveBayesAlgorithm(Algorithm):
    """NaiveBayesAlgorithm.scala of the template → ops.nb."""

    params_class = NaiveBayesAlgorithmParams
    query_class_ = Query

    def __init__(self, params: NaiveBayesAlgorithmParams = NaiveBayesAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> NBModel:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.nb import nb_fit

        model = nb_fit(
            jnp.asarray(pd.features), jnp.asarray(pd.labels),
            n_classes=len(pd.label_values), lambda_=self.params.lambda_,
        )
        return NBModel(nb=model, label_values=pd.label_values)

    def predict(self, model: NBModel, query: Query) -> PredictedResult:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.nb import nb_predict

        cls = int(nb_predict(
            model.nb, jnp.asarray([query.features], jnp.float32)
        )[0])
        return PredictedResult(label=model.label_values[cls])


@dataclasses.dataclass(frozen=True)
class LogRegAlgorithmParams(Params):
    __camel_case__ = True

    steps: int = 300
    learning_rate: float = 0.1
    l2: float = 1e-4


@dataclasses.dataclass
class LogRegModelWrap:
    lr: Any
    label_values: Tuple[float, ...]


class LogRegAlgorithm(Algorithm):
    """The add-algorithm second model → optax logreg (ops.logreg)."""

    params_class = LogRegAlgorithmParams
    query_class_ = Query

    def __init__(self, params: LogRegAlgorithmParams = LogRegAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> LogRegModelWrap:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.logreg import logreg_fit

        model = logreg_fit(
            jnp.asarray(pd.features), jnp.asarray(pd.labels),
            n_classes=len(pd.label_values),
            steps=self.params.steps,
            learning_rate=self.params.learning_rate,
            l2=self.params.l2,
        )
        return LogRegModelWrap(lr=model, label_values=pd.label_values)

    def predict(self, model: LogRegModelWrap, query: Query) -> PredictedResult:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.logreg import logreg_predict

        cls = int(logreg_predict(
            model.lr, jnp.asarray([query.features], jnp.float32)
        )[0])
        return PredictedResult(label=model.label_values[cls])


class AccuracyMetric(AverageMetric):
    """The template's evaluation metric (the reference's evaluation variant
    scores exact-label accuracy)."""

    def calculate_qpa(self, q: Query, p: PredictedResult, a: float) -> float:
        return 1.0 if p.label == a else 0.0


class ClassificationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ClassificationDataSource,
            ClassificationPreparator,
            {"naive": NaiveBayesAlgorithm, "logreg": LogRegAlgorithm},
            FirstServing,
        )
