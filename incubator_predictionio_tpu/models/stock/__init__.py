from incubator_predictionio_tpu.models.stock.engine import (
    BacktestingEvaluator,
    BacktestingParams,
    DataSourceParams,
    MomentumStrategyParams,
    Prediction,
    Query,
    RegressionStrategyParams,
    StockEngine,
)

__all__ = [
    "BacktestingEvaluator", "BacktestingParams", "DataSourceParams",
    "MomentumStrategyParams", "Prediction", "Query",
    "RegressionStrategyParams", "StockEngine",
]
