"""Stock backtesting engine (the scala-stock experimental template).

Reference parity (examples/experimental/scala-stock/src/main/scala/):

- a price panel over (time × tickers) with an active mask
  (Data.scala RawData: _price/_active arrays),
- strategies scoring every ticker each day: ``empty``
  (Algorithm.scala EmptyStrategy), ``momentum`` (ShiftsIndicator-style
  windowed log return, Indicators.scala:40), and ``regression``
  (RegressionStrategy.scala: per-ticker linear regression of the
  next-day return on shift-return indicators — here ALL tickers fit in
  one batched ``vmap`` of the normal-equation solve, ops/linreg.py,
  instead of a per-ticker breeze loop),
- a backtesting evaluator (BackTestingMetrics.scala): daily enter/exit
  by score thresholds under a position cap, NAV tracking, and an
  OverallStat of return/vol/Sharpe.

Prices live in the event store as ``price`` events on ``ticker``
entities (``properties.price``, event time = the trading day) — the
YahooDataSource role without the HTTP fetch (zero-egress image).
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    Params,
)
from incubator_predictionio_tpu.core.base import Evaluator
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    """Score all tickers as of time index ``idx`` (Data.scala QueryDate)."""

    __camel_case__ = True

    idx: int


@dataclasses.dataclass(frozen=True)
class Prediction:
    __camel_case__ = True

    #: ticker → strategy score (Data.scala Prediction's HashMap)
    scores: Dict[str, float]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    entity_type: str = "ticker"
    event_name: str = "price"
    price_attr: str = "price"
    market_ticker: str = "SPY"
    #: first index handed to eval queries + how many eval days
    eval_from_idx: int = 30
    eval_days: int = 0


@dataclasses.dataclass
class TrainingData:
    prices: np.ndarray       # [T, N] f64, NaN where inactive
    active: np.ndarray       # [T, N] bool
    tickers: Tuple[str, ...]
    times: Tuple[Any, ...]   # [T] event datetimes (trading days)
    market_ticker: str

    def sanity_check(self) -> None:
        if self.prices.size == 0:
            raise ValueError("TrainingData has no prices")


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    from_idx: int
    #: the panel the queries index into — rides with the eval set so the
    #: backtesting evaluator can simulate against real prices
    td: Optional["TrainingData"] = None


class StockDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        by_day: Dict[Any, Dict[str, float]] = {}
        tickers: set = set()
        for ev in EventStore.find(
                app_name=self.params.app_name,
                entity_type=self.params.entity_type,
                event_names=(self.params.event_name,)):
            price = ev.properties.get_or_else(self.params.price_attr, None)
            if not isinstance(price, (int, float)) or isinstance(price, bool):
                continue
            day = ev.event_time.date()
            by_day.setdefault(day, {})[ev.entity_id] = float(price)
            tickers.add(ev.entity_id)
        days = sorted(by_day)
        names = sorted(tickers)
        col = {t: j for j, t in enumerate(names)}
        prices = np.full((len(days), len(names)), np.nan)
        for i, day in enumerate(days):
            for t, p in by_day[day].items():
                prices[i, col[t]] = p
        return TrainingData(
            prices=prices,
            active=~np.isnan(prices),
            tickers=tuple(names),
            times=tuple(days),
            market_ticker=self.params.market_ticker,
        )

    def read_eval(self, ctx: RuntimeContext):
        if self.params.eval_days <= 0:
            return []
        td = self.read_training(ctx)
        lo = self.params.eval_from_idx
        hi = min(len(td.times) - 1, lo + self.params.eval_days)
        qa = [(Query(idx=i), None) for i in range(lo, hi)]
        return [(td, EvalInfo(from_idx=lo, td=td), qa)]


def _log_returns(prices: np.ndarray, period: int) -> np.ndarray:
    """log p_t − log p_{t−period}, 0 where undefined (ShiftsIndicator).

    Callers MUST also mask on activity at both endpoints: the NaN→1
    placeholder turns a missing endpoint into ±log(p) ≈ ±4.6 — two
    orders of magnitude above a real daily return."""
    logp = np.log(np.where(np.isnan(prices), 1.0, prices))
    out = np.zeros_like(logp)
    out[period:] = logp[period:] - logp[:-period]
    return out


def _row_log_returns(prices: np.ndarray, active: np.ndarray, i: int,
                     periods: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
    """Features for ONE day: ([N, F] shift returns, [N] validity) — the
    serving-path form, touching only the |periods|+1 rows it needs
    instead of re-deriving the whole [0..i] prefix per query."""
    n = prices.shape[1]
    feats = np.zeros((n, len(periods)))
    ok = active[i].copy()
    logp_i = np.log(np.where(active[i], prices[i], 1.0))
    for f, p in enumerate(periods):
        if i < p:
            ok[:] = False
            break
        ok &= active[i - p]
        logp_prev = np.log(np.where(active[i - p], prices[i - p], 1.0))
        feats[:, f] = logp_i - logp_prev
    return feats, ok


@dataclasses.dataclass
class StockModel:
    td: TrainingData
    #: [N, F+1] regression weights (intercept last); None for
    #: non-regression strategies
    weights: Optional[np.ndarray]
    params: Any


@dataclasses.dataclass(frozen=True)
class EmptyStrategyParams(Params):
    __camel_case__ = True


class EmptyStrategy(Algorithm):
    """Algorithm.scala EmptyStrategy: predicts nothing for every day."""

    params_class = EmptyStrategyParams
    query_class_ = Query

    def __init__(self, params: EmptyStrategyParams = EmptyStrategyParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, td: TrainingData) -> StockModel:
        return StockModel(td=td, weights=None, params=self.params)

    def predict(self, model: StockModel, query: Query) -> Prediction:
        return Prediction(scores={})


@dataclasses.dataclass(frozen=True)
class MomentumStrategyParams(Params):
    __camel_case__ = True

    window: int = 5


class MomentumStrategy(Algorithm):
    """Windowed log return per ticker — the ShiftsIndicator as a
    standalone strategy."""

    params_class = MomentumStrategyParams
    query_class_ = Query

    def __init__(self,
                 params: MomentumStrategyParams = MomentumStrategyParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, td: TrainingData) -> StockModel:
        return StockModel(td=td, weights=None, params=self.params)

    def predict(self, model: StockModel, query: Query) -> Prediction:
        td = model.td
        w = model.params.window
        i = query.idx
        if not 0 <= i < len(td.times) or i < w:
            return Prediction(scores={})
        feats, ok = _row_log_returns(td.prices, td.active, i, (w,))
        return Prediction(scores={
            t: float(feats[j, 0])
            for j, t in enumerate(td.tickers) if ok[j]
        })


@dataclasses.dataclass(frozen=True)
class RegressionStrategyParams(Params):
    __camel_case__ = True

    #: shift-return indicator periods (RegressionStrategy.scala's
    #: ShiftsIndicator set)
    periods: Tuple[int, ...] = (1, 5, 22)
    max_training_window: int = 250
    #: ridge keeps the solve conditioned when indicators are near-collinear
    #: (steady trends make every shift-return a multiple of the 1-day one)
    l2: float = 1e-4


class RegressionStrategy(Algorithm):
    """Per-ticker next-day-return regression on shift-return indicators.

    The reference fits one breeze regression per ticker in a Scala loop
    (RegressionStrategy.scala:regress); here every ticker's normal
    equations solve in ONE vmapped device call (ops/linreg.py)."""

    params_class = RegressionStrategyParams
    query_class_ = Query

    def __init__(
        self,
        params: RegressionStrategyParams = RegressionStrategyParams(),
    ):
        super().__init__(params)

    def _features(self, prices: np.ndarray) -> np.ndarray:
        # [T, N, F] indicator stack
        return np.stack(
            [_log_returns(prices, p) for p in self.params.periods], axis=-1)

    def train(self, ctx: RuntimeContext, td: TrainingData) -> StockModel:
        import jax
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.linreg import linreg_fit

        t_end = len(td.times)
        t_start = max(max(self.params.periods) + 1,
                      t_end - self.params.max_training_window)
        if t_end - t_start < len(self.params.periods) + 2:
            return StockModel(td=td, weights=None, params=self.params)
        feats = self._features(td.prices)              # [T, N, F]
        next_ret = np.zeros_like(td.prices)
        next_ret[:-1] = _log_returns(td.prices, 1)[1:]  # ret of t→t+1
        x = feats[t_start:t_end - 1]                    # [S, N, F]
        y = next_ret[t_start:t_end - 1]                 # [S, N]
        # a sample is valid only when every endpoint it touches is active:
        # the day itself, the NEXT day (the target), and each feature's
        # t−period day — otherwise the NaN placeholder injects ±log(p)
        # outliers two orders above real returns
        ok = (td.active[t_start:t_end - 1]
              & td.active[t_start + 1:t_end])
        for p in self.params.periods:
            ok = ok & td.active[t_start - p:t_end - 1 - p]
        ok = ok[..., None]
        x = np.where(ok, x, 0.0)
        y = np.where(ok[..., 0], y, 0.0)
        fit = jax.vmap(lambda xi, yi: linreg_fit(xi, yi, l2=self.params.l2))
        weights = fit(
            jnp.asarray(np.swapaxes(x, 0, 1), jnp.float32),  # [N, S, F]
            jnp.asarray(y.T, jnp.float32),                   # [N, S]
        )
        return StockModel(td=td, weights=np.asarray(weights),
                          params=self.params)

    def predict(self, model: StockModel, query: Query) -> Prediction:
        td = model.td
        i = query.idx
        if model.weights is None or not 0 <= i < len(td.times):
            return Prediction(scores={})
        feats, ok = _row_log_returns(td.prices, td.active, i,
                                     model.params.periods)
        aug = np.concatenate([feats, np.ones((feats.shape[0], 1))], axis=1)
        scores = (aug * model.weights).sum(axis=1)
        return Prediction(scores={
            t: float(scores[j])
            for j, t in enumerate(td.tickers) if ok[j]
        })


# ---------------------------------------------------------------------------
# Backtesting (BackTestingMetrics.scala)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BacktestingParams(Params):
    __camel_case__ = True

    enter_threshold: float = 0.0
    exit_threshold: float = 0.0
    max_positions: int = 1


@dataclasses.dataclass
class DailyStat:
    time: Any
    nav: float
    ret: float
    market: float
    position_count: int


@dataclasses.dataclass
class OverallStat:
    ret: float
    vol: float
    sharpe: float
    days: int


@dataclasses.dataclass
class BacktestingResult:
    daily: List[DailyStat]
    overall: OverallStat

    def to_one_liner(self) -> str:
        o = self.overall
        return (f"ret={o.ret:.4f} vol={o.vol:.4f} sharpe={o.sharpe:.2f} "
                f"days={o.days}")

    def to_jsonable(self) -> dict:
        return {
            "overall": dataclasses.asdict(self.overall),
            "daily": [
                {**dataclasses.asdict(d), "time": str(d.time)}
                for d in self.daily
            ],
        }

    def to_html(self) -> str:
        """BacktestingResult's NiceRendering role (the reference renders
        html.backtesting(); a NAV table serves the dashboard here)."""
        rows = "".join(
            f"<tr><td>{d.time}</td><td>{d.nav:.4f}</td>"
            f"<td>{d.ret:+.4%}</td><td>{d.market:+.4%}</td>"
            f"<td>{d.position_count}</td></tr>"
            for d in self.daily
        )
        o = self.overall
        return (
            f"<h3>Backtest: ret={o.ret:.2%} vol={o.vol:.2%} "
            f"sharpe={o.sharpe:.2f} over {o.days} days</h3>"
            "<table border=1><tr><th>date</th><th>NAV</th><th>ret</th>"
            f"<th>market</th><th>positions</th></tr>{rows}</table>"
        )


class BacktestingEvaluator(Evaluator):
    """Simulates the daily enter/exit book the reference's evaluator keeps
    (BackTestingMetrics.scala evaluateUnit/evaluateAll): scores ≥
    enterThreshold queue entries (best first, up to maxPositions), scores
    ≤ exitThreshold close positions, NAV compounds the equal-weighted
    next-day return of the held names."""

    def __init__(self, params: BacktestingParams = BacktestingParams()):
        super().__init__()
        self.params = params

    def _backtest(self, td: TrainingData,
                  day_preds: List[Tuple[int, Prediction]]) -> BacktestingResult:
        p = self.params
        positions: set = set()
        nav = 1.0
        daily: List[DailyStat] = []
        ret1 = np.zeros_like(td.prices)
        ret1[1:] = td.prices[1:] / td.prices[:-1] - 1.0  # NaN where gaps
        col = {t: j for j, t in enumerate(td.tickers)}
        mkt = col.get(td.market_ticker)
        for idx, pred in sorted(day_preds, key=lambda kv: kv[0]):
            if idx + 1 >= len(td.times):
                break
            ranked = sorted(pred.scores.items(), key=lambda kv: -kv[1])
            for t, s in ranked:
                if s <= p.exit_threshold:
                    positions.discard(t)
            for t, s in ranked:
                if s >= p.enter_threshold and len(positions) < p.max_positions:
                    positions.add(t)
            rets = [
                float(ret1[idx + 1, col[t]]) for t in positions
                if t in col and np.isfinite(ret1[idx + 1, col[t]])
            ]
            day_ret = float(np.mean(rets)) if rets else 0.0
            nav *= 1.0 + day_ret
            market = (float(ret1[idx + 1, mkt])
                      if mkt is not None
                      and np.isfinite(ret1[idx + 1, mkt]) else 0.0)
            daily.append(DailyStat(
                time=td.times[idx], nav=nav, ret=day_ret, market=market,
                position_count=len(positions)))
        rets = np.array([d.ret for d in daily]) if daily else np.zeros(1)
        vol = float(rets.std() * math.sqrt(252))
        mean = float(rets.mean() * 252)
        overall = OverallStat(
            ret=nav - 1.0,
            vol=vol,
            sharpe=mean / vol if vol > 0 else 0.0,
            days=len(daily),
        )
        return BacktestingResult(daily=daily, overall=overall)

    def evaluate(self, ctx: RuntimeContext, evaluation: Any,
                 engine_eval_data_set: Sequence[Tuple[Any, Any]],
                 params: Any = None) -> BacktestingResult:
        best: Optional[BacktestingResult] = None
        for _engine_params, eval_data in engine_eval_data_set:
            for info, qpas in eval_data:
                if info.td is None:
                    raise ValueError(
                        "EvalInfo.td missing — use StockDataSource's "
                        "read_eval")
                day_preds = [(q.idx, pr) for q, pr, _a in qpas]
                result = self._backtest(info.td, day_preds)
                if best is None or result.overall.ret > best.overall.ret:
                    best = result
        if best is None:
            raise ValueError("no evaluation data to backtest")
        return best


class StockEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            StockDataSource,
            IdentityPreparator,
            {
                "empty": EmptyStrategy,
                "momentum": MomentumStrategy,
                "regression": RegressionStrategy,
            },
            FirstServing,
        )
