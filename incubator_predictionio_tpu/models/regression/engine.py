"""Regression engine: labeled feature vectors → linear model on device.

Reference parity (the one mainline algorithm family previously missing —
examples/experimental/scala-parallel-regression/Run.scala and
scala-local-regression/Run.scala):

- DataSource reads labeled points. The reference examples read a text
  file of ``label f0 f1 ...`` rows; here points live in the event store
  as entity properties (``label`` + ``features``), with a file reader
  kept for the examples' lr_data.txt format. k-fold read_eval mirrors
  the parallel example's ``MLUtils.kFold`` (Run.scala:63).
- Two algorithms under one engine: ``linear`` (exact normal-equation
  solve — the local example's breeze/nak path) and ``sgd``
  (LinearRegressionWithSGD's numIterations/stepSize contract).
- AverageServing combines them (the parallel example's LAverageServing),
  and predictions are plain doubles on the wire.
- MeanSquareError metric (controller.MeanSquareError in both examples).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    AverageMetric,
    AverageServing,
    DataSource,
    Engine,
    EngineFactory,
    Params,
    Preparator,
)
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    features: Tuple[float, ...]


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    label: float
    features: Tuple[float, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str = ""
    #: optional ``label f0 f1 ...`` text file (the reference examples'
    #: lr_data.txt format); when set, the event store is not consulted
    filepath: str = ""
    entity_type: str = "point"
    label_attr: str = "label"
    features_attr: str = "features"
    eval_k: int = 0
    seed: int = 9527


@dataclasses.dataclass
class TrainingData:
    labeled_points: List[LabeledPoint]

    def sanity_check(self) -> None:
        if not self.labeled_points:
            raise ValueError("TrainingData has no labeled points")


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    fold: int


class RegressionDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _read_points(self) -> List[LabeledPoint]:
        if self.params.filepath:
            points = []
            with open(self.params.filepath) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    points.append(LabeledPoint(
                        label=float(parts[0]),
                        features=tuple(float(v) for v in parts[1:]),
                    ))
            return points
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            required=[self.params.label_attr, self.params.features_attr],
        )
        points = []
        for _entity, pm in sorted(props.items()):
            features = pm.get(self.params.features_attr, list)
            points.append(LabeledPoint(
                label=pm.get(self.params.label_attr, float),
                features=tuple(float(v) for v in features),
            ))
        return points

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        return TrainingData(self._read_points())

    def read_eval(self, ctx: RuntimeContext):
        from incubator_predictionio_tpu.e2 import split_data

        if self.params.eval_k <= 0:
            return []
        points = self._read_points()
        return [
            (TrainingData(train), EvalInfo(fold), qa)
            for train, fold, qa in split_data(
                self.params.eval_k, points,
                lambda p: (Query(features=p.features), p.label),
            )
        ]


@dataclasses.dataclass
class PreparedData:
    features: np.ndarray   # [N, K] f32
    labels: np.ndarray     # [N] f32


class RegressionPreparator(Preparator):
    """Points → dense device-ready arrays (IdentityPreparator's role; the
    columnar form is the TPU-native identity)."""

    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        return PreparedData(
            features=np.array([p.features for p in td.labeled_points],
                              np.float32),
            labels=np.array([p.label for p in td.labeled_points],
                            np.float32),
        )


@dataclasses.dataclass(frozen=True)
class LinearAlgorithmParams(Params):
    __camel_case__ = True

    l2: float = 0.0


@dataclasses.dataclass
class RegressionModel:
    weights: Any  # [K+1] device array, intercept last


def _predict(model: RegressionModel, query: Query) -> float:
    """The one prediction path both algorithms share (a regression model
    is just its weight vector, however it was fit)."""
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops.linreg import linreg_predict

    return float(linreg_predict(
        model.weights, jnp.asarray([query.features], jnp.float32))[0])


class LinearAlgorithm(Algorithm):
    """Exact normal-equation ridge solve (the local example's
    nak LinearRegression.regress path → ops.linreg.linreg_fit)."""

    params_class = LinearAlgorithmParams
    query_class_ = Query

    def __init__(self, params: LinearAlgorithmParams = LinearAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> RegressionModel:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.linreg import linreg_fit

        return RegressionModel(weights=linreg_fit(
            jnp.asarray(pd.features), jnp.asarray(pd.labels),
            l2=self.params.l2))

    def predict(self, model: RegressionModel, query: Query) -> float:
        return _predict(model, query)


@dataclasses.dataclass(frozen=True)
class SGDAlgorithmParams(Params):
    __camel_case__ = True

    num_iterations: int = 200
    step_size: float = 0.1
    l2: float = 0.0


class SGDAlgorithm(Algorithm):
    """Gradient-descent fit (LinearRegressionWithSGD's contract —
    Run.scala AlgorithmParams(numIterations, stepSize))."""

    params_class = SGDAlgorithmParams
    query_class_ = Query

    def __init__(self, params: SGDAlgorithmParams = SGDAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> RegressionModel:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.linreg import linreg_fit_sgd

        return RegressionModel(weights=linreg_fit_sgd(
            jnp.asarray(pd.features), jnp.asarray(pd.labels),
            steps=self.params.num_iterations,
            step_size=self.params.step_size,
            l2=self.params.l2))

    def predict(self, model: RegressionModel, query: Query) -> float:
        return _predict(model, query)


class MeanSquareError(AverageMetric):
    """controller.MeanSquareError (both reference regression examples'
    evaluator)."""

    def header(self) -> str:
        return "MSE"

    def calculate_qpa(self, q: Query, p: float, a: float) -> float:
        return (p - a) ** 2

    def compare(self, left: float, right: float) -> int:
        # lower MSE is better
        return (left < right) - (left > right)


class RegressionEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            RegressionDataSource,
            RegressionPreparator,
            {"linear": LinearAlgorithm, "sgd": SGDAlgorithm},
            AverageServing,
        )
