from incubator_predictionio_tpu.models.regression.engine import (
    DataSourceParams,
    LinearAlgorithmParams,
    MeanSquareError,
    Query,
    RegressionEngine,
    SGDAlgorithmParams,
)

__all__ = [
    "DataSourceParams", "LinearAlgorithmParams", "MeanSquareError",
    "Query", "RegressionEngine", "SGDAlgorithmParams",
]
