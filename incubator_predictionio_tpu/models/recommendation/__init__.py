"""Recommendation template (explicit-rating ALS).

Parity: examples/scala-parallel-recommendation/ — all four variants'
capabilities in one engine: custom queries (creation-year filter), custom
preparator hooks, custom serving, and filter-by-category style masks.
"""

from incubator_predictionio_tpu.models.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    Rating,
    RecommendationDataSource,
    RecommendationEngine,
    RecommendationPreparator,
    RecommendationServing,
    TrainingData,
)

__all__ = [
    "ALSAlgorithm", "ALSAlgorithmParams", "ALSModel", "DataSourceParams",
    "ItemScore", "PredictedResult", "Query", "Rating",
    "RecommendationDataSource", "RecommendationEngine",
    "RecommendationPreparator", "RecommendationServing", "TrainingData",
]
